"""Tenant-aware SLO plane: per-tenant accounting, error budgets, burn-rate
sentinels, and the overload signal bus.

ROADMAP item 4 (multi-tenant SLO serving tier) needs admission control,
quotas, and weighted-fair scheduling — none of which can act on signals
that do not exist. This module is the telemetry substrate, built one PR
ahead of the control plane exactly like PR 7's heat accounting preceded
shard migration:

- :class:`SLOSpec` / :class:`SLOTracker` — per-tenant SLO declarations
  (latency-percentile target + availability target, from the ``slo_specs``
  config knob or registered at runtime) and a rolling tracker fed at the
  proxy's reply observation point (the same place PR 7's
  ``LatencyAttributor`` observes). It computes per-tenant compliance,
  remaining error budget, and multi-window burn rates (fast
  ``slo_fast_window_s`` / slow ``slo_slow_window_s``, SRE-workbook style).
- the **burn-rate sentinel** — when BOTH windows exceed their thresholds
  (``slo_burn_fast_x`` / ``slo_burn_slow_x``) for a spec'd tenant, it
  counts ``wukong_slo_burn_alerts_total{tenant,window}`` and force-dumps
  the offending tenant's trace through the flight recorder (reason
  ``SLO_BURN``) under a per-tenant ``slo_dump_cooldown_s`` re-arm — one
  burn episode is one attributable dump, never a storm.
- :class:`OverloadSignals` — the overload signal bus: per-lane queue-delay
  EWMA + depth, pool utilization, shed-rate by cause, and per-tenant
  in-flight + arrival-rate EWMAs, published as pull gauges.
  ``ADMISSION_INPUTS`` literally maps each signal the admission controller
  will consume to the registered metric that backs it (the
  ``PLACEMENT_INPUTS`` contract from obs/heat.py; the ``slo-telemetry``
  analysis gate keeps the map honest).

Tenant label cardinality is bounded: past ``max_tenants`` distinct values
every new tenant lands in the ``"__overflow__"`` bucket, so a hostile or
buggy client can never mint unbounded metric series. Everything is gated
on ``enable_tenant_accounting`` (default ON — the per-reply cost is a few
leaf-lock updates, pinned by BENCH_SERVE.json detail.tenant_accounting);
off degrades every hook to one knob check.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.recorder import get_recorder
from wukong_tpu.utils.logger import log_warn
from wukong_tpu.utils.timer import get_usec

#: the bounded-cardinality catch-all tenant label
OVERFLOW_TENANT = "__overflow__"

#: every signal the (item 4) admission controller will consume, mapped to
#: the registered metric that backs it — scrape-able truth for each number
#: an admission decision reads. The slo-telemetry analysis gate verifies
#: each named metric is actually registered somewhere in code.
ADMISSION_INPUTS = {
    "lane_queue_delay_ewma": "wukong_lane_queue_delay_us",
    "lane_depth": "wukong_pool_lane_depth",
    "pool_utilization": "wukong_pool_utilization",
    "shed_by_cause": "wukong_shed_total",
    "tenant_inflight": "wukong_tenant_inflight",
    "tenant_arrival_rate": "wukong_tenant_arrival_rate",
    "tenant_latency": "wukong_tenant_latency_us",
    "tenant_replies": "wukong_queries_total",
}

EWMA_ALPHA = 0.2  # obs/heat.py's smoothing, shared posture

#: replies a burn window needs before the sentinel may page from it — a
#: single bad reply after a quiet period is noise, not a budget cliff
BURN_MIN_SAMPLES = 16

# every lock here guards deque/dict/float updates only — innermost by
# construction, like heat.shard (observes fire outside every other lock)
declare_leaf("slo.labels")
declare_leaf("slo.tenants")
declare_leaf("slo.signals")

_M_LATENCY = get_registry().histogram(
    "wukong_tenant_latency_us", "Per-tenant reply latency (usec)",
    labels=("tenant",))
_M_SHED = get_registry().counter(
    "wukong_shed_total", "Queries shed/degraded by cause and tenant",
    labels=("cause", "tenant"))
_M_ALERTS = get_registry().counter(
    "wukong_slo_burn_alerts_total",
    "Burn-rate sentinel alerts by tenant and window",
    labels=("tenant", "window"))


# ---------------------------------------------------------------------------
# bounded tenant labels
# ---------------------------------------------------------------------------

_label_lock = make_lock("slo.labels")
_seen_tenants: set = set()  # guarded by: _label_lock


def tenant_label(tenant) -> str:
    """The bounded metric-label form of a tenant id: itself while under
    ``max_tenants`` distinct values, ``__overflow__`` past the cap."""
    t = str(tenant) if tenant else "default"
    cap = max(int(Global.max_tenants), 1)
    with _label_lock:
        if t in _seen_tenants:
            return t
        if len(_seen_tenants) >= cap:
            return OVERFLOW_TENANT
        _seen_tenants.add(t)
        return t


def reset_labels() -> None:
    """Drop the seen-tenant set (tests / scenario runs)."""
    with _label_lock:
        _seen_tenants.clear()


# ---------------------------------------------------------------------------
# SLO specs + tracker
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SLOSpec:
    """One tenant's SLO: a latency-percentile target (``latency_ms`` at
    ``percentile``; 0 disables the latency SLI) and an availability
    target. A reply is *good* when it succeeded AND met the latency
    target; the error budget is ``1 - availability``."""

    tenant: str
    percentile: float = 0.95
    latency_ms: float = 0.0
    availability: float = 0.99

    @property
    def budget(self) -> float:
        return max(1.0 - float(self.availability), 1e-9)


def parse_specs(text: str) -> list[SLOSpec]:
    """Parse the ``slo_specs`` knob: ";"-separated
    ``<tenant>:<percentile>:<latency_ms>:<availability>`` entries.
    Percentile AND availability accept either fraction (0.999) or percent
    (99.9) form — an availability of 99.9 taken literally would leave a
    1e-9 error budget and page on every blip. Out-of-range values are a
    config error, not a silent mis-arm."""
    out = []
    for ent in (text or "").split(";"):
        ent = ent.strip()
        if not ent:
            continue
        parts = ent.split(":")
        if len(parts) != 4:
            raise ValueError(
                f"bad slo_specs entry {ent!r} (want "
                "tenant:percentile:latency_ms:availability)")
        p = float(parts[1])
        a = float(parts[3])
        a = a / 100.0 if a > 1 else a
        if not (0.0 < a < 1.0):
            raise ValueError(
                f"bad availability {parts[3]!r} in {ent!r} "
                "(want a fraction in (0,1) or a percent in (0,100))")
        out.append(SLOSpec(tenant=parts[0].strip(),
                           percentile=p / 100.0 if p > 1 else p,
                           latency_ms=float(parts[2]),
                           availability=a))
    return out


#: burn-window bucket width: the slow window aggregates into this many
#: time buckets (a bounded ring regardless of qps — a raw sample deque
#: would cap the slow window at slo_window recent samples and make the
#: two burn windows see identical data under any real load)
BURN_BUCKETS = 360


class _TenantSLO:
    """One tenant's rolling window (mutated under the tracker lock)."""

    __slots__ = ("samples", "buckets", "total", "good", "errors", "alerts",
                 "last_alert_us")

    def __init__(self, window: int):
        # (t_us, dur_us, good) triples, newest last — feeds the latency
        # percentile and the windowed compliance view
        self.samples: deque = deque(maxlen=window)  # caller holds: slo.tenants (the tracker lock)
        # (bucket_start_us, n, bad) time buckets, newest last — feed the
        # burn-rate windows with bounded memory at ANY qps; pruned past
        # the slow window on every observe
        self.buckets: deque = deque()  # caller holds: slo.tenants (the tracker lock)
        self.total = 0
        self.good = 0
        self.errors = 0
        self.alerts = 0
        self.last_alert_us = 0  # sentinel cooldown cursor

    def charge_bucket(self, now: int, good: bool, slow_window_s: int) -> None:
        """Caller holds the tracker lock. Bucket width tracks the slow
        window so the ring stays ~BURN_BUCKETS entries."""
        width_us = max(slow_window_s * 1_000_000 // BURN_BUCKETS, 1)
        start = now - now % width_us
        if self.buckets and self.buckets[-1][0] == start:
            s, n, bad = self.buckets[-1]
            self.buckets[-1] = (s, n + 1, bad + int(not good))
        else:
            self.buckets.append((start, 1, int(not good)))
        cut = now - slow_window_s * 1_000_000 - width_us
        while self.buckets and self.buckets[0][0] < cut:
            self.buckets.popleft()


class SLOTracker:
    """Per-tenant SLO accounting fed at the reply observation point."""

    def __init__(self, window: int | None = None):
        self._window = window
        self._lock = make_lock("slo.tenants")
        self._tenants: dict[str, _TenantSLO] = {}  # guarded by: _lock
        self._specs: dict[str, SLOSpec] = {}  # guarded by: _lock
        # last parsed slo_specs text (change-detection for runtime reloads)
        self._specs_src = None  # guarded by: _lock

    # ------------------------------------------------------------------
    def register(self, spec: SLOSpec) -> None:
        """Runtime SLO registration (idempotent per tenant; last wins)."""
        with self._lock:
            self._specs[spec.tenant] = spec

    def spec(self, tenant: str) -> SLOSpec | None:
        self._reload_config_specs()
        with self._lock:
            return self._specs.get(tenant)

    def _reload_config_specs(self) -> None:
        """Fold ``slo_specs`` into the registry when the knob changed
        (runtime ``config -s`` reload picks up new declarations)."""
        src = Global.slo_specs
        with self._lock:
            if src == self._specs_src:
                return
            self._specs_src = src
        try:
            specs = parse_specs(src)
        except ValueError as e:
            log_warn(f"slo_specs ignored: {e}")
            return
        for sp in specs:
            self.register(sp)

    # ------------------------------------------------------------------
    def observe(self, tenant: str, dur_us: int, ok: bool,
                trace=None) -> dict | None:
        """Fold one reply into its tenant's window; returns the burn
        verdict when the sentinel trips, else None. ``tenant`` must
        already be the bounded label (``tenant_label``). The tripped
        tenant's trace (when one rode the query) auto-dumps through the
        flight recorder with reason ``SLO_BURN``."""
        self._reload_config_specs()
        now = get_usec()
        win = self._window or max(int(Global.slo_window), 16)
        verdict = None
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                st = self._tenants[tenant] = _TenantSLO(win)
            spec = self._specs.get(tenant)
            good = bool(ok) and (spec is None or spec.latency_ms <= 0
                                 or dur_us <= spec.latency_ms * 1000.0)
            st.samples.append((now, int(dur_us), good))
            st.charge_bucket(now, good,
                             max(int(Global.slo_slow_window_s), 1))
            st.total += 1
            st.good += int(good)
            st.errors += int(not ok)
            if spec is not None:
                verdict = self._maybe_alert(st, spec, now)
        _M_LATENCY.labels(tenant=tenant).observe(dur_us)
        if verdict is not None:
            for w in verdict["windows"]:
                _M_ALERTS.labels(tenant=tenant, window=w).inc()
            # the burn lands in the cluster-event journal FIRST so the
            # flight-recorder dump can reference its triggering event id
            from wukong_tpu.obs.events import emit_event

            eid = emit_event("slo.burn", tenant=tenant,
                             fast_burn=verdict["fast_burn"],
                             slow_burn=verdict["slow_burn"])
            verdict["event_id"] = eid
            if trace is not None:
                get_recorder().dump(trace, "SLO_BURN", event_id=eid)
            log_warn(
                f"SLO burn: tenant {tenant} fast={verdict['fast_burn']:.1f}x"
                f" slow={verdict['slow_burn']:.1f}x (budget "
                f"{spec.budget:.4f}"
                + (f", event {eid}" if eid else "") + "); "
                + ("trace dumped" if trace is not None
                   else "no trace on this reply (enable_tracing for dumps)"))
        return verdict

    def _maybe_alert(self, st: _TenantSLO, spec: SLOSpec,
                     now: int) -> dict | None:
        """Caller holds the tracker lock. The SRE-workbook multi-window
        rule: page only when BOTH the fast and the slow window burn the
        budget faster than their thresholds."""
        if now - st.last_alert_us < max(
                int(Global.slo_dump_cooldown_s), 0) * 1_000_000:
            return None
        fast, n_fast = self._burn(
            st, now, max(int(Global.slo_fast_window_s), 1), spec.budget)
        slow, _n_slow = self._burn(
            st, now, max(int(Global.slo_slow_window_s), 1), spec.budget)
        if n_fast < BURN_MIN_SAMPLES:
            return None  # one bad reply after a quiet spell is not a cliff
        fast_hit = fast >= max(float(Global.slo_burn_fast_x), 1.0)
        slow_hit = slow >= max(float(Global.slo_burn_slow_x), 1.0)
        if not (fast_hit and slow_hit):
            return None
        st.alerts += 1
        st.last_alert_us = now
        return {"tenant": spec.tenant, "fast_burn": round(fast, 2),
                "slow_burn": round(slow, 2),
                "windows": ("fast", "slow")}

    @staticmethod
    def _burn(st: _TenantSLO, now: int, window_s: int,
              budget: float) -> tuple[float, int]:
        """(burn rate, sample count) over one window: the window's bad
        fraction divided by the error budget — 1.0 means the budget is
        being consumed at exactly the rate that exhausts it over the SLO
        period. Reads the time-bucket ring, NOT the bounded sample deque:
        the slow window must see its full history at any qps, or the
        multi-window filter degenerates into two copies of the fast one."""
        cut = now - window_s * 1_000_000
        n = bad = 0
        for (t, cnt, b) in reversed(st.buckets):
            if t < cut:
                break
            n += cnt
            bad += b
        return ((bad / n) / budget if n else 0.0), n

    # ------------------------------------------------------------------
    def compliance(self, tenant: str) -> dict | None:
        """One tenant's SLO view: windowed compliance, observed latency
        percentile, remaining error budget, and both burn rates."""
        self._reload_config_specs()
        now = get_usec()
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return None
            spec = self._specs.get(tenant)
            samples = list(st.samples)
            total, cum_good, errors, alerts = (st.total, st.good,
                                               st.errors, st.alerts)
            fast = slow = None
            if spec is not None:
                fast, _ = self._burn(st, now, max(
                    int(Global.slo_fast_window_s), 1), spec.budget)
                slow, _ = self._burn(st, now, max(
                    int(Global.slo_slow_window_s), 1), spec.budget)
        n = len(samples)
        good = sum(1 for (_t, _d, g) in samples if g)
        lats = sorted(d for (_t, d, _g) in samples)
        p = spec.percentile if spec is not None else 0.95
        p_us = lats[min(int(p * n), n - 1)] if n else 0
        out = {
            "tenant": tenant,
            "samples": n,
            "total": total,
            "errors": errors,
            "compliance": round(good / n, 4) if n else None,
            "cum_compliance": round(cum_good / total, 4) if total else None,
            "latency_p_us": int(p_us),
            "alerts": alerts,
            "spec": None,
        }
        if spec is not None:
            bad_frac = (n - good) / n if n else 0.0
            out["spec"] = {"percentile": spec.percentile,
                           "latency_ms": spec.latency_ms,
                           "availability": spec.availability}
            # fraction of the error budget still unspent over the window
            out["error_budget_remaining"] = round(
                1.0 - bad_frac / spec.budget, 4)
            out["burn"] = {"fast": round(fast, 2), "slow": round(slow, 2)}
            out["latency_met"] = (spec.latency_ms <= 0
                                  or p_us <= spec.latency_ms * 1000.0)
        return out

    def report(self) -> dict:
        """Every tracked tenant's compliance view (spec'd tenants first,
        worst fast-burn first)."""
        with self._lock:
            tenants = list(self._tenants)
        rows = [c for t in tenants if (c := self.compliance(t)) is not None]
        rows.sort(key=lambda r: (-(r.get("burn") or {}).get("fast", -1.0),
                                 r["tenant"]))
        return {"tenants": rows,
                "specs": len([r for r in rows if r["spec"] is not None])}

    def reset(self) -> None:
        """Drop tracker state (tests / scenario runs). Registry counters
        are cumulative and stay."""
        with self._lock:
            self._tenants.clear()
            self._specs.clear()
            self._specs_src = None


# ---------------------------------------------------------------------------
# the overload signal bus
# ---------------------------------------------------------------------------

class _LaneSignal:
    __slots__ = ("ewma_us", "count")

    def __init__(self):
        self.ewma_us = 0.0
        self.count = 0


class _TenantSignal:
    __slots__ = ("inflight", "arrival_ewma_qps", "last_arrival_us")

    def __init__(self):
        self.inflight = 0
        self.arrival_ewma_qps = 0.0
        self.last_arrival_us = 0


class OverloadSignals:
    """The inputs item 4's admission controller will consume, accumulated
    where the events happen (scheduler pops, shed sites, proxy admission)
    and published as pull gauges — see ``ADMISSION_INPUTS``."""

    def __init__(self):
        self._lock = make_lock("slo.signals")
        self._lanes: dict[str, _LaneSignal] = {}  # guarded by: _lock
        self._tenants: dict[str, _TenantSignal] = {}  # guarded by: _lock
        self._sheds: dict[str, int] = {}  # guarded by: _lock
        # (cause, tenant) -> count: who absorbed each shed class — the
        # admission drill's "bulk absorbs the damage" evidence
        self._shed_tenants: dict = {}  # guarded by: _lock

    # -- producers ------------------------------------------------------
    def note_queue_delay(self, lane: str, dur_us: int) -> None:
        """One pool-queue wait, charged by the popping engine."""
        with self._lock:
            s = self._lanes.get(lane)
            if s is None:
                s = self._lanes[lane] = _LaneSignal()
            s.count += 1
            s.ewma_us = (float(dur_us) if s.count == 1
                         else EWMA_ALPHA * dur_us
                         + (1 - EWMA_ALPHA) * s.ewma_us)

    def note_admit(self, tenant: str) -> None:
        """One query admitted for a tenant (proxy entry)."""
        now = get_usec()
        with self._lock:
            s = self._tenants.get(tenant)
            if s is None:
                s = self._tenants[tenant] = _TenantSignal()
            s.inflight += 1
            if s.last_arrival_us:
                gap = max(now - s.last_arrival_us, 1)
                s.arrival_ewma_qps = (EWMA_ALPHA * (1e6 / gap)
                                      + (1 - EWMA_ALPHA)
                                      * s.arrival_ewma_qps)
            s.last_arrival_us = now

    def note_done(self, tenant: str) -> None:
        with self._lock:
            s = self._tenants.get(tenant)
            if s is not None:
                s.inflight = max(s.inflight - 1, 0)

    def note_shed(self, cause: str, tenant: str) -> None:
        with self._lock:
            self._sheds[cause] = self._sheds.get(cause, 0) + 1
            k = (cause, tenant)
            self._shed_tenants[k] = self._shed_tenants.get(k, 0) + 1
        _M_SHED.labels(cause=cause, tenant=tenant).inc()

    # -- pull-gauge feeds ----------------------------------------------
    def lane_delay_series(self) -> dict:
        with self._lock:
            return {(lane,): s.ewma_us for lane, s in self._lanes.items()}

    def inflight_series(self) -> dict:
        with self._lock:
            return {(t,): s.inflight for t, s in self._tenants.items()}

    def arrival_series(self) -> dict:
        with self._lock:
            return {(t,): s.arrival_ewma_qps
                    for t, s in self._tenants.items()}

    # -- the bus view ---------------------------------------------------
    def report(self) -> dict:
        """One structured snapshot of every admission input (the /slo
        body's ``signals`` section). Lane depths and pool utilization are
        read from their live pull sources so the bus never caches them."""
        with self._lock:
            lanes = {lane: {"queue_delay_ewma_us": round(s.ewma_us, 1),
                            "pops": s.count}
                     for lane, s in self._lanes.items()}
            tenants = {t: {"inflight": s.inflight,
                           "arrival_qps": round(s.arrival_ewma_qps, 2)}
                       for t, s in self._tenants.items()}
            sheds = dict(self._sheds)
            shed_tenants = {f"{c}/{t}": n
                            for (c, t), n in self._shed_tenants.items()}
        depths = {}
        util = 0.0
        try:
            from wukong_tpu.runtime.scheduler import (
                _lane_depth_series,
                _pool_utilization,
            )

            depths = {k[0]: int(v) for k, v in
                      _lane_depth_series().items()}
            util = _pool_utilization()
        except Exception:
            pass  # no pool module state yet: the bus stays readable
        for lane, d in depths.items():
            lanes.setdefault(lane, {"queue_delay_ewma_us": 0.0,
                                    "pops": 0})["depth"] = d
        return {"lanes": lanes, "pool_utilization": round(util, 4),
                "shed_by_cause": sheds, "shed_by_tenant": shed_tenants,
                "tenants": tenants,
                "inputs": dict(ADMISSION_INPUTS)}

    def reset(self) -> None:
        with self._lock:
            self._lanes.clear()
            self._tenants.clear()
            self._sheds.clear()
            self._shed_tenants.clear()


# process-wide instances (the proxy, scheduler, batcher, and /slo share them)
_tracker = SLOTracker()
_signals = OverloadSignals()

get_registry().gauge(
    "wukong_lane_queue_delay_us",
    "Per-lane pool queue-delay EWMA (usec)",
    labels=("lane",)).set_function(_signals.lane_delay_series)
get_registry().gauge(
    "wukong_tenant_inflight", "In-flight queries per tenant",
    labels=("tenant",)).set_function(_signals.inflight_series)
get_registry().gauge(
    "wukong_tenant_arrival_rate",
    "Per-tenant arrival-rate EWMA (queries/s)",
    labels=("tenant",)).set_function(_signals.arrival_series)


def get_slo() -> SLOTracker:
    return _tracker


def get_overload() -> OverloadSignals:
    return _signals


def maybe_note_queue_delay(lane: str, dur_us: int) -> None:
    """The scheduler's pop hook: one knob check when accounting is off."""
    if not Global.enable_tenant_accounting:
        return
    _signals.note_queue_delay(lane, dur_us)


def maybe_note_shed(cause: str, tenant) -> None:
    """Shed-site hook (scheduler queue expiry, batcher member
    settlement, reply-side timeout/budget): one knob check when off."""
    if not Global.enable_tenant_accounting:
        return
    _signals.note_shed(cause, tenant_label(tenant))


# ---------------------------------------------------------------------------
# the admission controller's ONLY read path
# ---------------------------------------------------------------------------

def read_admission_input(signal: str):
    """The single accessor through which the admission controller
    (runtime/admission.py) reads the overload bus — the serving cache's
    ``read_cache_input`` pattern. Every signal name must be declared in
    ``ADMISSION_INPUTS`` (KeyError otherwise — the admit gate holds the
    controller's literal ``CONSUMED_INPUTS`` to this registry statically,
    and this raises on anything undeclared dynamically). Returns live
    values, never cached:

    - ``lane_queue_delay_ewma`` -> {lane: ewma_us}
    - ``lane_depth``            -> {lane: queued items}
    - ``pool_utilization``      -> float 0..1
    - ``tenant_inflight``       -> {tenant: in-flight count}
    - ``tenant_arrival_rate``   -> {tenant: arrival EWMA q/s}
    - ``shed_by_cause``         -> {cause: count}
    - ``tenant_latency``        -> {tenant: windowed p-latency us}
    - ``tenant_replies``        -> {tenant: windowed reply count}
    """
    if signal not in ADMISSION_INPUTS:
        raise KeyError(f"undeclared admission input {signal!r} "
                       f"(declared: {sorted(ADMISSION_INPUTS)})")
    if signal == "lane_queue_delay_ewma":
        return {lane: v for (lane,), v
                in _signals.lane_delay_series().items()}
    if signal == "tenant_inflight":
        return {t: v for (t,), v in _signals.inflight_series().items()}
    if signal == "tenant_arrival_rate":
        return {t: v for (t,), v in _signals.arrival_series().items()}
    if signal == "shed_by_cause":
        with _signals._lock:
            return dict(_signals._sheds)
    if signal in ("lane_depth", "pool_utilization"):
        try:
            from wukong_tpu.runtime.scheduler import (
                _lane_depth_series,
                _pool_utilization,
            )
        except Exception:
            return {} if signal == "lane_depth" else 0.0
        if signal == "lane_depth":
            return {k[0]: int(v) for k, v in _lane_depth_series().items()}
        return float(_pool_utilization())
    # tenant_latency / tenant_replies: the tracker's windowed view
    rep = _tracker.report()
    if signal == "tenant_latency":
        return {r["tenant"]: r["latency_p_us"] for r in rep["tenants"]}
    return {r["tenant"]: r["samples"] for r in rep["tenants"]}


# ---------------------------------------------------------------------------
# the /slo report (endpoint + console verb + Monitor line)
# ---------------------------------------------------------------------------

def render_slo(k: int | None = None) -> tuple[str, dict]:
    """(plain-text table, JSON dict) for the /slo endpoint and the
    ``slo`` console verb: per-tenant compliance / error budget / burn
    rates on top, the overload signal bus below."""
    rep = _tracker.report()
    sig = _signals.report()
    kk = k if k is not None else max(int(Global.top_k), 1)
    js = {"tenants": rep["tenants"], "signals": sig}

    lines = ["wukong-slo  (per-tenant SLO + overload signals)", ""]
    lines.append(f"{'tenant':<14} {'samples':>8} {'compl':>7} "
                 f"{'budget':>7} {'burn_f':>7} {'burn_s':>7} "
                 f"{'p_us':>9} {'alerts':>6} {'target':>16}")
    for r in rep["tenants"][:kk]:
        spec = r["spec"]
        tgt = ("-" if spec is None else
               f"p{int(spec['percentile'] * 100)}"
               + (f"<{spec['latency_ms']:g}ms" if spec["latency_ms"] > 0
                  else "")
               + f"@{spec['availability']:g}")
        burn = r.get("burn") or {}
        budget = r.get("error_budget_remaining")
        if budget is not None:
            budget = max(budget, -9.0)  # display floor; JSON stays exact
        lines.append(
            f"{r['tenant']:<14.14} {r['samples']:>8,} "
            f"{'-' if r['compliance'] is None else format(r['compliance'], '.1%'):>7} "
            f"{'-' if budget is None else format(budget, '.0%'):>7} "
            f"{'-' if 'fast' not in burn else format(burn['fast'], '.1f'):>7} "
            f"{'-' if 'slow' not in burn else format(burn['slow'], '.1f'):>7} "
            f"{r['latency_p_us']:>9,} {r['alerts']:>6} {tgt:>16}")
    if not rep["tenants"]:
        lines.append("  (no tenant replies observed — "
                     "enable_tenant_accounting on?)")
    lines.append("")
    lines.append(f"SIGNALS  pool_utilization {sig['pool_utilization']:.0%}")
    for lane, d in sorted(sig["lanes"].items()):
        lines.append(f"  lane[{lane}]: delay_ewma "
                     f"{d['queue_delay_ewma_us']:,.0f}us"
                     + (f", depth {d['depth']}" if "depth" in d else "")
                     + f" ({d['pops']:,} pops)")
    for cause, n in sorted(sig["shed_by_cause"].items()):
        lines.append(f"  shed[{cause}]: {n:,}")
    for t, d in sorted(sig["tenants"].items()):
        lines.append(f"  tenant[{t}]: inflight {d['inflight']}, "
                     f"arrival {d['arrival_qps']:,.1f} q/s")
    return "\n".join(lines) + "\n", js
