"""Per-query trace context: trace id + span stack, propagated end to end.

SURVEY §5 notes the reference has "no pervasive tracing framework" — its
only timing is the proxy-side Monitor's latency records. This module is the
structured replacement: a :class:`QueryTrace` is created at proxy receipt
(sampled via the ``enable_tracing`` / ``trace_sample_every`` knobs), carried
on the query object (``q.trace``, next to ``q.deadline``), and *activated*
as a thread-ambient context while an engine executes it, so deep layers
that never see the query (shard fetches, retry/backoff, circuit breakers,
fault injection) can attach spans and events to the right trace without
plumbing it through every signature.

Granularity contract: spans are opened per STEP (BGP step, chain dispatch,
shard fetch, stream epoch phase), never per row — with tracing off, every
hook is a single ``getattr``/``None`` check, so the hot path stays flat.

The host-side per-label aggregate recorder (:class:`StepTrace`) and the
scoped JAX device profiler (``device_trace`` in obs/export.py) were
absorbed from the retired ``runtime/tracing.py``.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
from collections import defaultdict

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.utils.timer import get_usec

# span-stack locks only ever guard list/dict appends — innermost by
# construction (the resilience layer already fires its trace hooks outside
# the breaker lock; lockdep now proves that stays true)
declare_leaf("trace.spans")

_tls = threading.local()
_trace_seq = itertools.count(1)
# one sampling sequence PER KIND: a burst of stream epochs must not skew
# the 1-in-N sampling of interactive queries (and vice versa)
_sample_seqs: dict[str, itertools.count] = {}


class Span:
    """One timed operation inside a trace. ``end()`` is idempotent and may
    run on a different thread than ``start`` (queue spans end in the engine
    thread that popped the query)."""

    __slots__ = ("name", "t0_us", "t1_us", "attrs", "events", "depth", "tid")

    def __init__(self, name: str, attrs: dict, depth: int, tid: int):
        self.name = name
        self.t0_us = get_usec()
        self.t1_us: int | None = None
        self.attrs = attrs
        self.events: list[tuple[int, str, dict]] = []
        self.depth = depth
        self.tid = tid

    def event(self, name: str, **attrs) -> None:
        self.events.append((get_usec(), name, attrs))

    def end(self, **attrs) -> None:
        if attrs:
            self.attrs.update(attrs)
        if self.t1_us is None:
            self.t1_us = get_usec()

    @property
    def dur_us(self) -> int:
        return (self.t1_us if self.t1_us is not None else get_usec()) - self.t0_us

    def to_dict(self) -> dict:
        return {"name": self.name, "t0_us": self.t0_us,
                "dur_us": self.dur_us, "depth": self.depth, "tid": self.tid,
                "attrs": dict(self.attrs),
                "events": [{"t_us": t, "name": n, "attrs": a}
                           for t, n, a in self.events]}


class QueryTrace:
    """Trace id + per-thread span stacks for one query (or stream epoch).

    Spans append under a lock: the proxy thread, the engine-pool thread
    executing the query, and (in principle) fetch helpers may all write.
    """

    def __init__(self, kind: str = "query", qid: int | None = None,
                 text: str | None = None, tenant: str = "default"):
        n = next(_trace_seq)
        self.trace_id = f"{kind[0]}{n:06d}"
        self.kind = kind
        self.qid = n if qid is None else qid
        self.text = text
        # tenant identity (obs/slo.py): the proxy stamps the bounded
        # label at admission so every recorded/dumped trace is
        # attributable to a tenant without replaying it
        self.tenant = tenant
        self.t0_us = get_usec()
        self.t1_us: int | None = None
        self.status = "RUNNING"
        self.spans: list[Span] = []  # guarded by: _lock
        self._lock = make_lock("trace.spans")
        self._stacks: dict[int, list[Span]] = defaultdict(list)  # guarded by: _lock

    # ------------------------------------------------------------------
    def start_span(self, name: str, **attrs) -> Span:
        tid = threading.get_ident()
        with self._lock:
            stack = self._stacks[tid]
            sp = Span(name, attrs, depth=len(stack), tid=tid)
            stack.append(sp)
            self.spans.append(sp)
        return sp

    def end_span(self, sp: Span, **attrs) -> None:
        sp.end(**attrs)
        with self._lock:
            # pop from whichever thread-stack holds it (cross-thread ends)
            for stack in self._stacks.values():
                if sp in stack:
                    stack.remove(sp)
                    break

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        sp = self.start_span(name, **attrs)
        try:
            yield sp
        finally:
            self.end_span(sp)

    def event(self, name: str, **attrs) -> None:
        """Attach to the current thread's innermost open span, falling back
        to a zero-length synthetic span at trace level."""
        tid = threading.get_ident()
        with self._lock:
            stack = self._stacks.get(tid)
            if stack:
                stack[-1].events.append((get_usec(), name, attrs))
                return
            sp = Span(name, attrs, depth=0, tid=tid)
            sp.t1_us = sp.t0_us
            self.spans.append(sp)

    def finish(self, status: str = "SUCCESS") -> None:
        if self.t1_us is None:
            self.t1_us = get_usec()
            self.status = status

    # ------------------------------------------------------------------
    @property
    def dur_us(self) -> int:
        return (self.t1_us if self.t1_us is not None else get_usec()) - self.t0_us

    def step_summary(self) -> dict[str, dict]:
        """Aggregate span timings by name: the per-step time-breakdown
        section bench artifacts carry ({name: {count, total_us, max_us}})."""
        out: dict[str, dict] = {}
        for sp in self.spans:  # unguarded: reporting surface — runs on finished traces (recorder/bench), after every writer ended
            d = out.setdefault(sp.name, {"count": 0, "total_us": 0, "max_us": 0})
            d["count"] += 1
            d["total_us"] += sp.dur_us
            d["max_us"] = max(d["max_us"], sp.dur_us)
        return out

    def event_names(self) -> list[str]:
        return [n for sp in self.spans for (_t, n, _a) in sp.events]  # unguarded: reporting surface on finished traces

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "kind": self.kind, "qid": self.qid,
                "tenant": self.tenant,
                "status": self.status, "t0_us": self.t0_us,
                "dur_us": self.dur_us,
                **({"text": self.text} if self.text else {}),
                "spans": [sp.to_dict() for sp in self.spans]}  # unguarded: reporting surface on finished traces


# ---------------------------------------------------------------------------
# ambient (thread-local) current trace
# ---------------------------------------------------------------------------

def current() -> QueryTrace | None:
    """The trace active on this thread, or None (the deep-layer hook)."""
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def activate(trace: QueryTrace | None):
    """Make ``trace`` this thread's ambient trace for the block. Engines
    activate ``q.trace`` around execution so shard fetches / retries /
    breakers / fault sites attach to it without seeing the query."""
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    try:
        yield trace
    finally:
        _tls.trace = prev


def trace_event(name: str, **attrs) -> None:
    """Record an event on the ambient trace; no-op (one getattr) without one."""
    tr = getattr(_tls, "trace", None)
    if tr is not None:
        tr.event(name, **attrs)


def maybe_start_trace(kind: str = "query", qid: int | None = None,
                      text: str | None = None) -> QueryTrace | None:
    """A new QueryTrace per the ``enable_tracing`` + ``trace_sample_every``
    knobs, or None (the zero-overhead default)."""
    if not Global.enable_tracing:
        return None
    n = max(int(Global.trace_sample_every), 1)
    if n > 1:
        seq = _sample_seqs.get(kind)
        if seq is None:
            seq = _sample_seqs.setdefault(kind, itertools.count())
        if next(seq) % n:
            return None
    return QueryTrace(kind=kind, qid=qid, text=text)


# ---------------------------------------------------------------------------
# engine instrumentation helpers (one definition; cpu/tpu/dist share them)
# ---------------------------------------------------------------------------

def traced_execute(q, span_name: str, body, end_attrs=None):
    """Engine execute() wrapper: activate ``q.trace`` thread-ambiently and
    span the whole execution. The untraced path is ONE getattr then
    ``body()`` — the obs hot-path contract. ``end_attrs()`` (optional)
    supplies the span's closing attributes after body ran."""
    tr = getattr(q, "trace", None)
    if tr is None:
        return body()
    with activate(tr):
        sp = tr.start_span(span_name)
        try:
            return body()
        finally:
            tr.end_span(sp, **(end_attrs() if end_attrs is not None else {}))


def traced_step(tr, q, span_name: str, fn) -> None:
    """One BGP-step span with rows in/out (step granularity, zero per-row
    work); ``tr is None`` runs ``fn()`` bare."""
    if tr is None:
        fn()
        return
    rows_in = q.result.nrows
    sp = tr.start_span(span_name, step=q.pattern_step,
                       pattern=repr(q.get_pattern()))
    try:
        fn()
    finally:
        tr.end_span(sp, rows_in=rows_in, rows_out=q.result.nrows)


# ---------------------------------------------------------------------------
# StepTrace — absorbed from runtime/tracing.py (host-side per-label
# aggregates; engines can feed it when a full QueryTrace is overkill)
# ---------------------------------------------------------------------------

class StepTrace:
    """Per-query step timings: step label -> [usec]. Feed from engine loops."""

    def __init__(self):
        self.records: dict[str, list[int]] = defaultdict(list)
        self._open: dict[str, int] = {}

    @contextlib.contextmanager
    def span(self, label: str):
        t0 = get_usec()
        try:
            yield
        finally:
            self.records[label].append(get_usec() - t0)

    def summary(self) -> dict[str, dict]:
        out = {}
        for label, xs in self.records.items():
            out[label] = {"count": len(xs), "total_us": sum(xs),
                          "max_us": max(xs)}
        return out
