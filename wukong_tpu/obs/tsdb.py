"""Bounded in-memory metrics time-series ring: trend windows for placement.

ROADMAP item 3's migration loop needs *trends*, and until this module the
MetricsRegistry only answered "what is the value now" — a placement
decision reading a point-in-time snapshot cannot tell a transient spike
from a sustained hot spot. :class:`MetricsTSDB` samples
``MetricsRegistry.snapshot()`` on an interval (``tsdb_interval_s``) into a
bounded ring (``tsdb_retention_s`` deep), converting cumulative counters
into windowed *rates* and histogram buckets into windowed *percentiles*:

- :meth:`MetricsTSDB.rate` / :meth:`rate_by_label` — counter delta over a
  trend window divided by the window's wall time (per second), optionally
  grouped by one label (the per-shard load rates the PlacementAdvisor
  consumes — obs/placement.py).
- :meth:`MetricsTSDB.quantile` — histogram percentile over the *window's*
  bucket deltas (not the process lifetime), linearly interpolated inside
  the winning bucket like promql ``histogram_quantile``.
- :meth:`MetricsTSDB.series` / :meth:`latest` — raw (t, value) range reads
  for gauges and counters.

Surfaced as ``GET /history`` + ``/history.json`` on obs/httpd.py and the
``history`` console verb (:func:`render_history`). The sampler is a daemon
thread (:func:`maybe_start_tsdb`, idempotent per process) gated on the
``enable_tsdb`` knob; one snapshot every ``tsdb_interval_s`` seconds is
far off any hot path (the overhead guard rides BENCH_SERVE.json
``detail.observatory``). Tests drive :meth:`sample_once` directly for
deterministic trend windows.
"""

from __future__ import annotations

import math
import threading
from collections import deque

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.utils.logger import log_warn
from wukong_tpu.utils.timer import get_usec

# the ring lock only guards deque append/iterate and dict reads of frozen
# samples — innermost by construction, like heat.shard
declare_leaf("tsdb.ring")

_M_SAMPLES = get_registry().counter(
    "wukong_tsdb_samples_total", "Registry snapshots folded into the "
    "time-series ring")


def _series_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class _Sample:
    """One flattened registry snapshot (immutable once built)."""

    __slots__ = ("t_us", "scalars", "hists")

    def __init__(self, t_us: int, snap: dict):
        self.t_us = t_us
        # (name, labelkey) -> float for counters AND gauges (rates only
        # make sense for counters; the query side decides)
        self.scalars: dict = {}  # lock-free: written only during construction; immutable once ringed
        # (name, labelkey) -> (count, sum, ((le, n), ...)) raw buckets
        self.hists: dict = {}  # lock-free: written only during construction; immutable once ringed
        for name, fam in snap.items():
            kind = fam.get("kind")
            for s in fam.get("series", []):
                key = (name, _series_key(s.get("labels", {})))
                if kind == "histogram":
                    buckets = []
                    for le, n in (s.get("buckets") or {}).items():
                        b = math.inf if le == "+Inf" else float(le)
                        buckets.append((b, int(n)))
                    buckets.sort(key=lambda x: x[0])
                    self.hists[key] = (int(s.get("count", 0)),
                                      float(s.get("sum", 0.0)),
                                      tuple(buckets))
                else:
                    self.scalars[key] = float(s.get("value", 0.0))


class MetricsTSDB:
    """Process-wide bounded time-series ring over the metrics registry."""

    def __init__(self, interval_s: float | None = None,
                 retention_s: float | None = None):
        self._interval_override = interval_s
        self._retention_override = retention_s
        self._lock = make_lock("tsdb.ring")
        self._samples: deque[_Sample] = deque()  # guarded by: _lock

    # ------------------------------------------------------------------
    @property
    def interval_s(self) -> float:
        v = (self._interval_override if self._interval_override is not None
             else Global.tsdb_interval_s)
        return max(float(v), 0.1)

    @property
    def retention_s(self) -> float:
        v = (self._retention_override
             if self._retention_override is not None
             else Global.tsdb_retention_s)
        return max(float(v), self.interval_s)

    # ------------------------------------------------------------------
    def sample_once(self, now_us: int | None = None) -> _Sample:
        """Fold one registry snapshot into the ring and evict samples
        older than the retention window. ``now_us`` is injectable so
        tests build deterministic trend windows."""
        snap = get_registry().snapshot()
        sample = _Sample(get_usec() if now_us is None else int(now_us),
                         snap)
        cut = sample.t_us - int(self.retention_s * 1e6)
        # memory is bounded two ways: by age (retention) AND by count —
        # a caller sampling faster than the interval (tests, bursts)
        # must not grow the ring past its nominal depth
        cap = max(int(self.retention_s / self.interval_s), 1) + 8
        with self._lock:
            self._samples.append(sample)
            while self._samples and self._samples[0].t_us < cut:
                self._samples.popleft()
            while len(self._samples) > cap:
                self._samples.popleft()
        _M_SAMPLES.inc()
        return sample

    def _window(self, window_s: float | None) -> list[_Sample]:
        """Samples inside the trend window (retention-wide when None),
        oldest first — a snapshot list, safe to read without the lock."""
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return []
        w = self.retention_s if window_s is None else max(float(window_s),
                                                          0.001)
        cut = samples[-1].t_us - int(w * 1e6)
        return [s for s in samples if s.t_us >= cut]

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def span_s(self) -> float:
        """Wall time covered by the ring (0 with <2 samples)."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            return (self._samples[-1].t_us - self._samples[0].t_us) / 1e6

    def reset(self) -> None:
        """Drop the ring (tests / scenario runs start a clean window)."""
        with self._lock:
            self._samples.clear()

    # ------------------------------------------------------------------
    # range / rate / percentile queries
    # ------------------------------------------------------------------
    @staticmethod
    def _match(key: tuple, name: str, labels: dict) -> bool:
        kname, kl = key
        if kname != name:
            return False
        kd = dict(kl)
        return all(kd.get(k) == str(v) for k, v in labels.items())

    def series(self, name: str, window_s: float | None = None,
               **labels) -> list[tuple[float, float]]:
        """[(t_seconds, summed value)] per sample over the window, for
        counters and gauges (series matching the label subset are
        summed)."""
        out = []
        for s in self._window(window_s):
            vals = [v for k, v in s.scalars.items()
                    if self._match(k, name, labels)]
            if vals:
                out.append((s.t_us / 1e6, sum(vals)))
        return out

    def latest(self, name: str, **labels) -> float | None:
        """Newest sampled value of a scalar series (summed over matches),
        or None when the ring has never seen it."""
        with self._lock:
            samples = list(self._samples)
        for s in reversed(samples):
            vals = [v for k, v in s.scalars.items()
                    if self._match(k, name, labels)]
            if vals:
                return sum(vals)
        return None

    def rate(self, name: str, window_s: float | None = None,
             **labels) -> float | None:
        """Windowed rate (per second) of a cumulative counter: the delta
        between the window's first and last sample over their wall-time
        gap. None with <2 samples; clamped at 0 (a registry ``reset()``
        mid-window must not read as a negative rate)."""
        pts = self.series(name, window_s, **labels)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return max(v1 - v0, 0.0) / (t1 - t0)

    def rate_by_label(self, name: str, label: str,
                      window_s: float | None = None) -> dict[str, float]:
        """{label value: windowed rate} for one counter family, summing
        over every OTHER label (e.g. per-shard fetch rates summed over
        the ``kind`` label) — the PlacementAdvisor's trend read."""
        win = self._window(window_s)
        if len(win) < 2:
            return {}
        first, last = win[0], win[-1]
        dt = (last.t_us - first.t_us) / 1e6
        if dt <= 0:
            return {}
        acc: dict[str, float] = {}
        for key, v1 in last.scalars.items():
            kname, kl = key
            if kname != name:
                continue
            lv = dict(kl).get(label)
            if lv is None:
                continue
            delta = max(v1 - first.scalars.get(key, 0.0), 0.0)
            acc[lv] = acc.get(lv, 0.0) + delta
        return {k: v / dt for k, v in acc.items()}

    def quantile(self, name: str, q: float,
                 window_s: float | None = None, **labels) -> float | None:
        """Histogram quantile over the WINDOW's observations: bucket-count
        deltas between the window's first and last sample, linearly
        interpolated inside the winning bucket (promql
        ``histogram_quantile`` semantics; the +Inf bucket answers with the
        highest finite bound). None when the window saw no observation."""
        return self._quantile(name, q, window_s, labels)

    def _quantile(self, name: str, q: float, window_s: float | None,
                  labels: dict) -> float | None:
        # labels as a plain dict: a series whose label KEY is literally
        # "name"/"q" (lockdep's per-lock histograms) must not collide
        # with the public keyword signature
        win = self._window(window_s)
        if len(win) < 2:
            return None
        deltas = self._bucket_deltas(win[0], win[-1], name, labels)
        return self._quantile_of(deltas, q)

    @classmethod
    def _bucket_deltas(cls, first, last, name: str,
                       labels: dict) -> dict[float, float]:
        """Windowed per-bucket observation counts for the matching
        series: bucket-count deltas between the window's first and last
        sample, summed across matching label sets."""
        deltas: dict[float, float] = {}
        for key, (_c, _s, buckets) in last.hists.items():
            if not cls._match(key, name, labels):
                continue
            prev = dict(first.hists.get(key, (0, 0.0, ()))[2])
            for le, n in buckets:
                deltas[le] = deltas.get(le, 0.0) + max(n - prev.get(le, 0),
                                                       0)
        return deltas

    @staticmethod
    def _quantile_of(deltas: dict[float, float], q: float) -> float | None:
        total = sum(deltas.values())
        if total <= 0:
            return None
        rank = max(min(float(q), 1.0), 0.0) * total
        cum = 0.0
        lo = 0.0
        finite = [le for le in sorted(deltas) if le != math.inf]
        for le in sorted(deltas):
            cum += deltas[le]
            if cum >= rank:
                if le == math.inf:
                    return finite[-1] if finite else None
                frac = (rank - (cum - deltas[le])) / max(deltas[le], 1e-12)
                return lo + (le - lo) * frac
            if le != math.inf:
                lo = le
        return finite[-1] if finite else None

    # ------------------------------------------------------------------
    def report(self, k: int | None = None,
               window_s: float | None = None) -> dict:
        """The /history body: ring stats + the top-k counters by windowed
        rate, top-k histograms by windowed observation count (with
        p50/p99), and the latest gauge values."""
        kk = k if k is not None else max(int(Global.top_k), 1)
        win = self._window(window_s)
        out = {"samples": len(self), "interval_s": self.interval_s,
               "retention_s": self.retention_s,
               "window_s": ((win[-1].t_us - win[0].t_us) / 1e6
                            if len(win) >= 2 else 0.0),
               "counters": [], "histograms": [], "gauges": []}
        if len(win) < 2:
            return out
        first, last = win[0], win[-1]
        dt = max((last.t_us - first.t_us) / 1e6, 1e-9)
        kinds = self._family_kinds()
        counters = []
        gauges = []
        for key, v1 in last.scalars.items():
            name, kl = key
            kind = kinds.get(name)
            if kind == "counter":
                d = max(v1 - first.scalars.get(key, 0.0), 0.0)
                if d > 0:
                    counters.append({"name": name, "labels": dict(kl),
                                     "delta": round(d, 3),
                                     "rate_per_s": round(d / dt, 3)})
            elif kind == "gauge":
                gauges.append({"name": name, "labels": dict(kl),
                               "value": round(v1, 3)})
        counters.sort(key=lambda r: -r["rate_per_s"])
        gauges.sort(key=lambda r: -abs(r["value"]))
        hists = []
        for key, (c1, s1, _b) in last.hists.items():
            name, kl = key
            c0, s0, _b0 = first.hists.get(key, (0, 0.0, ()))
            dc = max(c1 - c0, 0)
            if dc <= 0:
                continue
            hists.append({
                "name": name, "labels": dict(kl), "count": dc,
                "mean": round(max(s1 - s0, 0.0) / dc, 1),
            })
        hists.sort(key=lambda r: -r["count"])
        hists = hists[:kk]
        # quantiles only for the survivors, computed from the first/last
        # samples already in hand: one delta pass per row, no re-snapshot
        # of the ring per percentile — scrape cost must not scale with
        # label cardinality or window depth
        for r in hists:
            deltas = self._bucket_deltas(first, last, r["name"],
                                         r["labels"])
            r["p50"] = self._quantile_of(deltas, 0.5)
            r["p99"] = self._quantile_of(deltas, 0.99)
        out["counters"] = counters[:kk]
        out["histograms"] = hists
        out["gauges"] = gauges[:kk]
        return out

    @staticmethod
    def _family_kinds() -> dict[str, str]:
        snap_families = get_registry()._families()
        return {m.name: m.kind for m in snap_families}


class TSDBSampler:
    """Daemon thread sampling the registry into the ring on the interval."""

    def __init__(self, tsdb: "MetricsTSDB"):
        self.tsdb = tsdb
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None  # lock-free: start/stop are operator-thread only

    def start(self) -> "TSDBSampler":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tsdb-sampler")
        self._thread.start()
        return self

    def _run(self) -> None:
        while True:
            # read the RAW knob: <=0 means "sampler off" at runtime —
            # interval_s clamps to 0.1s for ring math, which would turn
            # the off state into a 10 Hz full-registry sampling loop here
            raw = (self.tsdb._interval_override
                   if self.tsdb._interval_override is not None
                   else Global.tsdb_interval_s)
            enabled = Global.enable_tsdb and float(raw) > 0
            if self._stop.wait(self.tsdb.interval_s if enabled else 1.0):
                return
            if not enabled:
                continue  # knob flipped off at runtime: idle, keep the ring
            try:
                self.tsdb.sample_once()
            except Exception as e:  # the sampler must never die silently
                log_warn(f"tsdb sample failed: {e!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


# process-wide ring (the sampler, /history, and the PlacementAdvisor share it)
_tsdb = MetricsTSDB()
_sampler_lock = threading.Lock()  # plain: guards one-shot sampler start only
_sampler: "TSDBSampler | None" = None  # guarded by: _sampler_lock


def get_tsdb() -> MetricsTSDB:
    return _tsdb


def maybe_start_tsdb() -> "TSDBSampler | None":
    """Start the background sampler if ``enable_tsdb`` asks for one;
    idempotent per process (a second Proxy reuses the running sampler)."""
    global _sampler
    if not Global.enable_tsdb or Global.tsdb_interval_s <= 0:
        return None
    with _sampler_lock:
        if _sampler is None:
            _sampler = TSDBSampler(_tsdb).start()
        return _sampler


def stop_tsdb() -> None:
    """Stop the background sampler (tests / console teardown)."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


# ---------------------------------------------------------------------------
# the /history report (endpoint + console verb)
# ---------------------------------------------------------------------------

def render_history(k: int | None = None,
                   window_s: float | None = None) -> tuple[str, dict]:
    """(plain-text table, JSON dict) for the /history endpoint and the
    ``history`` console verb: windowed counter rates, histogram
    percentiles, and gauge values from the time-series ring."""
    rep = _tsdb.report(k, window_s)
    lines = [
        "wukong-history  (metrics trend window)",
        "",
        f"samples {rep['samples']}  interval {rep['interval_s']:g}s  "
        f"retention {rep['retention_s']:g}s  window "
        f"{rep['window_s']:.1f}s",
    ]
    if rep["samples"] < 2:
        lines.append("  (need >=2 samples — enable_tsdb on and the "
                     "sampler running, or call sample_once())")
        return "\n".join(lines) + "\n", rep
    lines.append("")
    lines.append("COUNTER RATES over window")
    lines.append(f"{'metric':<44} {'labels':<28} {'rate/s':>10} "
                 f"{'delta':>10}")
    for r in rep["counters"]:
        lbl = ",".join(f"{k2}={v}" for k2, v in sorted(r["labels"].items()))
        lines.append(f"{r['name']:<44.44} {lbl:<28.28} "
                     f"{r['rate_per_s']:>10,.2f} {r['delta']:>10,.0f}")
    if not rep["counters"]:
        lines.append("  (no counter moved inside the window)")
    lines.append("")
    lines.append("HISTOGRAMS over window")
    lines.append(f"{'metric':<44} {'labels':<28} {'count':>8} {'mean':>10} "
                 f"{'p50':>10} {'p99':>10}")
    for r in rep["histograms"]:
        lbl = ",".join(f"{k2}={v}" for k2, v in sorted(r["labels"].items()))
        p50 = "-" if r["p50"] is None else f"{r['p50']:,.0f}"
        p99 = "-" if r["p99"] is None else f"{r['p99']:,.0f}"
        lines.append(f"{r['name']:<44.44} {lbl:<28.28} {r['count']:>8,} "
                     f"{r['mean']:>10,.1f} {p50:>10} {p99:>10}")
    if not rep["histograms"]:
        lines.append("  (no histogram observed inside the window)")
    lines.append("")
    lines.append("GAUGES (latest sample)")
    for r in rep["gauges"]:
        lbl = ",".join(f"{k2}={v}" for k2, v in sorted(r["labels"].items()))
        lines.append(f"  {r['name']}{{{lbl}}} {r['value']:,.2f}")
    if not rep["gauges"]:
        lines.append("  (no gauges sampled)")
    return "\n".join(lines) + "\n", rep
