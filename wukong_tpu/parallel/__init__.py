from wukong_tpu.parallel.mesh import make_mesh  # noqa: F401
from wukong_tpu.parallel.dist_engine import DistEngine  # noqa: F401
