"""Distributed query execution: shard_map chains with all-to-all row exchange.

This is the TPU-native replacement for the reference's distributed machinery:

- graph partitioned by hash(vid) % D over mesh devices (base_loader.hpp:172-173)
- one-sided RDMA reads + fork-join sub-queries (sparql.hpp:746-814,
  rmap.hpp) become a capacity-padded `lax.all_to_all` of binding-table rows
  keyed by the anchor column's owner, executed INSIDE one compiled program
- index-origin starts run on every shard over its local index slice
  (= dispatch to all servers x mt_factor, sparql.hpp:1064-1088)
- mid-chain type-membership expansion all-gathers rows and expands against
  each shard's local type index (= the reference's dispatch-to-all for
  `p == TYPE_ID && d == IN`, sparql.hpp:1139-1152)

The whole pattern chain for a query compiles to ONE jitted shard_map program
(cached per plan signature x capacity classes): zero mid-query host syncs, one
device_get at the end for row counts + overflow totals (+ gathered tables when
not blind). Capacity overflow anywhere (expansion or exchange) triggers a
host-side retry of the whole chain at exact capacities.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from wukong_tpu.config import Global
from wukong_tpu.engine import tpu_kernels as K
from wukong_tpu.parallel.sharded_store import ShardedDeviceStore
from wukong_tpu.sparql.ir import NO_RESULT, PGType, SPARQLQuery
from wukong_tpu.types import IN, OUT, PREDICATE_ID, TYPE_ID, AttrType
from wukong_tpu.utils.errors import ErrorCode, WukongError, assert_ec


@dataclass
class _Step:
    kind: str  # init_index | init_const | expand | expand_type_all | member
    pid: int = 0
    dir: int = 0
    col: int = -1  # anchor column
    vals_col: int = -1  # member: end column (-1 => const)
    const: int = 0  # member const / init const vid
    cap: int = 0  # output capacity class (expansion / exchange target)
    exch_cap: int = 0  # per-destination exchange capacity (0 = no exchange)
    new_col: bool = False


@dataclass
class _Plan:
    steps: list = field(default_factory=list)
    width: int = 0
    v2c: dict = field(default_factory=dict)

    def signature(self):
        return tuple(
            (s.kind, s.pid, s.dir, s.col, s.vals_col, s.const, s.cap, s.exch_cap)
            for s in self.steps)


class DistEngine:
    """Executes device-supported SPARQL plans across a device mesh."""

    def __init__(self, stores: list, str_server=None, mesh=None, axis: str = "x"):
        from wukong_tpu.parallel.mesh import make_mesh

        self.mesh = mesh or make_mesh(len(stores))
        self.axis = axis
        self.D = len(stores)
        self.sstore = ShardedDeviceStore(stores, self.mesh, axis)
        self.str_server = str_server
        self.cap_min = Global.table_capacity_min
        self.cap_max = Global.table_capacity_max
        self._fn_cache: dict = {}

    # ------------------------------------------------------------------
    def execute(self, q: SPARQLQuery, from_proxy: bool = True) -> SPARQLQuery:
        if self.sstore.check_version():
            # compiled chains bake per-segment max_probe/depth — stale after
            # dynamic inserts (dynamic_gstore.hpp lease invalidation analogue)
            self._fn_cache.clear()
        try:
            self._execute_inner(q)
            # FILTER/FINAL run host-side on the gathered table (they touch
            # strings and projections, not the graph). Top-level UNION runs
            # branch-per-branch in _execute_inner; OPTIONAL stays unsupported
            # in distributed v1
            if q.pattern_group.filters or from_proxy:
                assert_ec(self.str_server is not None or not
                          (q.pattern_group.filters or q.orders),
                          ErrorCode.UNKNOWN_FILTER,
                          "FILTER/ORDER BY needs a string server")
            if q.pattern_group.filters:
                self._host()._execute_filters(q)
            if from_proxy:
                self._host()._final_process(q)
        except WukongError as e:
            q.result.status_code = e.code
        return q

    def _host(self):
        from wukong_tpu.engine.cpu import CPUEngine

        if not hasattr(self, "_host_engine"):
            self._host_engine = CPUEngine(None, self.str_server)
        return self._host_engine

    def _execute_inner(self, q: SPARQLQuery) -> None:
        if q.pattern_group.unions and not q.has_pattern \
                and not q.pattern_group.optional:
            # top-level UNION: each branch is an independent distributed BGP;
            # branch results merge host-side (Result::merge_result semantics)
            self._execute_union_branches(q)
            return
        assert_ec(q.has_pattern, ErrorCode.UNKNOWN_PLAN, "no patterns")
        if q.pattern_group.unions or q.pattern_group.optional:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "distributed engine v1 supports BGP(+FILTER) "
                              "and top-level-UNION plans")
        assert_ec(not (q.result.blind and q.pattern_group.filters),
                  ErrorCode.UNSUPPORTED_SHAPE,
                  "blind mode cannot evaluate FILTER phases")
        cap_override: dict[int, int] = {}
        for _attempt in range(8):
            plan = self._build_plan(q, cap_override)
            fn, args = self._get_fn(plan)
            out = fn(*args)
            import jax

            if q.result.blind:
                ns, totals = jax.device_get((out["n"], out["totals"]))
                tables = None
            else:
                tables, ns, totals = jax.device_get(
                    (out["table"], out["n"], out["totals"]))
            totals = np.asarray(totals)  # [D, 2 * nsteps]
            S = len(plan.steps)
            over = False
            for i, s in enumerate(plan.steps):
                t = int(totals[:, i].max())
                if t > s.cap:
                    if t > self.cap_max:
                        raise WukongError(
                            ErrorCode.UNKNOWN_PATTERN,
                            f"intermediate result ({t:,} rows/shard) exceeds "
                            f"table_capacity_max ({self.cap_max:,})")
                    cap_override[("cap", i)] = K.next_capacity(
                        t, self.cap_min, self.cap_max)
                    over = True
                if s.exch_cap:
                    em = int(totals[:, S + i].max())
                    if em > s.exch_cap:
                        if em > self.cap_max:
                            raise WukongError(
                                ErrorCode.UNKNOWN_PATTERN,
                                f"exchange destination load ({em:,} rows) "
                                f"exceeds table_capacity_max ({self.cap_max:,})")
                        cap_override[("exch", i)] = K.next_capacity(
                            em, self.cap_min, self.cap_max)
                        over = True
            if not over:
                break
        else:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              "distributed capacity retry limit exceeded")

        res = q.result
        res.v2c_map = dict(plan.v2c)
        res.col_num = plan.width
        n_total = int(np.sum(ns))
        if q.result.blind:
            res.nrows = n_total
        else:
            parts = []
            for d in range(self.D):
                parts.append(np.asarray(tables[d][:, : int(ns[d])]).T)
            res.set_table(np.concatenate(parts).astype(np.int64)
                          if parts else np.empty((0, plan.width)))
        q.pattern_step = len(q.pattern_group.patterns)

    def _execute_union_branches(self, q: SPARQLQuery) -> None:
        merged = None
        host = self._host()
        for sub_pg in q.pattern_group.unions:
            assert_ec(not sub_pg.unions and not sub_pg.optional,
                      ErrorCode.UNSUPPORTED_SHAPE,
                      "nested groups inside UNION branches are unsupported "
                      "in distributed v1")
            child = SPARQLQuery()
            child.pg_type = PGType.UNION
            child.pattern_group = sub_pg
            child.result.nvars = q.result.nvars
            child.result.blind = False
            self._execute_inner(child)
            if sub_pg.filters:  # branch-level FILTERs run host-side per branch
                assert_ec(self.str_server is not None, ErrorCode.UNKNOWN_FILTER,
                          "FILTER needs a string server")
                host._execute_filters(child)
            merged = host._merge_union(merged, child.result, q.result.nvars)
        q.result.v2c_map = merged.v2c_map
        q.result.col_num = merged.col_num
        q.result.set_table(merged.table)
        q.union_done = True

    # ------------------------------------------------------------------
    # plan building (host): pattern list -> step descriptors with capacities
    # ------------------------------------------------------------------
    def _build_plan(self, q: SPARQLQuery, cap_override: dict) -> _Plan:
        plan = _Plan()
        v2c: dict[int, int] = {}
        width = 0
        aligned_col = None  # column rows are currently partitioned by
        est_rows = 1

        def cap_for(i, est):
            return cap_override.get(("cap", i)) or K.next_capacity(
                max(int(est), self.cap_min), self.cap_min, self.cap_max)

        patterns = q.pattern_group.patterns
        for i, pat in enumerate(patterns):
            s, p, d, o = pat.subject, pat.predicate, pat.direction, pat.object
            assert_ec(pat.pred_type == int(AttrType.SID_t) and p >= 0,
                      ErrorCode.UNSUPPORTED_SHAPE,
                      "attr/versatile unsupported in distributed v1")
            if i == 0 and q.start_from_index():
                idx = self.sstore.index_list(s, d)
                est_rows = max(idx.total // self.D, 1) * 2
                step = _Step(kind="init_index", pid=s, dir=d,
                             cap=cap_for(i, est_rows))
                v2c[o] = 0
                width = 1
                aligned_col = 0  # index lists are owner-local by construction
                plan.steps.append(step)
                continue
            if i == 0 or width == 0:
                assert_ec(s > 0, ErrorCode.FIRST_PATTERN_ERROR)
                seg = self.sstore.segment(p, d)
                est_rows = int((seg.avg_deg if seg else 1) * 2)
                step = _Step(kind="init_const", pid=p, dir=d, const=s,
                             cap=cap_for(i, est_rows))
                v2c[o] = 0
                width = 1
                aligned_col = None  # rows sit on the const's owner, not col 0's
                plan.steps.append(step)
                continue

            col = v2c.get(s, NO_RESULT)
            assert_ec(col != NO_RESULT, ErrorCode.UNSUPPORTED_SHAPE,
                      "distributed steps must anchor on a KNOWN subject")
            o_col = v2c.get(o, NO_RESULT) if o < 0 else NO_RESULT
            o_known = o < 0 and o_col != NO_RESULT

            type_all = (p == TYPE_ID and d == IN and o < 0 and not o_known)
            exch_cap = 0
            if not type_all and aligned_col != col:
                exch_cap = cap_override.get(("exch", i)) or K.next_capacity(
                    max(est_rows // self.D * 4, self.cap_min),
                    self.cap_min, self.cap_max)

            seg = self.sstore.segment(p, d)
            avg = seg.avg_deg if seg else 0.0
            if o < 0 and not o_known:  # expansion
                est_rows = int(max(est_rows * max(avg, 0.1) * 2, 1))
                kind = "expand_type_all" if type_all else "expand"
                step = _Step(kind=kind, pid=p, dir=d, col=col,
                             cap=min(cap_for(i, est_rows), self.cap_max),
                             exch_cap=exch_cap, new_col=True)
                v2c[o] = width
                width += 1
                aligned_col = width - 1 if type_all else col
            else:  # member filter
                step = _Step(kind="member", pid=p, dir=d, col=col,
                             vals_col=(o_col if o_known else -1),
                             const=(0 if o_known else o),
                             cap=cap_for(i, est_rows), exch_cap=exch_cap)
                aligned_col = col
            plan.steps.append(step)

        plan.width = width
        plan.v2c = v2c
        return plan

    # ------------------------------------------------------------------
    # compiled chain per plan signature
    # ------------------------------------------------------------------
    def _get_fn(self, plan: _Plan):
        # gather the device arrays each step needs (also the call args);
        # per-step (max_probe, max_deg_log2) join the cache key because the
        # compiled chain bakes them in as constants — a restaged segment
        # (dynamic insert) must never reuse a chain with smaller bounds
        bounds = []
        args = []
        for s in plan.steps:
            if s.kind == "init_index":
                idx = self.sstore.index_list(s.pid, s.dir)
                args.append((idx.edges, self._real_lens_arr(idx)))
                bounds.append((0, 0))
            else:
                seg = self.sstore.segment(s.pid, s.dir)
                if seg is None:
                    args.append(None)
                    bounds.append((0, 0))
                else:
                    args.append((seg.bkey, seg.bstart, seg.bdeg, seg.edges))
                    bounds.append((seg.max_probe, seg.max_deg_log2))
        sig = (plan.signature(), tuple(bounds))
        if sig in self._fn_cache:
            return self._fn_cache[sig], self._flatten_args(args)
        fn = self._compile(plan, args)
        self._fn_cache[sig] = fn
        return fn, self._flatten_args(args)

    def _real_lens_arr(self, idx):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(idx.real_lens.astype(np.int32).reshape(-1, 1),
                              NamedSharding(self.mesh, P(self.axis, None)))

    @staticmethod
    def _flatten_args(args):
        flat = []
        for a in args:
            if a is not None:
                flat.extend(a)
        return flat

    def _compile(self, plan: _Plan, args_template):
        import jax
        import jax.numpy as jnp
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        D = self.D
        axis = self.axis
        steps = [s for s in plan.steps]
        # arg layout mirrors _flatten_args
        arg_specs = []
        for a in args_template:
            if a is not None:
                arg_specs.extend([P(axis, *([None] * (x.ndim - 1))) for x in a])

        probes = {}
        depths = {}
        for i, s in enumerate(steps):
            if s.kind != "init_index":
                seg = self.sstore.segment(s.pid, s.dir)
                probes[i] = seg.max_probe if seg else 1
                depths[i] = seg.max_deg_log2 if seg else 1

        def shard_fn(*flat):
            # unflatten per-step args (squeeze the leading shard axis)
            per_step = []
            it = iter(flat)
            for a in args_template:
                if a is None:
                    per_step.append(None)
                else:
                    per_step.append(tuple(next(it)[0] for _ in a))

            table = None
            n = jnp.int32(0)
            totals = [jnp.int32(0)] * len(steps)
            exch_totals = [jnp.int32(0)] * len(steps)

            for i, s in enumerate(steps):
                if s.kind == "init_index":
                    edges, lens = per_step[i]
                    table, n = K.init_from_list.__wrapped__(
                        edges, lens[0], s.cap)
                    totals[i] = lens[0]
                    continue
                if s.kind == "init_const":
                    arrs = per_step[i]
                    const_tab = jnp.full((1, 1), np.int32(s.const), jnp.int32)
                    if arrs is None:
                        table = jnp.zeros((1, s.cap), jnp.int32)
                        n = jnp.int32(0)
                        continue
                    bkey, bstart, bdeg, edges = arrs
                    table, n, tot = K.expand.__wrapped__(
                        const_tab, jnp.int32(1), bkey, bstart, bdeg, edges,
                        col=0, cap_out=s.cap, max_probe=probes[i])
                    table = table[1:, :]  # drop the const row ([W, C] layout)
                    totals[i] = tot
                    continue

                if s.exch_cap:
                    table, n, em, tot_recv = _exchange(
                        table, n, s.col, s.exch_cap, s.cap, D, axis)
                    exch_totals[i] = em
                    totals[i] = jnp.maximum(totals[i], tot_recv)

                arrs = per_step[i]
                if s.kind in ("expand", "expand_type_all"):
                    if s.kind == "expand_type_all":
                        table, n = _allgather_rows(table, n, D, axis)
                    if arrs is None:
                        table = jnp.concatenate(
                            [table, jnp.zeros((1, table.shape[1]), jnp.int32)],
                            axis=0)
                        n = jnp.int32(0)
                        continue
                    bkey, bstart, bdeg, edges = arrs
                    table, n, tot = K.expand.__wrapped__(
                        table, n, bkey, bstart, bdeg, edges, col=s.col,
                        cap_out=s.cap, max_probe=probes[i])
                    totals[i] = jnp.maximum(totals[i], tot)
                elif s.kind == "member":
                    if arrs is None:
                        keep = jnp.zeros(table.shape[1], bool)
                    else:
                        bkey, bstart, bdeg, edges = arrs
                        if s.vals_col >= 0:
                            vals = table[s.vals_col]
                        else:
                            vals = jnp.full(table.shape[1], np.int32(s.const))
                        keep = K.member_mask_known.__wrapped__(
                            table, n, vals, bkey, bstart, bdeg, edges,
                            col=s.col, max_probe=probes[i], depth=depths[i])
                    table, n = K.compact.__wrapped__(table, keep)

            return {
                "table": table[None],
                "n": n[None],
                "totals": jnp.stack(totals + exch_totals)[None],
            }

        out_specs = {"table": P(axis), "n": P(axis), "totals": P(axis)}
        mapped = shard_map(shard_fn, mesh=self.mesh,
                           in_specs=tuple(arg_specs), out_specs=out_specs,
                           check_vma=False)
        return jax.jit(mapped)


# ---------------------------------------------------------------------------
# collective building blocks (inside shard_map)
# ---------------------------------------------------------------------------


def _exchange(table, n, col, exch_cap: int, cap_new: int, D: int, axis: str):
    """Repartition rows to hash owners of `col` — the fork-join replacement.

    table: [W, C]. Per-destination capacity-padded all_to_all: send buffer
    [D, W, exch_cap]; per-dest row counts ride along so receivers compact
    exactly. Returns (table [W, cap_new], n, max_dest_count, total_received).
    """
    import jax
    import jax.numpy as jnp

    W, C = table.shape
    rows = jnp.arange(C, dtype=jnp.int32)
    live = rows < n
    dest = jnp.where(live, table[col] % D, D)
    order = jnp.argsort(dest, stable=True)
    st = table[:, order]
    sd = dest[order]
    counts = jnp.bincount(dest, length=D + 1)[:D].astype(jnp.int32)
    cumx = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    within = rows - cumx[jnp.clip(sd, 0, D - 1)]
    slot = jnp.where((sd < D) & (within < exch_cap),
                     sd * exch_cap + within, D * exch_cap)
    send = jnp.zeros((W, D * exch_cap), jnp.int32).at[:, slot].set(
        st, mode="drop")
    send = send.reshape(W, D, exch_cap).transpose(1, 0, 2)  # [D, W, exch_cap]
    recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                              tiled=False)
    rcounts = jax.lax.all_to_all(counts.reshape(D, 1), axis, 0, 0,
                                 tiled=False).reshape(D)
    cumr = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(rcounts)[:-1].astype(jnp.int32)])
    flat = recv.transpose(1, 0, 2).reshape(W, D * exch_cap)
    r_in_blk = jnp.tile(jnp.arange(exch_cap, dtype=jnp.int32), D)
    blk = jnp.repeat(jnp.arange(D, dtype=jnp.int32), exch_cap)
    valid = r_in_blk < jnp.minimum(rcounts, exch_cap)[blk]
    pos = jnp.where(valid, cumr[blk] + r_in_blk, cap_new)
    out = jnp.zeros((W, cap_new), jnp.int32).at[:, pos].set(flat, mode="drop")
    tot_recv = rcounts.sum().astype(jnp.int32)
    new_n = jnp.minimum(tot_recv, cap_new)
    return out, new_n, counts.max(), tot_recv


def _allgather_rows(table, n, D: int, axis: str):
    """Replicate all live rows to every shard (dispatch-to-all for type steps).

    table: [W, C] -> [W, D*C]."""
    import jax
    import jax.numpy as jnp

    W, C = table.shape
    gat = jax.lax.all_gather(table, axis)  # [D, W, C]
    ns = jax.lax.all_gather(n, axis)  # [D]
    flat = gat.transpose(1, 0, 2).reshape(W, D * C)
    blk = jnp.repeat(jnp.arange(D, dtype=jnp.int32), C)
    r_in = jnp.tile(jnp.arange(C, dtype=jnp.int32), D)
    valid = r_in < ns[blk]
    cumn = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(ns)[:-1].astype(jnp.int32)])
    pos = jnp.where(valid, cumn[blk] + r_in, D * C)
    out = jnp.zeros((W, D * C), jnp.int32).at[:, pos].set(flat, mode="drop")
    return out, ns.sum().astype(jnp.int32)
