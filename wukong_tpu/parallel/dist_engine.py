"""Distributed query execution: shard_map chains with all-to-all row exchange.

This is the TPU-native replacement for the reference's distributed machinery:

- graph partitioned by hash(vid) % D over mesh devices (base_loader.hpp:172-173)
- one-sided RDMA reads + fork-join sub-queries (sparql.hpp:746-814,
  rmap.hpp) become a capacity-padded `lax.all_to_all` of binding-table rows
  keyed by the anchor column's owner, executed INSIDE one compiled program
- index-origin starts run on every shard over its local index slice
  (= dispatch to all servers x mt_factor, sparql.hpp:1064-1088)
- mid-chain type-membership expansion all-gathers rows and expands against
  each shard's local type index (= the reference's dispatch-to-all for
  `p == TYPE_ID && d == IN`, sparql.hpp:1139-1152)

The whole pattern chain for a query compiles to ONE jitted shard_map program
(cached per plan signature x capacity classes): zero mid-query host syncs, one
device_get at the end for row counts + overflow totals (+ gathered tables when
not blind). Capacity overflow anywhere (expansion or exchange) triggers a
host-side retry of the whole chain at exact capacities.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from wukong_tpu.config import Global
from wukong_tpu.engine import tpu_kernels as K
from wukong_tpu.parallel.sharded_store import ShardedDeviceStore
from wukong_tpu.sparql.ir import NO_RESULT, PGType, SPARQLQuery
from wukong_tpu.types import IN, OUT, PREDICATE_ID, TYPE_ID, AttrType
from wukong_tpu.utils.errors import (
    BudgetExceeded,
    CapacityExceeded,
    ErrorCode,
    QueryTimeout,
    WukongError,
    assert_ec,
)


@dataclass
class _Step:
    kind: str  # init_index | init_const | init_rows | expand
    #           | expand_type_all | expand_versatile | member
    pid: int = 0
    dir: int = 0
    col: int = -1  # anchor column
    vals_col: int = -1  # member: end column (-1 => const)
    const: int = 0  # member const / init const vid
    cap: int = 0  # output capacity class (expansion / exchange target)
    exch_cap: int = 0  # per-destination exchange capacity (0 = no exchange)
    new_col: bool = False
    width: int = 0  # init_rows: seed table width


@dataclass
class _Plan:
    steps: list = field(default_factory=list)
    width: int = 0
    v2c: dict = field(default_factory=dict)

    def signature(self):
        return tuple(
            (s.kind, s.pid, s.dir, s.col, s.vals_col, s.const, s.cap,
             s.exch_cap, s.width)
            for s in self.steps)


class DistEngine:
    """Executes device-supported SPARQL plans across a device mesh."""

    def __init__(self, stores: list, str_server=None, mesh=None, axis: str = "x"):
        from wukong_tpu.parallel.mesh import make_mesh

        self.mesh = mesh or make_mesh(len(stores))
        self.axis = axis
        self.D = len(stores)
        self.sstore = ShardedDeviceStore(stores, self.mesh, axis)
        self.str_server = str_server
        self.cap_min = Global.table_capacity_min
        self.cap_max = Global.table_capacity_max
        self._fn_cache: dict = {}
        # per-chain observability (bench --dist artifact detail): set by the
        # last successful _run_device_bgp — per-step row/exchange loads vs
        # their capacity classes, and how many whole-chain retries were paid
        self.last_chain_stats: dict | None = None
        self._last_plan: _Plan | None = None
        # one-shot dryrun hook: seed the NEXT chain's capacity overrides
        # (e.g. an undersized class) to exercise the overflow-retry path
        # deterministically; consumed and cleared by _run_device_bgp
        self.force_cap_override: dict | None = None
        # learned capacity classes per pattern-chain key: estimate-driven
        # first runs over-pad (the skew bound is conservative by design —
        # BENCH_DIST_r04 measured q1 shipping 335 MB of PADDED all-to-all
        # per chain against ~16x-smaller real peaks), so successful runs
        # record the EXACT classes and steady-state chains recompile once
        # at tight capacities; undersized learning self-corrects through
        # the normal overflow retry
        self._learned_caps: dict = {}

    # ------------------------------------------------------------------
    def execute(self, q: SPARQLQuery, from_proxy: bool = True) -> SPARQLQuery:
        from wukong_tpu.obs.trace import traced_execute

        # the ambient activation makes shard fetches / retries / breaker
        # trips land on this query's trace (see traced_execute)
        return traced_execute(
            q, "dist.execute", lambda: self._execute_impl(q, from_proxy),
            lambda: {"rows": q.result.nrows,
                     "status": q.result.status_code.name,
                     "complete": q.result.complete})

    def _execute_impl(self, q: SPARQLQuery,
                      from_proxy: bool = True) -> SPARQLQuery:
        if self.sstore.check_version():
            # compiled chains bake per-segment max_probe/depth — stale after
            # dynamic inserts (dynamic_gstore.hpp lease invalidation analogue);
            # learned capacity classes measured the old data; the in-place
            # engine's shard-segment/global-index memos point at old arrays
            self._fn_cache.clear()
            self._learned_caps.clear()
            self.__dict__.pop("_inplace_eng", None)
        # degraded stagings are never cached, so a query served entirely
        # from cache is complete by construction — judge incompleteness only
        # by fetch failures during THIS query, not a prior query's outage
        self.sstore.degraded_shards.clear()
        try:
            self._execute_sm(q, from_proxy)
        except (QueryTimeout, BudgetExceeded) as e:
            from wukong_tpu.runtime.resilience import mark_partial

            mark_partial(q, e)
        except WukongError as e:
            q.result.status_code = e.code
        if self.sstore.degraded_shards and q.result.status_code in (
                ErrorCode.SUCCESS, ErrorCode.QUERY_TIMEOUT,
                ErrorCode.BUDGET_EXCEEDED):
            # a down shard's partition contributed nothing to this chain:
            # the reply is well-formed but incomplete — tag it so clients
            # can distinguish "empty" from "missing a shard" (no crash)
            q.result.complete = False
            for s in sorted(self.sstore.degraded_shards):
                tag = f"shard:{s}"
                if tag not in q.result.dropped_patterns:
                    q.result.dropped_patterns.append(tag)
        return q

    def _execute_sm(self, q: SPARQLQuery, from_proxy: bool) -> None:
        """The distributed state machine: PATTERN -> UNION -> OPTIONAL ->
        FILTER -> FINAL (sparql.hpp:1564-1673). BGPs run as compiled
        shard_map chains; UNION branches and OPTIONAL groups run as seeded
        distributed children; FILTER/FINAL run host-side on the gathered
        table (they touch strings and projections, not the graph)."""
        if q.planner_empty and Global.enable_empty_shortcircuit:
            # planner-proved empty: no sharded chain, no collectives
            self._host().short_circuit_empty(q)
            if from_proxy:
                self._host()._final_process(q)
            return
        # silent-mode parity (reference Global::silent works for ANY shape —
        # it executes fully and simply never ships the table, query.hpp
        # shrink 619-630): shapes whose children need the gathered table
        # run non-blind internally and drop the table at reply time
        blind_deferred = bool(
            q.result.blind and (q.pattern_group.filters
                                or q.pattern_group.unions
                                or q.pattern_group.optional))
        if blind_deferred:
            q.result.blind = False
        if q.has_pattern and not q.done_patterns():
            self._execute_bgp(q)
        if q.pattern_group.unions and not q.union_done:
            self._execute_unions_dist(q)
        while q.optional_step < len(q.pattern_group.optional):
            self._execute_optional_dist(q)
        if q.pattern_group.filters or (from_proxy and q.orders):
            assert_ec(self.str_server is not None, ErrorCode.UNKNOWN_FILTER,
                      "FILTER/ORDER BY needs a string server")
        if q.pattern_group.filters:
            self._host()._execute_filters(q)
        if from_proxy:
            self._host()._final_process(q)
        if blind_deferred:
            # drop the table at reply time; the count survives (shrink)
            res = q.result
            res.blind = True
            nrows = res.nrows
            res.table = np.empty((0, res.col_num), dtype=np.int64)
            res.attr_table = np.empty((0, res.attr_col_num), np.float64)
            res.nrows = nrows

    def _host(self):
        from wukong_tpu.engine.cpu import CPUEngine

        if not hasattr(self, "_host_engine"):
            self._host_engine = CPUEngine(None, self.str_server)
        return self._host_engine

    def _attr_host(self):
        """Host engine over the sharded attribute segments: an attr lookup
        routes to the subject owner's partition — the reference executes attr
        patterns CPU-side too (gpu_engine.hpp:267-333 unsupported on GPU)."""
        from wukong_tpu.engine.cpu import CPUEngine

        if not hasattr(self, "_attr_engine"):
            self._attr_engine = CPUEngine(_ShardedAttrGraph(self.sstore.stores),
                                          self.str_server)
        return self._attr_engine

    # ------------------------------------------------------------------
    def _execute_bgp(self, q: SPARQLQuery) -> None:
        """Device-supported prefix as one distributed chain; a trailing run of
        attribute patterns executes host-side over the sharded attr stores."""
        pats = q.pattern_group.patterns
        split = q.pattern_step
        while split < len(pats) and \
                pats[split].pred_type == int(AttrType.SID_t):
            split += 1
        for pat in pats[split:]:  # the tail must be all-attr
            assert_ec(pat.pred_type != int(AttrType.SID_t),
                      ErrorCode.UNSUPPORTED_SHAPE,
                      "SID patterns after attr patterns are unsupported "
                      "in the distributed engine")
        first = pats[q.pattern_step] if split > q.pattern_step else None
        if first is not None \
                and self._try_inplace(q, n_steps=split - q.pattern_step):
            first = None  # the whole SID prefix ran in place
        if first is not None and q.result.col_num == 0 \
                and first.predicate < 0 and first.subject > 0:
            # versatile const start (CONST ?p ?y / CONST1 ?p CONST2): the
            # const's combined adjacency is one CSR walk on its owner
            # partition — done host-side, the rest of the chain runs as a
            # seeded distributed child (like the single-chip engine)
            self._versatile_const_start(q, first)
        if split > q.pattern_step:
            seed = None
            if q.result.col_num > 0:  # seeded child (UNION branch on a table)
                seed = (q.result.table, dict(q.result.v2c_map))
            tr = getattr(q, "trace", None)
            if tr is None:
                self._run_device_bgp(q, n_steps=split - q.pattern_step,
                                     seed=seed)
            else:
                sp = tr.start_span("dist.chain",
                                   steps=split - q.pattern_step,
                                   rows_in=q.result.nrows)
                try:
                    self._run_device_bgp(q, n_steps=split - q.pattern_step,
                                         seed=seed)
                finally:
                    st = getattr(self, "last_chain_stats", None) or {}
                    tr.end_span(sp, rows_out=q.result.nrows,
                                **{k: st[k] for k in
                                   ("mode", "retries", "exchanges")
                                   if k in st})
        while not q.done_patterns():  # attr tail (or attr-only query)
            self._attr_host()._execute_one_pattern(q)

    def _try_inplace(self, q: SPARQLQuery, n_steps: int) -> bool:
        """Owner-routed in-place fast path for small-table chains (reference
        need_fork_join, sparql.hpp:802-814; proxy owner routing,
        proxy.hpp:201-219): light queries run the whole SID prefix host-side
        against the federated partition view — zero collectives, zero
        compiles — and retreat to the collective chain the moment the live
        table outgrows Global.dist_inplace_rows. Returns True when the
        prefix completed in place (pattern_step advanced past it)."""
        if not Global.enable_dist_inplace or n_steps <= 0:
            return False
        from wukong_tpu.parallel.inplace import InplaceOverflow

        thr = max(int(Global.dist_inplace_rows), 1)
        pats = q.pattern_group.patterns[q.pattern_step:q.pattern_step
                                        + n_steps]
        first = pats[0]
        eng = self._inplace_engine()
        if q.result.col_num == 0:
            # fresh starts: const-anchored only — index origins scan whole
            # index lists (the heavies) and belong to the sharded chain
            if first.subject <= 0 or _is_index_pattern(first):
                return False
            if first.predicate > 0:
                # exact first fan-out, one owner CSR lookup — the entry
                # check (the reference sizes the same decision on fetch
                # length vs global_rdma_threshold)
                fan = len(eng.g.get_triples(
                    first.subject, first.predicate, first.direction))
                if fan > thr:
                    return False
            # versatile starts (p < 0): no cheap exact bound; the dynamic
            # abort below still caps the walk
        elif q.result.nrows > thr:
            return False  # seeded (UNION/OPTIONAL) child with a big table
        import copy

        snap_step = q.pattern_step
        snap_res = copy.deepcopy(q.result)
        target = q.pattern_step + n_steps
        from wukong_tpu.runtime.resilience import charge_query, check_query

        tr = getattr(q, "trace", None)
        sp = (tr.start_span("dist.inplace", steps=n_steps,
                            rows_in=q.result.nrows)
              if tr is not None else None)
        try:
            while q.pattern_step < target:
                check_query(q, f"dist.inplace step {q.pattern_step}")
                eng._execute_one_pattern(q)
                charge_query(q, q.result.nrows,
                             f"dist.inplace step {q.pattern_step - 1}")
                if q.result.nrows > thr:
                    raise InplaceOverflow()
        except InplaceOverflow:
            q.pattern_step = snap_step
            q.result = snap_res
            if sp is not None:  # aborted to the collective chain
                tr.end_span(sp, ok=False, overflow=True)
            return False
        except BaseException:
            if sp is not None:
                tr.end_span(sp, ok=False, raised=True)
            raise
        if sp is not None:
            tr.end_span(sp, ok=True, rows_out=int(q.result.nrows))
        if q.result.blind and q.done_patterns():
            # blind parity with the collective chain (which never gathers
            # the table): count survives, rows are dropped. A pending attr
            # tail keeps the table — it still anchors the attr kernels.
            res = q.result
            nrows = res.nrows
            res.table = np.empty((0, res.col_num), dtype=np.int64)
            res.nrows = nrows
        self.last_chain_stats = {"mode": "inplace", "retries": 0,
                                 "exchanges": 0, "steps": n_steps,
                                 "rows": int(q.result.nrows)}
        self._last_plan = None  # bytes_model: no collective chain to model
        return True

    def _inplace_engine(self):
        from wukong_tpu.parallel.inplace import InplaceEngine

        if not hasattr(self, "_inplace_eng"):
            self._inplace_eng = InplaceEngine(self.sstore.stores,
                                              self.str_server)
        return self._inplace_eng

    def _versatile_const_start(self, q: SPARQLQuery, pat) -> None:
        """Delegate to a CPU engine over the const's owner partition — the
        owner holds the full combined adjacency (vertices are placed by
        hash on both subject and object), and the CPU kernels carry the
        exact const_unknown_* semantics (incl. start_from_index rejection
        of malformed tpid starts)."""
        from wukong_tpu.engine.cpu import CPUEngine
        from wukong_tpu.utils.mathutil import hash_mod

        owner = int(hash_mod(int(np.int32(pat.subject)), self.D))
        if not hasattr(self, "_owner_hosts"):
            self._owner_hosts: dict = {}
        if owner not in self._owner_hosts:
            self._owner_hosts[owner] = CPUEngine(self.sstore.stores[owner],
                                                 self.str_server)
        self._owner_hosts[owner]._execute_one_pattern(q)

    def _execute_unions_dist(self, q: SPARQLQuery) -> None:
        """Each UNION branch is a distributed child seeded with the parent's
        result table (query.hpp:702-711 inherit_union); children recurse
        through the full state machine, so nested UNION/OPTIONAL work."""
        from wukong_tpu.sparql.ir import Result

        assert_ec(q.result.attr_col_num == 0, ErrorCode.UNSUPPORT_UNION)
        q.union_done = True
        merged = None
        host = self._host()
        for sub_pg in q.pattern_group.unions:
            child = SPARQLQuery()
            child.pqid = q.qid
            child.pg_type = PGType.UNION
            child.pattern_group = sub_pg
            child.deadline = q.deadline  # children share the parent's budget
            # children rebind result state rather than mutate it, so the
            # parent table is shared by reference (no deepcopy of rows)
            child.result = Result(q.result.nvars)
            child.result.v2c_map = dict(q.result.v2c_map)
            child.result.col_num = q.result.col_num
            child.result.table = q.result.table
            child.result.nrows = q.result.nrows
            child.result.blind = False
            self._execute_sm(child, from_proxy=False)
            if child.result.status_code != ErrorCode.SUCCESS:
                raise WukongError(child.result.status_code,
                                  "union child failed")
            merged = host._merge_union(merged, child.result, q.result.nvars)
        q.result.v2c_map = merged.v2c_map
        q.result.col_num = merged.col_num
        q.result.set_table(merged.table)

    def _execute_optional_dist(self, q: SPARQLQuery) -> None:
        """OPTIONAL as a dedup-seeded distributed child + host left join
        (the shared engine-agnostic formulation, engine/optional_join.py)."""
        from wukong_tpu.engine.optional_join import execute_optional_leftjoin

        execute_optional_leftjoin(
            q, self._host(),
            run_child=lambda c: self._execute_sm(c, from_proxy=False),
            str_server=self.str_server)

    # ------------------------------------------------------------------
    def load_cap_memo(self, path: str) -> None:
        """Load learned capacity classes persisted by a previous process.
        A cold process then traces ONE program per chain at the exact
        classes (whose XLA compilation the persistent cache already holds)
        instead of estimate-class + overflow-retry + tight-class recompile
        — the dominant share of BENCH_DIST_r04's 4.5-9.7 s first_us
        (round-4 verdict Weak #3)."""
        import json as _json

        try:
            with open(path) as f:
                for ent in _json.load(f):
                    key = tuple(tuple(p) for p in ent["pats"])
                    caps = {}
                    for ck, v in ent["caps"].items():
                        kind, i = ck.split(":")
                        caps[(kind, int(i))] = int(v)
                    self._learned_caps.setdefault(key, caps)
        except FileNotFoundError:
            pass
        except Exception as e:
            from wukong_tpu.utils.logger import log_warn

            log_warn(f"dist cap memo load failed: {e}")

    def save_cap_memo(self, path: str) -> None:
        import json as _json
        import os as _os

        try:
            data = [{"pats": [list(p) for p in key],
                     "caps": {f"{k}:{i}": int(v)
                              for (k, i), v in caps.items()}}
                    for key, caps in self._learned_caps.items()]
            tmp = path + ".tmp"
            _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                _json.dump(data, f)
            _os.replace(tmp, path)
        except Exception as e:
            from wukong_tpu.utils.logger import log_warn

            log_warn(f"dist cap memo save failed: {e}")

    def _run_device_bgp(self, q: SPARQLQuery, n_steps: int, seed=None) -> None:
        pats_key = tuple(
            (p.subject, p.predicate, int(p.direction), p.object)
            for p in q.pattern_group.patterns[
                q.pattern_step:q.pattern_step + n_steps])
        # learned caps apply only to unseeded chains, symmetric with the
        # write below: a seeded plan prepends init_rows (shifting every
        # step index) and carries a different parent table's cardinalities
        cap_override: dict = (dict(self._learned_caps.get(pats_key, {}))
                              if seed is None else {})
        if self.force_cap_override:
            cap_override.update(self.force_cap_override)
        self.force_cap_override = None
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.resilience import (
            charge_query,
            check_query,
            retry_call,
        )

        seed_cache: dict = {}  # seed shards are retry-invariant; transfer once
        for _attempt in range(8):
            check_query(q, f"dist.chain attempt {_attempt}")
            plan = self._build_plan(q, cap_override, n_steps, seed)
            fn, args = self._get_fn(plan, seed, seed_cache)

            def _dispatch():
                # transient dispatch failures (device hiccup, injected
                # chaos) retry with backoff; inputs are immutable so a
                # re-dispatch is safe. Routed through the transport seam:
                # the mesh is process-local on every backend we have, so
                # both transports execute in place, but the dispatch path
                # shares the fetch path's boundary object by contract.
                faults.site("dist.chain_dispatch")
                return self.sstore.transport.dispatch(fn, *args)

            out = retry_call(_dispatch, site="dist.chain_dispatch",
                             retry_on=(faults.TransientFault,),
                             deadline=getattr(q, "deadline", None))

            if q.result.blind:
                ns, totals = _gather_host((out["n"], out["totals"]))
                tables = None
            else:
                tables, ns, totals = _gather_host(
                    (out["table"], out["n"], out["totals"]))
            totals = np.asarray(totals)  # [D, 2 * nsteps]
            S = len(plan.steps)
            over = False
            for i, s in enumerate(plan.steps):
                t = int(totals[:, i].max())
                if t > s.cap:
                    if t > self.cap_max:
                        raise CapacityExceeded(
                            f"intermediate result ({t:,} rows/shard) exceeds "
                            f"table_capacity_max ({self.cap_max:,})")
                    cap_override[("cap", i)] = K.next_capacity(
                        t, self.cap_min, self.cap_max)
                    over = True
                if s.exch_cap:
                    em = int(totals[:, S + i].max())
                    if em > s.exch_cap:
                        if em > self.cap_max:
                            raise CapacityExceeded(
                                f"exchange destination load ({em:,} rows) "
                                f"exceeds table_capacity_max ({self.cap_max:,})")
                        cap_override[("exch", i)] = K.next_capacity(
                            em, self.cap_min, self.cap_max)
                        over = True
            if not over:
                break
        else:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              "distributed capacity retry limit exceeded")

        # chain observability for the bench artifact (round-4 verdict #3:
        # the 42x cpu-mesh number needs per-step evidence, not a single
        # end-to-end time): per step, the peak per-shard row load and peak
        # per-destination exchange load against their capacity classes
        S = len(plan.steps)
        step_stats = []
        for i, s in enumerate(plan.steps):
            st = {"kind": s.kind, "cap": s.cap,
                  "rows_peak_shard": int(totals[:, i].max()),
                  "rows_all_shards": int(totals[:, i].sum())}
            if s.exch_cap:
                st["exch_cap"] = s.exch_cap
                st["exch_peak_dest"] = int(totals[:, S + i].max())
            step_stats.append(st)
        self.last_chain_stats = {"retries": int(_attempt),
                                 "exchanges": sum(1 for s in plan.steps
                                                  if s.exch_cap),
                                 "steps": step_stats}
        self._last_plan = plan
        # learn EXACT classes for the next run of this chain (tighter
        # where the estimate over-padded, already-exact where it retried)
        learned = {}
        for i, s in enumerate(plan.steps):
            learned[("cap", i)] = K.next_capacity(
                max(int(totals[:, i].max()), 1), self.cap_min, self.cap_max)
            if s.exch_cap:
                learned[("exch", i)] = K.next_capacity(
                    max(int(totals[:, S + i].max()), 1),
                    self.cap_min, self.cap_max)
        if len(self._learned_caps) > 1024:
            self._learned_caps.clear()
        if seed is None:
            # seeded children sharing a pats key can carry very different
            # parent tables; learning from one would mis-size the next
            # (the retry would self-correct, but at a recompile per flip)
            self._learned_caps[pats_key] = learned

        n_total = int(np.sum(ns))
        charge_query(q, n_total, "dist.chain")
        res = q.result
        res.v2c_map = dict(plan.v2c)
        res.col_num = plan.width
        if q.result.blind:
            res.nrows = n_total
        else:
            parts = []
            for d in range(self.D):
                parts.append(np.asarray(tables[d][:, : int(ns[d])]).T)
            tab = (np.concatenate(parts) if parts
                   else np.empty((0, plan.width), np.int64))
            # device tables are int32; BLANK_ID must round-trip to its
            # uint32 host value (types.py BLANK_ID_I32)
            res.set_table(tab.astype(np.int64) & 0xFFFFFFFF
                          if tab.dtype == np.int32 else tab.astype(np.int64))
        q.pattern_step += n_steps

    # ------------------------------------------------------------------
    def bytes_model(self) -> dict | None:
        """Host-side traffic model of the LAST executed chain (the dist
        bench's roofline fields, round-4 verdict #4): staged segment arrays
        read, sharded table state written at the capacity classes, and —
        the number the 42x diagnosis needs — the capacity-PADDED collective
        traffic (all_to_all ships [D, W, exch_cap] per shard regardless of
        real row counts; expand_type_all allgathers the whole table). Each
        array counted once; a lower bound on real traffic per executed
        chain."""
        plan = self._last_plan
        if plan is None:
            return None
        W = 4  # int32 device arrays
        D = self.D
        seg_b = tab_b = exch_b = 0
        width = 0
        cap_prev = 0
        for s in plan.steps:
            w_in = width
            if s.kind == "init_rows":
                width = s.width
                cap_prev = s.cap
                tab_b += W * D * width * s.cap
                continue
            if s.kind == "init_index":
                idx = self.sstore.index_list(s.pid, s.dir)
                seg_b += int(idx.edges.size) * W
                width = 1
                cap_prev = s.cap
                tab_b += W * D * s.cap
                continue
            if s.kind == "init_const":
                seg = self.sstore.segment(s.pid, s.dir)
                seg_b += int(seg.nbytes) if seg is not None else 0
                width = 1
                cap_prev = s.cap
                tab_b += W * D * s.cap
                continue
            if s.exch_cap:
                exch_b += W * D * D * w_in * s.exch_cap
            if s.kind == "expand_type_all":
                # allgather replication of the whole table to every shard
                exch_b += W * D * D * w_in * cap_prev
            if s.kind == "member_index":
                idx = self.sstore.index_list(s.pid, s.dir)
                seg_b += int(idx.edges.size) * W
            elif s.kind in ("expand_versatile", "expand_versatile_const"):
                vseg = self.sstore.versatile_segment(s.dir)
                seg_b += int(vseg.nbytes) if vseg is not None else 0
            else:
                seg = self.sstore.segment(s.pid, s.dir)
                seg_b += int(seg.nbytes) if seg is not None else 0
            if s.new_col:
                width += 2 if s.kind == "expand_versatile" else 1
            tab_b += W * D * (w_in * cap_prev + width * s.cap)
            cap_prev = s.cap
        return {"segment_bytes": int(seg_b), "table_bytes": int(tab_b),
                "exchange_bytes": int(exch_b),
                "total_bytes": int(seg_b + tab_b + exch_b)}

    # ------------------------------------------------------------------
    # plan building (host): pattern list -> step descriptors with capacities
    # ------------------------------------------------------------------
    def _build_plan(self, q: SPARQLQuery, cap_override: dict,
                    n_steps: int | None = None, seed=None) -> _Plan:
        plan = _Plan()
        v2c: dict[int, int] = {}
        width = 0
        aligned_col = None  # column rows are currently partitioned by
        est_rows = 1
        # upper bound on how many rows can share one value per column —
        # exchanges route equal values to one destination, so the hot-dest
        # load is bounded by est/D + the anchor column's multiplicity (the
        # University0-hub skew the reference absorbs via work stealing,
        # engine.hpp:186-207). Index/const starts yield unique values
        # (bound 1); expansions multiply every column's bound by the
        # segment's max degree, and the new column's bound is the REVERSE
        # segment's max degree times the anchor's. Unknown columns (seeds)
        # stay untracked -> the generic 4x-slack estimate.
        col_mult: dict[int, int] = {}
        MULT_CAP = 1 << 31

        def cap_for(i, est):
            return cap_override.get(("cap", i)) or K.next_capacity(
                max(int(est), self.cap_min), self.cap_min, self.cap_max)

        def exch_cap_for(i, col):
            got = cap_override.get(("exch", i))
            if got:
                return got
            base = max(est_rows // self.D * 4, self.cap_min)
            hot = col_mult.get(col)
            if hot is not None:
                base = max(base, min(int(hot), int(est_rows))
                           + est_rows // self.D * 2)
            return K.next_capacity(min(base, self.cap_max),
                                   self.cap_min, self.cap_max)

        patterns = q.pattern_group.patterns[
            q.pattern_step:(None if n_steps is None
                            else q.pattern_step + n_steps)]
        if seed is not None:
            seed_table, seed_v2c = seed
            v2c.update(seed_v2c)
            width = seed_table.shape[1]
            first = patterns[0]
            if first.subject < 0:
                anchor = v2c.get(first.subject, NO_RESULT)
            else:
                # index membership or c2k on a bound (seeded) object column
                anchor = v2c.get(first.object, NO_RESULT)
            assert_ec(anchor != NO_RESULT,
                      ErrorCode.UNSUPPORTED_SHAPE,
                      "seeded distributed chains must start from a pattern "
                      "anchored on a seeded column")
            est_rows = max(len(seed_table) // self.D, 1) * 2
            plan.steps.append(_Step(
                kind="init_rows", col=anchor, width=width,
                cap=self._seed_cap(seed_table, anchor)))
            aligned_col = anchor  # seed rows are sharded by the anchor owner
        for pat in patterns:
            i = len(plan.steps)  # step index (seeded chains prepend init_rows)
            s, p, d, o = pat.subject, pat.predicate, pat.direction, pat.object
            assert_ec(pat.pred_type == int(AttrType.SID_t),
                      ErrorCode.UNSUPPORTED_SHAPE,
                      "attr patterns are host-side in the distributed engine")
            if p < 0:
                # VERSATILE known_unknown_unknown (?x ?p ?y, x bound) and
                # known_unknown_const (?x ?p CONST): each shard expands
                # against its combined adjacency; a const object folds to an
                # equality filter inside the same program (beyond the
                # reference — its accelerator refuses every versatile
                # shape). A bound predicate or bound object stays host-side
                # (the CPU engine rejects those too).
                col = v2c.get(s, NO_RESULT) if s < 0 else NO_RESULT
                assert_ec(width > 0 and col != NO_RESULT and p not in v2c
                          and (o > 0 or o not in v2c),
                          ErrorCode.UNSUPPORTED_SHAPE,
                          "distributed versatile supports ?x ?p ?y / "
                          "?x ?p CONST with x bound and p fresh")
                exch_cap = 0
                if aligned_col != col:
                    exch_cap = exch_cap_for(i, col)
                vseg = self.sstore.versatile_segment(d)
                avg = vseg.avg_deg if vseg else 0.0
                est_rows = int(max(est_rows * max(avg, 0.1) * 2, 1))
                kind = "expand_versatile" if o < 0 else "expand_versatile_const"
                plan.steps.append(_Step(
                    kind=kind, pid=0, dir=d, col=col,
                    const=(o if o > 0 else 0),
                    cap=min(cap_for(i, est_rows), self.cap_max),
                    exch_cap=exch_cap, new_col=True))
                fwd_max = vseg.max_deg if vseg else 1
                for c in list(col_mult):
                    col_mult[c] = min(col_mult[c] * fwd_max, MULT_CAP)
                # the fresh columns' multiplicity bounds are unknown
                # (reverse combined degrees aren't tracked) — leave untracked
                v2c[p] = width
                width += 1
                if o < 0:
                    v2c[o] = width
                    width += 1
                aligned_col = col
                continue
            if i == 0 and seed is None and q.pattern_step == 0 \
                    and pat is patterns[0] and q.start_from_index():
                idx = self.sstore.index_list(s, d)
                est_rows = max(idx.total // self.D, 1) * 2
                step = _Step(kind="init_index", pid=s, dir=d,
                             cap=cap_for(i, est_rows))
                v2c[o] = 0
                width = 1
                col_mult[0] = 1  # index members are globally unique
                aligned_col = 0  # index lists are owner-local by construction
                plan.steps.append(step)
                continue
            if width > 0 and _is_index_pattern(pat):
                # mid-chain index membership (index_to_known,
                # sparql.hpp:138-163): keep rows whose bound object is in
                # the owner shard's local index list
                ocol = v2c.get(o, NO_RESULT)
                assert_ec(ocol != NO_RESULT, ErrorCode.VERTEX_INVALID,
                          "index pattern needs a bound object mid-chain")
                exch_cap = 0
                if aligned_col != ocol:
                    exch_cap = exch_cap_for(i, ocol)
                self.sstore.index_list(s, d)  # ensure staged
                plan.steps.append(_Step(
                    kind="member_index", pid=s, dir=d, col=ocol,
                    cap=cap_for(i, est_rows), exch_cap=exch_cap))
                aligned_col = ocol
                continue
            if width == 0:
                assert_ec(s > 0, ErrorCode.FIRST_PATTERN_ERROR)
                seg = self.sstore.segment(p, d)
                est_rows = int((seg.avg_deg if seg else 1) * 2)
                step = _Step(kind="init_const", pid=p, dir=d, const=s,
                             cap=cap_for(i, est_rows))
                v2c[o] = 0
                width = 1
                col_mult[0] = 1  # one const's neighbor list: unique values
                aligned_col = None  # rows sit on the const's owner, not col 0's
                plan.steps.append(step)
                continue

            if s > 0:
                # const_to_known mid-chain (sparql.hpp:138-163's c2k): the
                # membership "bound ?o in adj(const, p, d)" is exactly
                # "const in adj(?o, p, flip(d))" — a member step against the
                # reverse segment anchored on the bound object column
                ocol = v2c.get(o, NO_RESULT) if o < 0 else NO_RESULT
                assert_ec(ocol != NO_RESULT, ErrorCode.UNSUPPORTED_SHAPE,
                          "const subject mid-chain needs a bound object")
                fd = OUT if d == IN else IN
                exch_cap = 0
                if aligned_col != ocol:
                    exch_cap = exch_cap_for(i, ocol)
                self.sstore.segment(p, fd)  # ensure staged
                plan.steps.append(_Step(
                    kind="member", pid=p, dir=fd, col=ocol, vals_col=-1,
                    const=s, cap=cap_for(i, est_rows), exch_cap=exch_cap))
                aligned_col = ocol
                continue
            col = v2c.get(s, NO_RESULT)
            assert_ec(col != NO_RESULT, ErrorCode.UNSUPPORTED_SHAPE,
                      "distributed steps must anchor on a KNOWN subject")
            o_col = v2c.get(o, NO_RESULT) if o < 0 else NO_RESULT
            o_known = o < 0 and o_col != NO_RESULT

            type_all = (p == TYPE_ID and d == IN and o < 0 and not o_known)
            exch_cap = 0
            if not type_all and aligned_col != col:
                exch_cap = exch_cap_for(i, col)

            seg = self.sstore.segment(p, d)
            avg = seg.avg_deg if seg else 0.0
            if o < 0 and not o_known:  # expansion
                est_rows = int(max(est_rows * max(avg, 0.1) * 2, 1))
                kind = "expand_type_all" if type_all else "expand"
                step = _Step(kind=kind, pid=p, dir=d, col=col,
                             cap=min(cap_for(i, est_rows), self.cap_max),
                             exch_cap=exch_cap, new_col=True)
                if type_all:
                    col_mult.clear()  # allgather replication: bounds unknown
                else:
                    fwd_max = seg.max_deg if seg else 1
                    # host metadata only — staging the reverse segment to
                    # device for one scalar would waste HBM
                    rev_max = self.sstore.host_max_deg(p, OUT if d == IN else IN)
                    anchor_mult = col_mult.get(col)
                    for c in list(col_mult):
                        col_mult[c] = min(col_mult[c] * fwd_max, MULT_CAP)
                    if anchor_mult is not None:
                        col_mult[width] = min(anchor_mult * rev_max, MULT_CAP)
                v2c[o] = width
                width += 1
                aligned_col = width - 1 if type_all else col
            else:  # member filter
                step = _Step(kind="member", pid=p, dir=d, col=col,
                             vals_col=(o_col if o_known else -1),
                             const=(0 if o_known else o),
                             cap=cap_for(i, est_rows), exch_cap=exch_cap)
                aligned_col = col
            plan.steps.append(step)

        plan.width = width
        plan.v2c = v2c
        return plan

    # ------------------------------------------------------------------
    # compiled chain per plan signature
    # ------------------------------------------------------------------
    def _get_fn(self, plan: _Plan, seed=None, seed_cache: dict | None = None):
        # gather the device arrays each step needs (also the call args);
        # per-step (max_probe, max_deg_log2) join the cache key because the
        # compiled chain bakes them in as constants — a restaged segment
        # (dynamic insert) must never reuse a chain with smaller bounds
        bounds = []
        args = []
        for s in plan.steps:
            if s.kind == "init_rows":
                key = (s.col, s.cap)
                if seed_cache is None:
                    args.append(self._shard_seed(seed[0], s.col, s.cap))
                elif key not in seed_cache:
                    seed_cache[key] = self._shard_seed(seed[0], s.col, s.cap)
                    args.append(seed_cache[key])
                else:
                    args.append(seed_cache[key])
                bounds.append((0, 0))
            elif s.kind in ("init_index", "member_index"):
                idx = self.sstore.index_list(s.pid, s.dir)
                args.append((idx.edges, self._real_lens_arr(idx)))
                bounds.append((0, 0))
            elif s.kind in ("expand_versatile", "expand_versatile_const"):
                vseg = self.sstore.versatile_segment(s.dir)
                if vseg is None:
                    args.append(None)
                    bounds.append((0, 0))
                else:
                    args.append((vseg.bkey, vseg.bstart, vseg.bdeg,
                                 vseg.edges, vseg.edges2))
                    bounds.append((vseg.max_probe, vseg.max_deg_log2))
            else:
                seg = self.sstore.segment(s.pid, s.dir)
                if seg is None:
                    args.append(None)
                    bounds.append((0, 0))
                else:
                    args.append((seg.bkey, seg.bstart, seg.bdeg, seg.edges))
                    bounds.append((seg.max_probe, seg.max_deg_log2))
        sig = (plan.signature(), tuple(bounds))
        if sig in self._fn_cache:
            return self._fn_cache[sig], self._flatten_args(args)
        fn = self._compile(plan, args)
        self._fn_cache[sig] = fn
        return fn, self._flatten_args(args)

    def _real_lens_arr(self, idx):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(idx.real_lens.astype(np.int32).reshape(-1, 1),
                              NamedSharding(self.mesh, P(self.axis, None)))

    def _seed_cap(self, seed_table: np.ndarray, anchor: int) -> int:
        """Exact per-shard capacity for a seed table (host knows the counts)."""
        from wukong_tpu.utils.mathutil import hash_mod

        if len(seed_table) == 0:
            return self.cap_min
        dest = hash_mod(seed_table[:, anchor].astype(np.int32), self.D)
        peak = int(np.bincount(dest, minlength=self.D).max())
        return K.next_capacity(max(peak, 1), self.cap_min, self.cap_max)

    def _shard_seed(self, seed_table: np.ndarray, anchor: int, cap: int):
        """[N, W] host rows -> ([D, W, cap] int32 sharded, [D, 1] counts).

        Rows go to hash(anchor)%D — computed on the int32 view so host
        sharding matches the device-side `table[col] % D` exchange owner
        (BLANK_ID wraps to -1 on both sides)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        W = seed_table.shape[1]
        t32 = seed_table.astype(np.int32)  # ids < 2^31; BLANK wraps to -1
        from wukong_tpu.utils.mathutil import hash_mod

        dest = hash_mod(t32[:, anchor], self.D)
        out = np.zeros((self.D, W, cap), dtype=np.int32)
        counts = np.zeros((self.D, 1), dtype=np.int32)
        for d in range(self.D):
            rows = t32[dest == d]
            counts[d, 0] = len(rows)
            out[d, :, : len(rows)] = rows.T
        sharding = NamedSharding(self.mesh, P(self.axis, None, None))
        return (jax.device_put(out, sharding),
                jax.device_put(counts,
                               NamedSharding(self.mesh, P(self.axis, None))))

    @staticmethod
    def _flatten_args(args):
        flat = []
        for a in args:
            if a is not None:
                flat.extend(a)
        return flat

    def _compile(self, plan: _Plan, args_template):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # pre-0.5 JAX exposes it under experimental
            from jax.experimental.shard_map import shard_map

        D = self.D
        axis = self.axis
        steps = [s for s in plan.steps]
        # arg layout mirrors _flatten_args
        arg_specs = []
        for a in args_template:
            if a is not None:
                arg_specs.extend([P(axis, *([None] * (x.ndim - 1))) for x in a])

        probes = {}
        depths = {}
        for i, s in enumerate(steps):
            if s.kind in ("expand_versatile", "expand_versatile_const"):
                # the combined segment's OWN probe bound — segment(pid=0)
                # would resolve to nothing and silently bake max_probe=1,
                # truncating probes on any hash-skewed versatile table
                vseg = self.sstore.versatile_segment(s.dir)
                probes[i] = vseg.max_probe if vseg else 1
                depths[i] = vseg.max_deg_log2 if vseg else 1
            elif s.kind not in ("init_index", "init_rows", "member_index"):
                seg = self.sstore.segment(s.pid, s.dir)
                probes[i] = seg.max_probe if seg else 1
                depths[i] = seg.max_deg_log2 if seg else 1

        def shard_fn(*flat):
            # unflatten per-step args (squeeze the leading shard axis)
            per_step = []
            it = iter(flat)
            for a in args_template:
                if a is None:
                    per_step.append(None)
                else:
                    per_step.append(tuple(next(it)[0] for _ in a))

            table = None
            n = jnp.int32(0)
            totals = [jnp.int32(0)] * len(steps)
            exch_totals = [jnp.int32(0)] * len(steps)

            for i, s in enumerate(steps):
                if s.kind == "init_rows":
                    table, counts = per_step[i]
                    n = counts[0]
                    totals[i] = n
                    continue
                if s.kind == "init_index":
                    edges, lens = per_step[i]
                    table, n = K.init_from_list.__wrapped__(
                        edges, lens[0], s.cap)
                    totals[i] = lens[0]
                    continue
                if s.kind == "init_const":
                    arrs = per_step[i]
                    const_tab = jnp.full((1, 1), np.int32(s.const), jnp.int32)
                    if arrs is None:
                        table = jnp.zeros((1, s.cap), jnp.int32)
                        n = jnp.int32(0)
                        continue
                    bkey, bstart, bdeg, edges = arrs
                    table, n, tot = K.expand.__wrapped__(
                        const_tab, jnp.int32(1), bkey, bstart, bdeg, edges,
                        col=0, cap_out=s.cap, max_probe=probes[i])
                    table = table[1:, :]  # drop the const row ([W, C] layout)
                    totals[i] = tot
                    continue

                if s.exch_cap:
                    table, n, em, tot_recv = _exchange(
                        table, n, s.col, s.exch_cap, s.cap, D, axis)
                    exch_totals[i] = em
                    totals[i] = jnp.maximum(totals[i], tot_recv)

                if s.kind == "member_index":
                    edges_i, lens = per_step[i]
                    keep = K.member_mask_list.__wrapped__(
                        table, n, s.col, edges_i, lens[0])
                    table, n = K.compact.__wrapped__(table, keep)
                    continue

                arrs = per_step[i]
                if s.kind in ("expand_versatile", "expand_versatile_const"):
                    fold = s.kind == "expand_versatile_const"
                    if arrs is None:
                        table = jnp.concatenate(
                            [table,
                             jnp.zeros((1 if fold else 2, table.shape[1]),
                                       jnp.int32)],
                            axis=0)
                        n = jnp.int32(0)
                        continue
                    bkey, bstart, bdeg, edges, edges2 = arrs
                    table, n, tot = K.expand2.__wrapped__(
                        table, n, bkey, bstart, bdeg, edges2, edges,
                        col=s.col, cap_out=s.cap, max_probe=probes[i])
                    totals[i] = jnp.maximum(totals[i], tot)
                    if fold:
                        # known_unknown_const: keep value == const rows,
                        # drop the value row — the surviving table binds
                        # only the predicate column
                        keep = (jnp.arange(s.cap, dtype=jnp.int32) < n) \
                            & (table[-1] == jnp.int32(s.const))
                        table, n = K.compact.__wrapped__(table, keep)
                        table = table[:-1]
                elif s.kind in ("expand", "expand_type_all"):
                    if s.kind == "expand_type_all":
                        table, n = _allgather_rows(table, n, D, axis)
                    if arrs is None:
                        table = jnp.concatenate(
                            [table, jnp.zeros((1, table.shape[1]), jnp.int32)],
                            axis=0)
                        n = jnp.int32(0)
                        continue
                    bkey, bstart, bdeg, edges = arrs
                    table, n, tot = K.expand.__wrapped__(
                        table, n, bkey, bstart, bdeg, edges, col=s.col,
                        cap_out=s.cap, max_probe=probes[i])
                    totals[i] = jnp.maximum(totals[i], tot)
                elif s.kind == "member":
                    if arrs is None:
                        keep = jnp.zeros(table.shape[1], bool)
                    else:
                        bkey, bstart, bdeg, edges = arrs
                        if s.vals_col >= 0:
                            vals = table[s.vals_col]
                        else:
                            vals = jnp.full(table.shape[1], np.int32(s.const))
                        keep = K.member_mask_known.__wrapped__(
                            table, n, vals, bkey, bstart, bdeg, edges,
                            col=s.col, max_probe=probes[i], depth=depths[i])
                    table, n = K.compact.__wrapped__(table, keep)

            return {
                "table": table[None],
                "n": n[None],
                "totals": jnp.stack(totals + exch_totals)[None],
            }

        out_specs = {"table": P(axis), "n": P(axis), "totals": P(axis)}
        try:
            mapped = shard_map(shard_fn, mesh=self.mesh,
                               in_specs=tuple(arg_specs), out_specs=out_specs,
                               check_vma=False)
        except TypeError:  # pre-0.5 JAX names the replication check check_rep
            mapped = shard_map(shard_fn, mesh=self.mesh,
                               in_specs=tuple(arg_specs), out_specs=out_specs,
                               check_rep=False)
        return jax.jit(mapped)


def _gather_host(tree):
    """Bring chain outputs to host. Single-process: plain device_get.
    Multi-process (jax.distributed, the reference's mpiexec contract,
    wukong.cpp:102-104): outputs are sharded across processes and
    device_get would raise on non-addressable shards — every process
    allgathers instead, so all controllers see identical totals/tables
    and take identical retry/assembly decisions (SPMD discipline)."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(tree, tiled=True)
    return jax.device_get(tree)


def _is_index_pattern(pat) -> bool:
    """Type/predicate index pattern: tpid subject under rdf:type or
    __PREDICATE__ with a variable object."""
    from wukong_tpu.types import is_tpid

    return (pat.subject > 0 and is_tpid(pat.subject)
            and pat.predicate in (PREDICATE_ID, TYPE_ID) and pat.object < 0)


class _ShardedAttrGraph:
    """Attribute lookups routed to the subject owner's partition — the same
    hash_mod placement build_partition uses for attr segments."""

    def __init__(self, stores: list):
        self.stores = stores
        self.D = len(stores)

    def get_attr(self, vid: int, aid: int, d: int = OUT):
        from wukong_tpu.utils.mathutil import hash_mod

        return self.stores[int(hash_mod(int(vid), self.D))].get_attr(
            vid, aid, d)


# ---------------------------------------------------------------------------
# collective building blocks (inside shard_map)
# ---------------------------------------------------------------------------


def _exchange(table, n, col, exch_cap: int, cap_new: int, D: int, axis: str):
    """Repartition rows to hash owners of `col` — the fork-join replacement.

    table: [W, C]. Per-destination capacity-padded all_to_all: send buffer
    [D, W, exch_cap]; per-dest row counts ride along so receivers compact
    exactly. Returns (table [W, cap_new], n, max_dest_count, total_received).
    """
    import jax
    import jax.numpy as jnp

    W, C = table.shape
    rows = jnp.arange(C, dtype=jnp.int32)
    live = rows < n
    dest = jnp.where(live, table[col] % D, D)
    order = jnp.argsort(dest, stable=True)
    st = table[:, order]
    sd = dest[order]
    counts = jnp.bincount(dest, length=D + 1)[:D].astype(jnp.int32)
    cumx = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    within = rows - cumx[jnp.clip(sd, 0, D - 1)]
    slot = jnp.where((sd < D) & (within < exch_cap),
                     sd * exch_cap + within, D * exch_cap)
    send = jnp.zeros((W, D * exch_cap), jnp.int32).at[:, slot].set(
        st, mode="drop")
    send = send.reshape(W, D, exch_cap).transpose(1, 0, 2)  # [D, W, exch_cap]
    recv = jax.lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                              tiled=False)
    rcounts = jax.lax.all_to_all(counts.reshape(D, 1), axis, 0, 0,
                                 tiled=False).reshape(D)
    cumr = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(rcounts)[:-1].astype(jnp.int32)])
    flat = recv.transpose(1, 0, 2).reshape(W, D * exch_cap)
    r_in_blk = jnp.tile(jnp.arange(exch_cap, dtype=jnp.int32), D)
    blk = jnp.repeat(jnp.arange(D, dtype=jnp.int32), exch_cap)
    valid = r_in_blk < jnp.minimum(rcounts, exch_cap)[blk]
    pos = jnp.where(valid, cumr[blk] + r_in_blk, cap_new)
    out = jnp.zeros((W, cap_new), jnp.int32).at[:, pos].set(flat, mode="drop")
    tot_recv = rcounts.sum().astype(jnp.int32)
    new_n = jnp.minimum(tot_recv, cap_new)
    return out, new_n, counts.max(), tot_recv


def _allgather_rows(table, n, D: int, axis: str):
    """Replicate all live rows to every shard (dispatch-to-all for type steps).

    table: [W, C] -> [W, D*C]."""
    import jax
    import jax.numpy as jnp

    W, C = table.shape
    gat = jax.lax.all_gather(table, axis)  # [D, W, C]
    ns = jax.lax.all_gather(n, axis)  # [D]
    flat = gat.transpose(1, 0, 2).reshape(W, D * C)
    blk = jnp.repeat(jnp.arange(D, dtype=jnp.int32), C)
    r_in = jnp.tile(jnp.arange(C, dtype=jnp.int32), D)
    valid = r_in < ns[blk]
    cumn = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(ns)[:-1].astype(jnp.int32)])
    pos = jnp.where(valid, cumn[blk] + r_in, D * C)
    out = jnp.zeros((W, D * C), jnp.int32).at[:, pos].set(flat, mode="drop")
    return out, ns.sum().astype(jnp.int32)
