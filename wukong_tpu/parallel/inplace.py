"""Owner-routed in-place execution for small-table distributed queries.

Reference behavior matched here: light queries never pay fork-join — the
proxy routes a const-start query straight to the start vertex's owner server
(proxy.hpp:201-219) and the engine answers it in place, pulling remote
neighbor lists with one-sided RDMA reads whenever a step leaves the local
partition; only a step whose fetch outgrows `global_rdma_threshold` forks
(sparql.hpp:802-814 need_fork_join). That is why the reference answers
lights in microseconds *on a cluster*.

The TPU-native single-driver analogue: every partition's CSR already lives
in driver host memory, so the "one-sided read" is a direct owner-routed
array access. `InplaceEngine` walks the whole chain host-side with per-row
owner routing and ZERO collectives; `DistEngine._try_inplace` enters it for
chains whose live table stays under `Global.dist_inplace_rows` and aborts
back to the capacity-padded collective path (the fork-join analogue) the
moment a table outgrows the bound. Correctness relies on the partitioning
invariant (store/gstore.py:5-17): vertex v's owner holds v's FULL OUT and
IN adjacency for every predicate, its full type/predicate lists, and its
attributes — so routing each row's lookup to `owner_of_subject(anchor)`
always finds complete data.
"""

from __future__ import annotations

import numpy as np

from wukong_tpu.engine.cpu import CPUEngine
from wukong_tpu.store.gstore import owner_of_subject
from wukong_tpu.types import IN, OUT, PREDICATE_ID, TYPE_ID
from wukong_tpu.utils.mathutil import hash_mod


class FederatedGraph:
    """GStore-lookup facade over all partitions: scalar lookups route to the
    vid's owner; index lists concatenate the shards' owner-local lists into
    the global list (each index member appears on exactly one shard)."""

    def __init__(self, stores: list):
        self.stores = stores
        self.D = len(stores)
        self._index_memo: dict = {}

    def get_triples(self, vid: int, pid: int, d: int) -> np.ndarray:
        return self.stores[hash_mod(int(vid), self.D)].get_triples(
            vid, pid, d)

    def get_index(self, tpid: int, d: int) -> np.ndarray:
        key = (int(tpid), int(d))
        got = self._index_memo.get(key)
        if got is None:
            parts = [np.asarray(st.get_index(tpid, d), dtype=np.int64)
                     for st in self.stores]
            got = (np.concatenate(parts) if parts
                   else np.empty(0, dtype=np.int64))
            self._index_memo[key] = got
        return got

    def get_attr(self, vid: int, aid: int, d: int = OUT):
        return self.stores[hash_mod(int(vid), self.D)].get_attr(vid, aid, d)


class InplaceOverflow(Exception):
    """Live table outgrew dist_inplace_rows — retreat to the collective path."""


class InplaceEngine(CPUEngine):
    """CPUEngine whose three vectorized graph accessors route each row to its
    anchor vertex's owner partition. Per-(pid, dir) shard segments share one
    virtual edge space (shard k's offsets shifted by the edge counts of
    shards < k), so `(start, local)` pairs produced by `_neighbors_many`
    decode back to the owning shard inside `_gather_edges` with no copies."""

    def __init__(self, stores: list, str_server=None):
        super().__init__(FederatedGraph(stores), str_server)
        self._stores = stores
        self._D = len(stores)
        self._shard_segs: dict = {}

    def _segs(self, pid: int, d: int):
        key = (int(pid), int(d))
        got = self._shard_segs.get(key)
        if got is None:
            segs = []
            for st in self._stores:
                if pid == PREDICATE_ID:
                    segs.append(st.vp.get(int(d)))
                else:
                    segs.append(st.segments.get((int(pid), int(d))))
            bases = np.zeros(self._D + 1, dtype=np.int64)
            for k, sg in enumerate(segs):
                bases[k + 1] = bases[k] + (len(sg.edges)
                                           if sg is not None else 0)
            got = (segs, bases)
            self._shard_segs[key] = got
        return got

    # -- vectorized accessors, owner-routed ----------------------------
    def _neighbors_many(self, cur: np.ndarray, pid: int, d: int):
        if pid == TYPE_ID and d == IN:
            # type membership reads the GLOBAL type index (facade concat)
            return super()._neighbors_many(cur, pid, d)
        segs, bases = self._segs(pid, d)
        cur = np.asarray(cur)
        start = np.zeros(len(cur), dtype=np.int64)
        deg = np.zeros(len(cur), dtype=np.int64)
        owners = owner_of_subject(cur, self._D)
        for k in np.unique(owners):  # only shards that own frontier rows
            m = owners == k
            if segs[k] is not None:
                s, dg = segs[k].lookup_many(cur[m])
                start[m] = s + bases[k]
                deg[m] = dg
        return start, deg

    def _gather_edges(self, pid: int, d: int, cur, start, local) -> np.ndarray:
        if pid == TYPE_ID and d == IN:
            return super()._gather_edges(pid, d, cur, start, local)
        segs, bases = self._segs(pid, d)
        pos = np.asarray(start, dtype=np.int64) + np.asarray(local,
                                                            dtype=np.int64)
        out = np.empty(len(pos), dtype=np.int64)
        ks = np.searchsorted(bases, pos, side="right") - 1
        for k in np.unique(ks):  # only shards whose edge ranges are hit
            m = ks == k
            out[m] = np.asarray(segs[k].edges,
                                dtype=np.int64)[pos[m] - bases[k]]
        return out

    def _contains_many(self, cur, pid: int, d: int, vals) -> np.ndarray:
        if pid == TYPE_ID and d == IN:
            return super()._contains_many(cur, pid, d, vals)
        segs, _bases = self._segs(pid, d)
        cur = np.asarray(cur)
        vals = np.asarray(vals)
        ok = np.zeros(len(cur), dtype=bool)
        owners = owner_of_subject(cur, self._D)
        for k in np.unique(owners):
            m = owners == k
            if segs[k] is not None:
                ok[m] = segs[k].contains_pair(cur[m], vals[m])
        return ok

    def _segment(self, pid: int, d: int):
        raise AssertionError(
            "InplaceEngine must never take the single-partition segment "
            "path — a new CPUEngine kernel bypassed the routed accessors")
