"""Device mesh helpers.

The reference's cluster is `mpiexec -n N` + RDMA QP mesh (rdma_lib, run.sh);
ours is a jax.sharding.Mesh over ICI/DCN. One mesh axis ("x") carries the graph
partition dimension — the analogue of the server id (sid). Multi-host runs get
the same mesh from jax.distributed initialization; nothing else changes.
"""

from __future__ import annotations


def init_multihost(coordinator: str | None = None, num_processes: int | None = None,
                   process_id: int | None = None) -> None:
    """Multi-host bring-up (the reference's mpiexec rank assignment,
    scripts/run.sh + wukong.cpp:102-104): initialize jax.distributed so
    jax.devices() spans all hosts and make_mesh() lays the partition axis over
    ICI first, DCN across hosts. No-op when args are absent and the env lacks
    a coordinator (single-host)."""
    import os

    import jax

    if coordinator is None and "JAX_COORDINATOR_ADDRESS" not in os.environ \
            and "COORDINATOR_ADDRESS" not in os.environ:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id)


def make_mesh(n_shards: int | None = None, devices=None, axis: str = "x"):
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    if len(devices) < n_shards:
        raise ValueError(f"need {n_shards} devices, have {len(devices)}")
    import numpy as np

    return Mesh(np.array(devices[:n_shards]), (axis,))
