"""Device mesh helpers.

The reference's cluster is `mpiexec -n N` + RDMA QP mesh (rdma_lib, run.sh);
ours is a jax.sharding.Mesh over ICI/DCN. One mesh axis ("x") carries the graph
partition dimension — the analogue of the server id (sid). Multi-host runs get
the same mesh from jax.distributed initialization; nothing else changes.
"""

from __future__ import annotations


def make_mesh(n_shards: int | None = None, devices=None, axis: str = "x"):
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_shards is None:
        n_shards = len(devices)
    if len(devices) < n_shards:
        raise ValueError(f"need {n_shards} devices, have {len(devices)}")
    import numpy as np

    return Mesh(np.array(devices[:n_shards]), (axis,))
