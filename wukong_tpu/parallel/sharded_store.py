"""Sharded device store: per-partition hashed CSR segments stacked over a mesh.

Each worker partition (GStore) stages its segments exactly like the single-chip
DeviceStore, but all shards of a (pid, dir) segment share one bucket count,
probe bound, and edge padding so the stacked arrays [D, NB, 8] / [D, E_pad] are
SPMD-uniform; the leading axis is sharded over the mesh ("x"), so each device
holds exactly its partition — the device-memory analogue of the reference's
per-server gstore region (core/mem.hpp kvstore).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from wukong_tpu.engine.device_store import _next_pow2, build_hash_table
from wukong_tpu.types import IN, TYPE_ID

INT32_MAX = np.iinfo(np.int32).max


@dataclass
class StackedSegment:
    bkey: object  # [D, NB*8] sharded on axis 0 (flat buckets per shard)
    bstart: object
    bdeg: object
    edges: object  # [D, E_pad]
    max_probe: int
    max_deg_log2: int
    avg_deg: float  # global average degree (capacity estimation)
    max_deg: int = 1  # global max degree (skew-aware exchange capacities)
    # VERSATILE combined segments: aligned per-edge predicate ids [D, E_pad]
    edges2: object = None

    @property
    def nbytes(self) -> int:
        n = (self.bkey.size + self.bstart.size + self.bdeg.size
             + self.edges.size) * 4
        if self.edges2 is not None:
            n += self.edges2.size * 4
        return n


@dataclass
class StackedIndex:
    edges: object  # [D, L_pad] sharded on axis 0; pad INT32_MAX
    real_lens: np.ndarray  # [D] host-side true lengths
    total: int


class ShardedDeviceStore:
    def __init__(self, stores: list, mesh, axis: str = "x"):
        from wukong_tpu.runtime.resilience import CircuitBreaker

        self.stores = stores
        self.mesh = mesh
        self.axis = axis
        self.D = len(stores)
        assert self.D == mesh.devices.size, "one partition per mesh device"
        self._cache: dict = {}
        self._index_cache: dict = {}
        self.bytes_used = 0
        self._seen_version = self.version()
        # resilience: per-shard circuit breaker over host-side fetches, and
        # the set of shards whose data is currently missing from stagings
        # (the dist engine tags replies incomplete while it is non-empty)
        self.breaker = CircuitBreaker()
        self.degraded_shards: set[int] = set()

    def version(self) -> int:
        """Max dynamic-insert version across all partitions."""
        return max((getattr(g, "version", 0) for g in self.stores), default=0)

    def check_version(self) -> bool:
        """Drop stale stagings after dynamic inserts (mirrors the single-chip
        DeviceStore._check_version). Returns True when caches were invalidated
        so the engine can also drop compiled plans whose baked-in probe/depth
        bounds came from the old segments."""
        v = self.version()
        if v != self._seen_version:
            self._cache.clear()
            self._index_cache.clear()
            self.bytes_used = 0
            self._seen_version = v
            # stagings are gone, so no staged data is missing any shard;
            # the next staging re-evaluates shard health through the breaker
            self.degraded_shards.clear()
            return True
        return False

    def _fetch_shard(self, i: int, fn, what: str):
        """One shard's host-side fetch through the resilience layer: the
        ``dist.shard_fetch`` fault site, retry with backoff on transients,
        and the per-shard circuit breaker. Returns (value, ok); ok=False
        marks the shard degraded — the caller substitutes empty shard data
        so the compiled chain routes around the shard instead of crashing.
        A later successful fetch clears the degraded flag (recovery).

        Observability: when the executing query is traced, each fetch is a
        ``shard.fetch`` span on the ambient trace — retry attempts, breaker
        trips, and injected fault sites land on it as span events (the
        retry/breaker/fault hooks use the same ambient trace)."""
        from wukong_tpu.obs import trace as obs_trace

        tr = obs_trace.current()
        if tr is None:
            return self._fetch_shard_impl(i, fn, what)
        sp = tr.start_span("shard.fetch", shard=i, what=what)
        try:
            out, ok = self._fetch_shard_impl(i, fn, what)
        except BaseException:
            tr.end_span(sp, ok=False, raised=True)
            raise
        tr.end_span(sp, ok=ok)
        return out, ok

    def _fetch_shard_impl(self, i: int, fn, what: str):
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.resilience import retry_call
        from wukong_tpu.utils.errors import RetryExhausted, ShardUnavailable
        from wukong_tpu.utils.logger import log_warn

        def attempt():
            faults.site("dist.shard_fetch", shard=i)
            return fn()

        try:
            out = retry_call(attempt, site=f"dist.shard_fetch[{i}]",
                             retry_on=(faults.TransientFault,),
                             breaker=self.breaker, key=i)
        except faults.ShardDown as e:
            # persistent fault: not retryable — retry_call already counted
            # it toward the breaker, so repeated stagings trip it and stop
            # touching the shard
            log_warn(f"shard {i} down during {what} ({e}); substituting an "
                     "empty shard — results will be flagged incomplete")
            self._mark_degraded(i)
            return None, False
        except (ShardUnavailable, RetryExhausted) as e:
            log_warn(f"shard {i} unavailable during {what} "
                     f"({e.code.name}); substituting an empty shard — "
                     "results will be flagged incomplete")
            self._mark_degraded(i)
            return None, False
        self.degraded_shards.discard(i)
        return out, True

    def _mark_degraded(self, i: int) -> None:
        from wukong_tpu.obs.metrics import get_registry

        self.degraded_shards.add(i)
        get_registry().counter(
            "wukong_shard_fetch_degraded_total",
            "Shard fetches that substituted empty data",
            labels=("shard",)).labels(shard=i).inc()

    def _put(self, arr: np.ndarray):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.axis, *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------
    def segment(self, pid: int, d: int) -> StackedSegment | None:
        self.check_version()
        key = (int(pid), int(d))
        if key in self._cache:
            return self._cache[key]
        empty3 = (np.empty(0, np.int64), np.zeros(1, np.int64),
                  np.empty(0, np.int64))

        def fetch(g):
            if pid == TYPE_ID and int(d) == IN:
                return self._type_csr(g)
            host = g.segments.get(key)
            return ((host.keys, host.offsets, host.edges)
                    if host is not None else empty3)

        shards = []
        healthy = True
        for i, g in enumerate(self.stores):
            got, ok = self._fetch_shard(i, lambda g=g: fetch(g),
                                        f"segment({pid},{d})")
            healthy &= ok
            shards.append(got if ok else empty3)
        if all(len(k) == 0 for (k, _, _) in shards):
            if healthy:
                self._cache[key] = None
            return None
        # SPMD-uniform sizing across shards
        max_k = max(len(k) for (k, _, _) in shards)
        NB = max(_next_pow2((max_k + 3) // 4), 2)
        max_e = max(len(e) for (_, _, e) in shards)
        Ep = _next_pow2(max(max_e, 1))
        bkeys, bstarts, bdegs, edges_l = [], [], [], []
        max_probe = 1
        max_deg = 1
        tot_e = tot_k = 0
        for (k, o, e) in shards:
            bk, bs, bd, mp = build_hash_table(np.asarray(k), np.asarray(o),
                                              num_buckets=NB)
            # flat [NB*8] per shard (see tpu_kernels LAYOUT RULE)
            bkeys.append(bk.reshape(-1))
            bstarts.append(bs.reshape(-1))
            bdegs.append(bd.reshape(-1))
            max_probe = max(max_probe, mp)
            if len(k):
                max_deg = max(max_deg, int((o[1:] - o[:-1]).max()))
            tot_e += len(e)
            tot_k += len(k)
            ee = np.full(Ep, INT32_MAX, dtype=np.int32)
            ee[: len(e)] = e
            edges_l.append(ee)
        seg = StackedSegment(
            bkey=self._put(np.stack(bkeys)),
            bstart=self._put(np.stack(bstarts)),
            bdeg=self._put(np.stack(bdegs)),
            edges=self._put(np.stack(edges_l)),
            max_probe=max_probe,
            max_deg_log2=max(int(max_deg).bit_length(), 1),
            avg_deg=tot_e / max(tot_k, 1),
            max_deg=int(max_deg),
        )
        if healthy:
            # degraded stagings are never cached: the next query re-stages,
            # so a recovered shard's data reappears without a version bump
            self._cache[key] = seg
            self.bytes_used += seg.nbytes
        return seg

    def _type_csr(self, g):
        from wukong_tpu.engine.device_store import type_index_csr

        return type_index_csr(g)

    def versatile_segment(self, d: int) -> StackedSegment | None:
        """Per-shard COMBINED adjacency of direction d, stacked over the
        mesh: every (predicate, neighbor) pair keyed by vid (the device form
        of the VERSATILE vp lists — see DeviceStore.versatile_segment). The
        distributed expand_versatile step probes it and binds both the
        predicate and the neighbor column; the reference never accelerates
        any versatile shape (gpu_engine.hpp:267-333)."""
        self.check_version()
        key = ("vpv", int(d))
        if key in self._cache:
            return self._cache[key]
        from wukong_tpu.engine.device_store import combined_adjacency

        empty4 = (np.empty(0, np.int64), np.zeros(1, np.int64),
                  np.empty(0, np.int64), np.empty(0, np.int64))
        shards = []
        healthy = True
        for i, g in enumerate(self.stores):
            got, ok = self._fetch_shard(
                i, lambda g=g: combined_adjacency(g, d),
                f"versatile_segment({d})")
            healthy &= ok
            shards.append(got if ok else empty4)
        if all(len(k) == 0 for (k, _, _, _) in shards):
            if healthy:
                self._cache[key] = None
            return None
        max_k = max(len(k) for (k, _, _, _) in shards)
        NB = max(_next_pow2((max_k + 3) // 4), 2)
        Ep = _next_pow2(max(max(len(e) for (_, _, e, _) in shards), 1))
        bkeys, bstarts, bdegs, edges_l, pids_l = [], [], [], [], []
        max_probe = 1
        max_deg = 1
        tot_e = tot_k = 0
        for (k, o, e, p) in shards:
            bk, bs, bd, mp = build_hash_table(np.asarray(k), np.asarray(o),
                                              num_buckets=NB)
            bkeys.append(bk.reshape(-1))
            bstarts.append(bs.reshape(-1))
            bdegs.append(bd.reshape(-1))
            max_probe = max(max_probe, mp)
            if len(k):
                max_deg = max(max_deg, int((o[1:] - o[:-1]).max()))
            tot_e += len(e)
            tot_k += len(k)
            ee = np.full(Ep, INT32_MAX, dtype=np.int32)
            ee[: len(e)] = e
            edges_l.append(ee)
            pp = np.full(Ep, INT32_MAX, dtype=np.int32)
            pp[: len(p)] = p
            pids_l.append(pp)
        seg = StackedSegment(
            bkey=self._put(np.stack(bkeys)),
            bstart=self._put(np.stack(bstarts)),
            bdeg=self._put(np.stack(bdegs)),
            edges=self._put(np.stack(edges_l)),
            edges2=self._put(np.stack(pids_l)),
            max_probe=max_probe,
            max_deg_log2=max(int(max_deg).bit_length(), 1),
            avg_deg=tot_e / max(tot_k, 1),
            max_deg=int(max_deg),
        )
        if healthy:
            self._cache[key] = seg
            self.bytes_used += seg.nbytes
        return seg

    def host_max_deg(self, pid: int, d: int) -> int:
        """Global max degree of (pid, d) from host CSR metadata — no device
        staging (capacity estimation reads only this scalar)."""
        md = 0
        for g in self.stores:
            host = g.segments.get((int(pid), int(d)))
            if host is not None and len(host.offsets) > 1:
                md = max(md, int(np.diff(host.offsets).max()))
        return max(md, 1)

    # ------------------------------------------------------------------
    def index_list(self, tpid: int, d: int) -> StackedIndex:
        self.check_version()
        key = (int(tpid), int(d))
        if key in self._index_cache:
            return self._index_cache[key]
        lists = []
        healthy = True
        for i, g in enumerate(self.stores):
            got, ok = self._fetch_shard(
                i, lambda g=g: np.asarray(g.get_index(tpid, d),
                                          dtype=np.int32),
                f"index_list({tpid},{d})")
            healthy &= ok
            lists.append(got if ok else np.empty(0, np.int32))
        L = _next_pow2(max(max((len(x) for x in lists), default=1), 1))
        stacked = np.full((self.D, L), INT32_MAX, dtype=np.int32)
        for i, x in enumerate(lists):
            stacked[i, : len(x)] = x
        idx = StackedIndex(
            edges=self._put(stacked),
            real_lens=np.asarray([len(x) for x in lists], dtype=np.int64),
            total=int(sum(len(x) for x in lists)),
        )
        if healthy:
            self._index_cache[key] = idx
            self.bytes_used += stacked.nbytes
        return idx
