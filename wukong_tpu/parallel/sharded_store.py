"""Sharded device store: per-partition hashed CSR segments stacked over a mesh.

Each worker partition (GStore) stages its segments exactly like the single-chip
DeviceStore, but all shards of a (pid, dir) segment share one bucket count,
probe bound, and edge padding so the stacked arrays [D, NB, 8] / [D, E_pad] are
SPMD-uniform; the leading axis is sharded over the mesh ("x"), so each device
holds exactly its partition — the device-memory analogue of the reference's
per-server gstore region (core/mem.hpp kvstore).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.engine.device_store import _next_pow2, build_hash_table
from wukong_tpu.runtime.transport import make_transport, run_op

INT32_MAX = np.iinfo(np.int32).max

# the migration cutover lock guards the shard->host placement map, the
# read-rotation registry, and the stores[] swap — plain list/dict stores
# only, innermost by construction (breaker/staging work runs outside it)
declare_leaf("migration.cutover")


@dataclass
class StackedSegment:
    bkey: object  # [D, NB*8] sharded on axis 0 (flat buckets per shard)
    bstart: object
    bdeg: object
    edges: object  # [D, E_pad]
    max_probe: int
    max_deg_log2: int
    avg_deg: float  # global average degree (capacity estimation)
    max_deg: int = 1  # global max degree (skew-aware exchange capacities)
    # VERSATILE combined segments: aligned per-edge predicate ids [D, E_pad]
    edges2: object = None

    @property
    def nbytes(self) -> int:
        n = (self.bkey.size + self.bstart.size + self.bdeg.size
             + self.edges.size) * 4
        if self.edges2 is not None:
            n += self.edges2.size * 4
        return n


@dataclass
class StackedIndex:
    edges: object  # [D, L_pad] sharded on axis 0; pad INT32_MAX
    real_lens: np.ndarray  # [D] host-side true lengths
    total: int


def _exec_local(fn, g):
    """Run a fetch spec against a parent-local store (replica/rotation
    copies): declared ``(op, args)`` tuples through run_op, closures
    directly. Never touches the transport — these copies exist to answer
    when the remote side is gone."""
    if isinstance(fn, tuple):
        return run_op(fn[0], g, *fn[1])
    return fn(g)


class ShardedDeviceStore:
    def __init__(self, stores: list, mesh, axis: str = "x",
                 replication_factor: int | None = None):
        from wukong_tpu.config import Global
        from wukong_tpu.runtime.resilience import CircuitBreaker

        self.stores = stores  # lock-free: slot replacement (rebuild_shard) is a single atomic list-item store; readers see old or new, never torn
        self.mesh = mesh
        self.axis = axis
        self.D = len(stores)
        assert self.D == mesh.devices.size, "one partition per mesh device"
        # staging caches are lock-free by design: engines and the heal
        # watcher race dict get/set/clear, every one an atomic CPython op.
        # The worst interleaving re-stages a segment (idempotent, cached
        # value identical) — taking a lock here would serialize every
        # staged fetch behind the slowest staging
        self._cache: dict = {}  # lock-free: atomic dict ops; losers of a staging race overwrite with an identical value
        self._index_cache: dict = {}  # lock-free: atomic dict ops, same contract as _cache
        self.bytes_used = 0  # lock-free: advisory accounting (HBM budget report), drift is bounded by one staging
        self._seen_version = self.version()  # lock-free: single int store; a stale read just re-runs check_version
        # resilience: per-shard circuit breaker over host-side fetches, and
        # the set of shards whose data is currently missing from stagings
        # (the dist engine tags replies incomplete while it is non-empty)
        self.breaker = CircuitBreaker()
        self.degraded_shards: set[int] = set()  # lock-free: atomic set add/discard; a stale read only delays healing by one watcher sweep
        # fault tolerance: with replication_factor k > 1 each logical
        # shard's data is mirrored onto its k-1 successor hosts; a failed
        # primary fetch fails over to a replica instead of substituting an
        # empty shard, and failover_shards records primaries currently
        # served by replicas (the recovery manager's rebuild signal)
        k = (Global.replication_factor if replication_factor is None
             else replication_factor)
        self.replication_factor = max(1, min(int(k), self.D))
        # shard -> [(host, GStore)]
        self.replicas: dict[int, list] = {}  # lock-free: whole-dict replacement in refresh_replicas; readers iterate a snapshot reference
        self.failover_shards: set[int] = set()  # lock-free: atomic set ops, same contract as degraded_shards
        # journal-edge dedup for shard.failover/shard.degraded events:
        # dict.setdefault is the atomic test-and-set a plain `in` check
        # is not (two engine threads racing the first replica fetch must
        # not double-journal one outage episode); keys are
        # ("failover", shard, host) — per serving replica, so a
        # mid-episode hop to the next replica is its own edge — and
        # ("degraded", shard), swept by _rearm_events on recovery so the
        # NEXT episode re-emits
        self._event_noted: dict = {}  # lock-free: atomic dict setdefault/pop
        # elastic data plane (runtime/migration.py): shard -> serving host
        # (identity unless a migration moved it) and shard -> demoted
        # donor copies still serving rotated reads (replica-read rotation,
        # ROADMAP follow-up j — the plan's predicted-balance model)
        self._migration_lock = make_lock("migration.cutover")
        self.placement: dict[int, int] = {}  # lock-free: reads are atomic dict gets on the fetch path; writes publish under _migration_lock (cutover/rollback)
        self.rotation: dict[int, list] = {}  # lock-free: fetch-path reads see the old or new list, never torn; writes publish under _migration_lock
        self._rotation_rr: dict[int, int] = {}  # lock-free: racy int bumps only skew the read split by one turn
        # the data plane's remote boundary (runtime/transport.py): named
        # ops route primary fetches through it (loopback executes against
        # the local store — byte-for-byte the single-process behavior; the
        # socket transport sends them to worker processes). Replica and
        # rotation fetches stay parent-local by design: they exist to
        # answer when the remote side is GONE.
        self.transport = make_transport()  # lock-free: whole-reference swap by the supervisor; fetches read it once per attempt
        if self.replication_factor > 1:
            self.refresh_replicas()

    def host_of(self, i: int) -> int:
        """The host serving shard ``i``'s primary (identity until a
        migration moves it)."""
        return int(self.placement.get(int(i), int(i)))

    def refresh_replicas(self) -> None:
        """(Re)clone every shard's replicas from its current primary —
        called at construction and after a checkpoint restore (the old
        clones would otherwise mirror a dead store's state)."""
        from wukong_tpu.store.persist import clone_gstore

        self.replicas = {
            i: [((i + j) % self.D, clone_gstore(self.stores[i]))
                for j in range(1, self.replication_factor)]
            for i in range(self.D)}
        if self.rotation:
            # read-rotation copies (demoted migration donors) mirror the
            # restored primaries too, keeping their hosts. Clones are
            # built OUTSIDE the cutover lock (it guards plain dict/list
            # publications only — a concurrent cutover must never stall
            # behind a deep copy), then published in one swap
            with self._migration_lock:
                snap = {i: [h for (h, _g) in rots]
                        for i, rots in self.rotation.items()}
            rebuilt = {i: [(h, clone_gstore(self.stores[i]))
                           for h in hosts]
                       for i, hosts in snap.items()}
            with self._migration_lock:
                self.rotation = rebuilt

    def invalidate_stagings(self) -> None:
        """Drop every staged segment so the next query re-fetches from the
        host partitions (the kill-and-recover drill's model of losing a
        host: its staged device data dies with it)."""
        self._cache.clear()
        self._index_cache.clear()
        self.bytes_used = 0

    def replica_stores(self) -> list:
        """Every replica GStore plus every read-rotation copy (mutation
        fan-out targets: an insert that reaches a primary must reach its
        mirrors, or failover/rotated reads would serve stale data)."""
        return ([rg for reps in self.replicas.values() for (_h, rg) in reps]
                + [rg for rots in self.rotation.values()
                   for (_h, rg) in rots])

    def rebuild_shard(self, i: int, store=None, source: str = "replica"
                      ) -> bool:
        """Promote a rebuilt partition as shard ``i``'s primary: install
        it, close the breaker, clear the degradation flags, and drop
        stagings so the next query fetches from the healed primary. With
        no explicit ``store`` the first surviving replica is cloned.
        Returns False when there is nothing to rebuild from."""
        from wukong_tpu.obs.metrics import get_registry
        from wukong_tpu.obs.trace import trace_event
        from wukong_tpu.store.persist import clone_gstore

        if store is None:
            reps = self.replicas.get(int(i))
            if not reps:
                return False
            store = clone_gstore(reps[0][1])
        self.stores[int(i)] = store
        self.breaker.record_success(int(i))  # promote: close the breaker
        self.degraded_shards.discard(int(i))
        self.failover_shards.discard(int(i))
        self._rearm_events(int(i))
        self.invalidate_stagings()
        trace_event("shard.rebuild", shard=int(i), source=source)
        from wukong_tpu.obs.events import emit_event
        from wukong_tpu.obs.placement import get_lineage

        emit_event("shard.rebuild", shard=int(i), source=source)
        get_lineage().note_heal(int(i), source=source)
        get_registry().counter(
            "wukong_recovery_rebuilds_total",
            "Failed shards rebuilt and promoted",
            labels=("shard", "source")).labels(shard=int(i),
                                               source=source).inc()
        return True

    def cutover_shard(self, i: int, store, host: int,
                      rotate: bool = False) -> None:
        """Migration read-path cutover (runtime/migration.py, called with
        the WAL mutation lock held so no batch commit straddles the swap):
        install ``store`` as shard ``i``'s primary served from ``host``.
        With ``rotate`` the displaced copy is demoted to a read-rotation
        replica on its old host — reads split across both copies, the
        MigrationPlan's predicted-balance model. Then the failover/rebuild
        promotion mechanics: breaker closed, degradation flags cleared,
        stagings dropped so the next query fetches the new primary."""
        # guarded by: _migration_lock — the swap, placement update, and
        # rotation demotion are one atomic publication to the read path
        i = int(i)
        with self._migration_lock:
            old = self.stores[i]
            old_host = self.placement.get(i, i)
            self.stores[i] = store
            self.placement[i] = int(host)
            if rotate and old is not store:
                # APPEND: a re-migrated shard keeps its earlier rotation
                # copies serving — the advisor's predicted-balance model
                # grows the serving set k -> k+1, and the executed split
                # must match what it scored
                self.rotation[i] = (list(self.rotation.get(i, ()))
                                    + [(int(old_host), old)])
        self.breaker.record_success(i)
        self.degraded_shards.discard(i)
        self.failover_shards.discard(i)
        self._rearm_events(i)
        self.invalidate_stagings()

    def rollback_cutover(self, i: int, donor_store, donor_host) -> None:
        """Migration abort after a published cutover: swap the donor back
        as primary on its old host and drop the rotation demotion (called
        with the WAL mutation lock held, like the cutover itself)."""
        # guarded by: _migration_lock — the rollback is the same atomic
        # read-path publication as the cutover it undoes
        i = int(i)
        with self._migration_lock:
            self.stores[i] = donor_store
            self.placement[i] = int(donor_host if donor_host is not None
                                    else i)
            # drop only the entry the cutover demoted (the donor now
            # reinstated as primary) — earlier migrations' rotation
            # copies keep serving
            rots = [(h, g) for (h, g) in self.rotation.get(i, ())
                    if g is not donor_store]
            if rots:
                self.rotation[i] = rots
            else:
                self.rotation.pop(i, None)
        self.breaker.record_success(i)
        self.invalidate_stagings()

    def version(self) -> int:
        """Max dynamic-insert version across all partitions."""
        return max((getattr(g, "version", 0) for g in self.stores), default=0)

    def check_version(self) -> bool:
        """Drop stale stagings after dynamic inserts (mirrors the single-chip
        DeviceStore._check_version). Returns True when caches were invalidated
        so the engine can also drop compiled plans whose baked-in probe/depth
        bounds came from the old segments."""
        v = self.version()
        if v != self._seen_version:
            self._cache.clear()
            self._index_cache.clear()
            self.bytes_used = 0
            self._seen_version = v
            # stagings are gone, so no staged data is missing any shard;
            # the next staging re-evaluates shard health through the breaker
            # (failover_shards persists — it tracks the primary's health for
            # the recovery manager, not this staging's completeness)
            self.degraded_shards.clear()
            # list() first: setdefault from concurrent fetch threads would
            # otherwise race this iteration into a RuntimeError
            for k in list(self._event_noted):
                if k[0] == "degraded":
                    self._event_noted.pop(k, None)
            return True
        return False

    def _fetch_shard(self, i: int, fn, what: str):
        """One shard's host-side fetch through the resilience layer: the
        ``dist.shard_fetch`` fault site, retry with backoff on transients,
        the per-shard circuit breaker, and — with replication on — failover
        to the shard's successor-host replicas. ``fn`` is either a declared
        transport op as an ``(op, args)`` tuple — the staging paths; the
        primary fetch routes it through ``self.transport``, so in socket
        mode it executes in the shard's worker process — or a plain
        closure ``fn(store)`` (probe/drill paths; always parent-local,
        closures cannot cross a process boundary). The primary is tried
        first, then each replica. Returns
        (value, ok); ok=False means primary AND replicas all failed — the
        caller substitutes empty shard data so the compiled chain routes
        around the shard instead of crashing. A later successful primary
        fetch clears the degraded/failover flags (recovery).

        Observability: when the executing query is traced, each fetch is a
        ``shard.fetch`` span on the ambient trace — retry attempts, breaker
        trips, failovers, and injected fault sites land on it as span
        events (the retry/breaker/fault hooks use the same ambient trace)."""
        from wukong_tpu.obs import trace as obs_trace

        tr = obs_trace.current()
        if tr is None:
            return self._fetch_shard_impl(i, fn, what)
        sp = tr.start_span("shard.fetch", shard=i, what=what)
        try:
            out, ok = self._fetch_shard_impl(i, fn, what)
        except BaseException:
            tr.end_span(sp, ok=False, raised=True)
            raise
        tr.end_span(sp, ok=ok)
        return out, ok

    def _fetch_shard_impl(self, i: int, fn, what: str):
        from wukong_tpu.obs.heat import maybe_charge
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.resilience import retry_call
        from wukong_tpu.utils.errors import RetryExhausted, ShardUnavailable
        from wukong_tpu.utils.logger import log_warn
        from wukong_tpu.utils.timer import get_usec

        def attempt():
            faults.site("dist.shard_fetch", shard=i)
            if isinstance(fn, tuple):
                op, args = fn
                return self.transport.fetch(i, self.stores[i], op, args)
            return fn(self.stores[i])

        # heat accounting (obs/heat.py): every fetch outcome charges this
        # shard's counters — fetch kind, payload rows/bytes, wall latency —
        # the access-heat histogram ROADMAP item 3's migration decisions
        # start from. One charge per staging, on the slow host path.
        t0 = get_usec()
        rots = self.rotation.get(i)
        if rots:
            # migrated shard with a demoted donor copy: rotate reads
            # across the serving copies (replica-read rotation) — the
            # executed form of the MigrationPlan's predicted balance. A
            # failed rotation read falls through to the primary path.
            got = self._fetch_rotation(i, rots, fn)
            if got is not None:
                maybe_charge(i, "rotation", got[0], get_usec() - t0)
                return got[0], True
        try:
            out = retry_call(attempt, site=f"dist.shard_fetch[{i}]",
                             retry_on=(faults.TransientFault,),
                             breaker=self.breaker, key=i)
        except (faults.ShardDown, ShardUnavailable, RetryExhausted) as e:
            # the primary is gone for this staging (persistent fault, open
            # breaker, or exhausted retries — retry_call already counted
            # the failure toward the breaker, so repeated stagings trip it
            # and stop touching the shard). With replication, fail over.
            got = self._fetch_failover(i, fn, what)
            if got is not None:
                maybe_charge(i, "failover", got[0], get_usec() - t0)
                return got[0], True
            code = e.code.name if isinstance(e, (ShardUnavailable,
                                                 RetryExhausted)) else str(e)
            log_warn(f"shard {i} unavailable during {what} ({code}) and no "
                     "replica answered; substituting an empty shard — "
                     "results will be flagged incomplete")
            self._mark_degraded(i)
            maybe_charge(i, "degraded", None, get_usec() - t0)
            return None, False
        was_down = i in self.degraded_shards or i in self.failover_shards
        self.degraded_shards.discard(i)
        self.failover_shards.discard(i)
        # recovered: re-arm THIS shard's journal edges for the next
        # episode. Gated on the shard actually having been down — while
        # some other shard's episode holds claims, healthy shards' fetches
        # must stay a set-membership test, not a per-fetch dict scan. A
        # claim minted between the was_down read and the discard is swept
        # by the next successful fetch (the claimant adds to the set
        # right after claiming), so no edge is lost, only deferred.
        if was_down and self._event_noted:
            self._rearm_events(i)
        maybe_charge(i, "primary", out, get_usec() - t0)
        return out, True

    def _fetch_rotation(self, i: int, rots: list, fn):
        """One rotated read: every (1 + len(rots))'th turn belongs to the
        primary (returns None — the caller proceeds down the primary
        path), the rest to a demoted-donor copy via the replica fetch
        machinery (its own ``replica.fetch`` fault site + per-(shard,host)
        breaker key). Returns (value,) on success, None to fall through."""
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.resilience import retry_call
        from wukong_tpu.utils.errors import RetryExhausted, ShardUnavailable
        from wukong_tpu.utils.logger import log_warn

        n = len(rots) + 1
        c = self._rotation_rr.get(i, 0)
        self._rotation_rr[i] = c + 1
        turn = c % n
        if turn == 0:
            return None  # the primary's turn in the rotation
        host, rg = rots[turn - 1]

        def attempt(rg=rg, host=host):
            faults.site("replica.fetch", shard=host)
            return _exec_local(fn, rg)

        try:
            out = retry_call(attempt, site=f"rotation.fetch[{i}@{host}]",
                             retry_on=(faults.TransientFault,),
                             breaker=self.breaker, key=(i, host))
        except (faults.ShardDown, ShardUnavailable, RetryExhausted) as e:
            log_warn(f"rotation copy {i}@{host} unavailable "
                     f"({e!r:.80}); serving from the primary")
            return None
        return (out,)

    def _fetch_failover(self, i: int, fn, what: str):
        """Try shard ``i``'s replicas in successor order; returns (value,)
        on the first success (the 1-tuple distinguishes a successful None
        fetch from exhaustion), or None when every replica failed too.
        Replica fetches get their own ``replica.fetch`` fault site and
        their own breaker keys, so a sick replica host is routed around
        independently of its primary."""
        from wukong_tpu.obs.metrics import get_registry
        from wukong_tpu.obs.trace import trace_event
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.resilience import retry_call
        from wukong_tpu.utils.errors import RetryExhausted, ShardUnavailable
        from wukong_tpu.utils.logger import log_warn

        for host, rg in self.replicas.get(i, []):
            def attempt(rg=rg, host=host):
                faults.site("replica.fetch", shard=host)
                return _exec_local(fn, rg)

            try:
                out = retry_call(attempt, site=f"replica.fetch[{i}->{host}]",
                                 retry_on=(faults.TransientFault,),
                                 breaker=self.breaker, key=(i, host))
            except (faults.ShardDown, ShardUnavailable, RetryExhausted) as e:
                log_warn(f"replica {i}->{host} unavailable during {what} "
                         f"({e!r:.80}); trying the next replica")
                continue
            # journal the failover on the state EDGE only (the first
            # fetch served by THIS replica, not every staging while the
            # primary stays down — a dead primary under load would churn
            # the bounded ring past the very timeline it preserves);
            # setdefault-with-sentinel is the atomic claim. The claim is
            # per (shard, host): a mid-episode hop to the next replica is
            # its own edge — without it the timeline (and the lineage's
            # failover_host) would keep naming the dead first replica
            tok = object()
            first = self._event_noted.setdefault(("failover", i, host),
                                                 tok) is tok
            self.failover_shards.add(i)
            self.degraded_shards.discard(i)
            self._event_noted.pop(("degraded", i), None)
            trace_event("shard.failover", shard=i, replica=host)
            if first:
                from wukong_tpu.obs.events import emit_event
                from wukong_tpu.obs.placement import get_lineage

                emit_event("shard.failover", shard=i, replica=host,
                           what=what)
                get_lineage().note_failover(i, host)
            get_registry().counter(
                "wukong_failover_total",
                "Shard fetches served by a replica after a primary failure",
                labels=("shard",)).labels(shard=i).inc()
            return (out,)
        return None

    def _rearm_events(self, i: int) -> None:
        """Drop every journal-edge claim for shard ``i`` (failover claims
        are per (shard, host), degraded per shard) so the next outage
        episode journals afresh. list() first: concurrent fetch-thread
        setdefault would race a live iteration into RuntimeError."""
        for k in list(self._event_noted):
            if k[1] == i:
                self._event_noted.pop(k, None)

    def _mark_degraded(self, i: int) -> None:
        from wukong_tpu.obs.events import emit_event
        from wukong_tpu.obs.metrics import get_registry

        # journal on the state edge only (see _fetch_failover)
        tok = object()
        first = self._event_noted.setdefault(("degraded", i), tok) is tok
        self.degraded_shards.add(i)
        if first:
            emit_event("shard.degraded", shard=i)
        get_registry().counter(
            "wukong_shard_fetch_degraded_total",
            "Shard fetches that substituted empty data",
            labels=("shard",)).labels(shard=i).inc()

    def _put(self, arr: np.ndarray):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(self.axis, *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    # ------------------------------------------------------------------
    def segment(self, pid: int, d: int) -> StackedSegment | None:
        self.check_version()
        key = (int(pid), int(d))
        if key in self._cache:
            return self._cache[key]
        empty3 = (np.empty(0, np.int64), np.zeros(1, np.int64),
                  np.empty(0, np.int64))
        shards = []
        healthy = True
        for i in range(self.D):
            got, ok = self._fetch_shard(i, ("segment", key),
                                        f"segment({pid},{d})")
            healthy &= ok
            shards.append(got if ok else empty3)
        if all(len(k) == 0 for (k, _, _) in shards):
            if healthy:
                self._cache[key] = None
            return None
        # SPMD-uniform sizing across shards
        max_k = max(len(k) for (k, _, _) in shards)
        NB = max(_next_pow2((max_k + 3) // 4), 2)
        max_e = max(len(e) for (_, _, e) in shards)
        Ep = _next_pow2(max(max_e, 1))
        bkeys, bstarts, bdegs, edges_l = [], [], [], []
        max_probe = 1
        max_deg = 1
        tot_e = tot_k = 0
        for (k, o, e) in shards:
            bk, bs, bd, mp = build_hash_table(np.asarray(k), np.asarray(o),
                                              num_buckets=NB)
            # flat [NB*8] per shard (see tpu_kernels LAYOUT RULE)
            bkeys.append(bk.reshape(-1))
            bstarts.append(bs.reshape(-1))
            bdegs.append(bd.reshape(-1))
            max_probe = max(max_probe, mp)
            if len(k):
                max_deg = max(max_deg, int((o[1:] - o[:-1]).max()))
            tot_e += len(e)
            tot_k += len(k)
            ee = np.full(Ep, INT32_MAX, dtype=np.int32)
            ee[: len(e)] = e
            edges_l.append(ee)
        seg = StackedSegment(
            bkey=self._put(np.stack(bkeys)),
            bstart=self._put(np.stack(bstarts)),
            bdeg=self._put(np.stack(bdegs)),
            edges=self._put(np.stack(edges_l)),
            max_probe=max_probe,
            max_deg_log2=max(int(max_deg).bit_length(), 1),
            avg_deg=tot_e / max(tot_k, 1),
            max_deg=int(max_deg),
        )
        if healthy:
            # degraded stagings are never cached: the next query re-stages,
            # so a recovered shard's data reappears without a version bump
            self._cache[key] = seg
            self.bytes_used += seg.nbytes
        return seg

    def versatile_segment(self, d: int) -> StackedSegment | None:
        """Per-shard COMBINED adjacency of direction d, stacked over the
        mesh: every (predicate, neighbor) pair keyed by vid (the device form
        of the VERSATILE vp lists — see DeviceStore.versatile_segment). The
        distributed expand_versatile step probes it and binds both the
        predicate and the neighbor column; the reference never accelerates
        any versatile shape (gpu_engine.hpp:267-333)."""
        self.check_version()
        key = ("vpv", int(d))
        if key in self._cache:
            return self._cache[key]
        empty4 = (np.empty(0, np.int64), np.zeros(1, np.int64),
                  np.empty(0, np.int64), np.empty(0, np.int64))
        shards = []
        healthy = True
        for i in range(self.D):
            got, ok = self._fetch_shard(
                i, ("versatile", (int(d),)),
                f"versatile_segment({d})")
            healthy &= ok
            shards.append(got if ok else empty4)
        if all(len(k) == 0 for (k, _, _, _) in shards):
            if healthy:
                self._cache[key] = None
            return None
        max_k = max(len(k) for (k, _, _, _) in shards)
        NB = max(_next_pow2((max_k + 3) // 4), 2)
        Ep = _next_pow2(max(max(len(e) for (_, _, e, _) in shards), 1))
        bkeys, bstarts, bdegs, edges_l, pids_l = [], [], [], [], []
        max_probe = 1
        max_deg = 1
        tot_e = tot_k = 0
        for (k, o, e, p) in shards:
            bk, bs, bd, mp = build_hash_table(np.asarray(k), np.asarray(o),
                                              num_buckets=NB)
            bkeys.append(bk.reshape(-1))
            bstarts.append(bs.reshape(-1))
            bdegs.append(bd.reshape(-1))
            max_probe = max(max_probe, mp)
            if len(k):
                max_deg = max(max_deg, int((o[1:] - o[:-1]).max()))
            tot_e += len(e)
            tot_k += len(k)
            ee = np.full(Ep, INT32_MAX, dtype=np.int32)
            ee[: len(e)] = e
            edges_l.append(ee)
            pp = np.full(Ep, INT32_MAX, dtype=np.int32)
            pp[: len(p)] = p
            pids_l.append(pp)
        seg = StackedSegment(
            bkey=self._put(np.stack(bkeys)),
            bstart=self._put(np.stack(bstarts)),
            bdeg=self._put(np.stack(bdegs)),
            edges=self._put(np.stack(edges_l)),
            edges2=self._put(np.stack(pids_l)),
            max_probe=max_probe,
            max_deg_log2=max(int(max_deg).bit_length(), 1),
            avg_deg=tot_e / max(tot_k, 1),
            max_deg=int(max_deg),
        )
        if healthy:
            self._cache[key] = seg
            self.bytes_used += seg.nbytes
        return seg

    def host_max_deg(self, pid: int, d: int) -> int:
        """Global max degree of (pid, d) from host CSR metadata — no device
        staging (capacity estimation reads only this scalar)."""
        md = 0
        for g in self.stores:
            host = g.segments.get((int(pid), int(d)))
            if host is not None and len(host.offsets) > 1:
                md = max(md, int(np.diff(host.offsets).max()))
        return max(md, 1)

    # ------------------------------------------------------------------
    def index_list(self, tpid: int, d: int) -> StackedIndex:
        self.check_version()
        key = (int(tpid), int(d))
        if key in self._index_cache:
            return self._index_cache[key]
        lists = []
        healthy = True
        for i in range(self.D):
            got, ok = self._fetch_shard(
                i, ("index", (int(tpid), int(d))),
                f"index_list({tpid},{d})")
            healthy &= ok
            lists.append(got if ok else np.empty(0, np.int32))
        L = _next_pow2(max(max((len(x) for x in lists), default=1), 1))
        stacked = np.full((self.D, L), INT32_MAX, dtype=np.int32)
        for i, x in enumerate(lists):
            stacked[i, : len(x)] = x
        idx = StackedIndex(
            edges=self._put(stacked),
            real_lens=np.asarray([len(x) for x in lists], dtype=np.int64),
            total=int(sum(len(x) for x in lists)),
        )
        if healthy:
            self._index_cache[key] = idx
            self.bytes_used += stacked.nbytes
        return idx
