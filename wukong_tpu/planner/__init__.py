from wukong_tpu.planner.plan_file import set_plan  # noqa: F401
from wukong_tpu.planner.heuristic import heuristic_plan  # noqa: F401
