"""Greedy fallback planner (used until/unless the cost-based optimizer runs).

Produces a valid execution plan: orders patterns so every step starts from a
CONST or KNOWN endpoint, orienting directions (and rewriting the first pattern
to a const/type-index/predicate-index start) the same way the reference's plans
do. This replaces nothing in the reference (its planner is cost-based,
core/planner.hpp); the full type-centric optimizer lives in
wukong_tpu.planner.optimizer and falls back here when stats are unavailable.
"""

from __future__ import annotations

from wukong_tpu.sparql.ir import Pattern, PatternGroup, SPARQLQuery
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, PREDICATE_ID, TYPE_ID, is_tpid
from wukong_tpu.utils.errors import ErrorCode, WukongError


def bound_vars(pg: PatternGroup) -> set:
    """Variables bound once a group's patterns have executed."""
    return {v for p in pg.patterns
            for v in (p.subject, p.predicate, p.object) if v < 0}


def plan_seeded_group(pg: PatternGroup, seed_known: set) -> bool:
    """Plan a UNION branch against inherited bindings (inherit_union,
    query.hpp:702-711). True if the branch anchors on a seeded var in
    subject/object position (planned in place, starting from that binding
    instead of a whole-graph index scan); False for disjoint branches —
    the caller plans those independently. THE single anchorability test:
    predicate-position sharing alone never anchors a chain."""
    anchored = any((p.subject < 0 and p.subject in seed_known)
                   or (p.object < 0 and p.object in seed_known)
                   for p in pg.patterns)
    if anchored:
        _plan_group(pg, seed_known=seed_known)
    return anchored


def heuristic_plan(q: SPARQLQuery) -> None:
    _plan_group(q.pattern_group)
    parent_bound = bound_vars(q.pattern_group)
    for u in q.pattern_group.unions:
        if not plan_seeded_group(u, parent_bound):
            _plan_group(u)
    # OPTIONAL groups are reordered at execution time against the bound result
    # (query.hpp reorder_optional_patterns), not planned here.


def _plan_group(pg: PatternGroup, seed_known: set | None = None) -> None:
    if not pg.patterns:
        return
    remaining = list(pg.patterns)
    planned: list[Pattern] = []
    known: set[int] = set(seed_known or ())

    def bindable(p: Pattern):
        """Orientation score for executing p next; higher is better.

        Mid-plan steps must be anchored on a KNOWN variable (const starts are
        only legal as the first pattern — const_to_unknown/const_unknown_*
        assert an empty table, sparql.hpp:246/717). Valid mid-plan shapes:
        k2k/k2c/c2k (filters, score 3), k2u / known_unknown_* (score 1).
        """
        s_var_known = p.subject < 0 and p.subject in known
        o_var_known = p.object < 0 and p.object in known
        if not (s_var_known or o_var_known):
            return None
        if p.pred_type != 0:  # attr patterns last: they decorate, never prune
            return 0
        s_bound = p.subject > 0 or s_var_known
        o_bound = p.object > 0 or o_var_known
        return 3 if (s_bound and o_bound) else 1

    # choose the start pattern: const start > type pattern > predicate index
    if known and any(bindable(p) is not None for p in remaining):
        # a seeded group (UNION branch) anchors on an inherited binding;
        # no start pattern needed — the greedy loop below orders everything
        first = None
    else:
        first = None
        for p in remaining:
            if (0 < p.subject and not is_tpid(p.subject)) or \
               (0 < p.object and not is_tpid(p.object)
                    and p.object >= NORMAL_ID_START):
                first = p
                break
        if first is None:
            # type-index start on a type pattern, else predicate-index start
            tpat = next((p for p in remaining
                         if p.predicate == TYPE_ID and is_tpid(p.object)),
                        None)
            if tpat is not None:
                remaining.remove(tpat)
                planned.append(Pattern(tpat.object, TYPE_ID, IN, tpat.subject))
            else:
                p0 = next((p for p in remaining if p.predicate > 1), None)
                if p0 is None:
                    raise WukongError(ErrorCode.UNKNOWN_PLAN,
                                      "no plannable start pattern")
                # predicate-index start: bind the subject side, keep the
                # pattern
                planned.append(
                    Pattern(p0.predicate, PREDICATE_ID, IN, p0.subject))
    if first is not None:
        remaining.remove(first)
        if first.subject > 0 and first.subject >= NORMAL_ID_START:
            planned.append(Pattern(first.subject, first.predicate, OUT,
                                   first.object, first.pred_type))
        else:  # const object: flip
            planned.append(Pattern(first.object, first.predicate, IN,
                                   first.subject, first.pred_type))
    for p in planned:
        _note_known(p, known)

    while remaining:
        best, best_score = None, -1
        for p in remaining:
            sc = bindable(p)
            if sc is not None and sc > best_score:
                best, best_score = p, sc
        if best is None:
            raise WukongError(ErrorCode.UNKNOWN_PLAN,
                              "disconnected pattern group")
        remaining.remove(best)
        # anchor on a KNOWN var side: prefer subject if it's a known var,
        # else a const subject with known object stays as written
        # (const_to_known). Variable-predicate patterns have no const-anchored
        # kernel mid-plan (no [CONST|UNKNOWN|KNOWN] kernel, sparql.hpp:981-983),
        # so they must anchor on the known VARIABLE side.
        s_var_known = best.subject < 0 and best.subject in known
        pred_is_var = best.predicate < 0
        s_const_ok = best.subject > 0 and not pred_is_var
        if s_var_known or s_const_ok:
            oriented = Pattern(best.subject, best.predicate, OUT, best.object,
                               best.pred_type)
        else:
            oriented = Pattern(best.object, best.predicate, IN, best.subject,
                               best.pred_type)
        planned.append(oriented)
        _note_known(oriented, known)

    pg.patterns[:] = planned


def _note_known(p: Pattern, known: set) -> None:
    for v in (p.subject, p.predicate, p.object):
        if v < 0:
            known.add(v)
