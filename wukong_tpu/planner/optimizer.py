"""Type-centric cost-based query optimizer.

Mirrors the reference Planner's structure (core/planner.hpp:218-874): DFS
enumeration of pattern orderings with branch-and-bound on estimated cost,
cardinalities derived from the type-centric statistics (stats.py), index-origin
rewriting of the chosen start pattern (the dummy __PREDICATE__ / rdf:type
pattern, planner.hpp:1647-1679), and a final fallback to the greedy heuristic
when estimation fails.

Simplification vs the reference (documented): the reference's "type table"
carries the joint distribution of (var -> type) row groups; we carry per-var
*marginal* type distributions and assume independence when combining — cheaper,
and sufficient to reproduce the reference's plan choices on the LUBM suites.
Cost constants play the role of planner.hpp:23-29 (AA_full/AA_early/BB_ifor/
CC_const_known/CC_unknown), retuned for the TPU kernel profile where expansion
rows dominate and membership filters are comparatively cheap.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.stats import Stats
from wukong_tpu.sparql.ir import Pattern, PatternGroup, SPARQLQuery
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, PREDICATE_ID, TYPE_ID, is_tpid

# cost weights (planner.hpp:23-29 analogues, TPU-tuned): per scanned row,
# per produced row, per membership probe
COST_SCAN = 1.0
COST_PRODUCE = 2.0
COST_PROBE = 0.5
INIT_COST = 64.0  # per-step fixed dispatch cost


@dataclass
class _State:
    rows: float
    vtypes: dict  # var -> {type: weight} marginal distribution
    cost: float
    plan: list


def _rescale(vtypes: dict, factor: float, skip: int | None = None) -> dict:
    """Scale every var's marginal mass by `factor` (row-count change)."""
    out = {}
    for v, dist in vtypes.items():
        if v == skip:
            out[v] = dict(dist)
        else:
            out[v] = {t: c * factor for t, c in dist.items()}
    return out


class Planner:
    """generate_plan(q) reorders q's patterns by estimated cost (True on success)."""

    def __init__(self, stats: Stats, max_branch: int = 6):
        self.stats = stats
        self.max_branch = max_branch

    # ------------------------------------------------------------------
    def generate_plan(self, q: SPARQLQuery) -> bool:
        pg = q.pattern_group
        if not pg.patterns:
            return True
        try:
            best = self._plan_group(pg)
        except Exception:
            best = None
        if best is None:
            heuristic_plan(q)
            return True
        pg.patterns[:] = [pat for (pat, _src) in best]
        for u in pg.unions:
            sub = SPARQLQuery()
            sub.pattern_group = u
            self.generate_plan(sub)
        return True

    # ------------------------------------------------------------------
    def _plan_group(self, pg: PatternGroup) -> list | None:
        pats = list(pg.patterns)
        self._best_cost = float("inf")
        self._best_plan = None
        for start_state in self._start_candidates(pats):
            self._dfs(start_state, pats)
        return self._best_plan

    def _dfs(self, state: _State, pats: list) -> None:
        if state.cost >= self._best_cost:  # branch and bound
            return
        remaining = [p for p in pats if not self._picked(state, p)]
        if not remaining:
            self._best_cost = state.cost
            self._best_plan = state.plan
            return
        cands = []
        for p in remaining:
            step = self._estimate_step(state, p)
            if step is not None:
                cands.append(step)
        cands.sort(key=lambda s: s.cost)
        for nxt in cands[: self.max_branch]:
            self._dfs(nxt, pats)

    def _picked(self, state: _State, p: Pattern) -> bool:
        return any(src is p for (_, src) in state.plan)

    # ------------------------------------------------------------------
    # start candidates (const start / type index / predicate index)
    # ------------------------------------------------------------------
    def _start_candidates(self, pats: list):
        st = self.stats
        out = []
        for p in pats:
            if p.predicate < 0:
                # versatile start from a const endpoint
                if p.subject >= NORMAL_ID_START:
                    out.append(self._mk_start(
                        Pattern(p.subject, p.predicate, OUT, p.object), p,
                        rows=8.0, var=p.object, dist={0: 8.0}))
                elif p.object >= NORMAL_ID_START:
                    out.append(self._mk_start(
                        Pattern(p.object, p.predicate, IN, p.subject), p,
                        rows=8.0, var=p.subject, dist={0: 8.0}))
                continue
            if p.predicate == TYPE_ID and p.subject < 0 and is_tpid(p.object):
                # type-index start: ?X rdf:type T  ->  (T, rdf:type, IN, ?X)
                cnt = float(st.count_containing(p.object))
                dist = {t: float(st.tyscount.get(t, 0))
                        for t in st.types_containing(p.object)}
                out.append(self._mk_start(
                    Pattern(p.object, TYPE_ID, IN, p.subject), p,
                    rows=cnt, var=p.subject, dist=dist))
                continue
            if p.subject >= NORMAL_ID_START and p.object < 0:
                deg = self._const_fanout(p.predicate, OUT)
                # neighbor types of the const's actual type (fine_type keyed
                # by the anchor type with OUT direction); potype fallback
                ct = st.type_of(p.subject)
                dist = dict(st.fine_type.get((ct, p.predicate, OUT), {})) or \
                    {t: c for t, c in st.potype.get(p.predicate, {}).items()}
                out.append(self._mk_start(
                    Pattern(p.subject, p.predicate, OUT, p.object,
                            p.pred_type), p,
                    rows=deg, var=p.object, dist=self._norm(dist, deg)))
            if p.object >= NORMAL_ID_START and p.subject < 0:
                deg = self._const_fanout(p.predicate, IN)
                ct = st.type_of(p.object)
                dist = dict(st.fine_type.get((ct, p.predicate, IN), {})) or \
                    {t: c for t, c in st.pstype.get(p.predicate, {}).items()}
                out.append(self._mk_start(
                    Pattern(p.object, p.predicate, IN, p.subject,
                            p.pred_type), p,
                    rows=deg, var=p.subject, dist=self._norm(dist, deg)))
            if p.subject < 0 and p.object < 0 and p.predicate > 1:
                # predicate-index start (both sides): dummy __PREDICATE__
                nsub = float(sum(st.pstype.get(p.predicate, {}).values()))
                dist = {t: float(c) for t, c in
                        st.pstype.get(p.predicate, {}).items()}
                out.append(self._mk_start(
                    Pattern(p.predicate, PREDICATE_ID, IN, p.subject), None,
                    rows=nsub, var=p.subject, dist=dist))
        return out

    def _mk_start(self, pat: Pattern, consumes, rows: float, var: int, dist):
        return _State(rows=max(rows, 1.0),
                      vtypes={var: dist or {0: max(rows, 1.0)}},
                      cost=INIT_COST + rows * COST_PRODUCE,
                      plan=[(pat, consumes)])

    def _const_fanout(self, pid: int, d: int) -> float:
        """Average neighbor count of one constant: edges / distinct anchors
        (the anchored side is the object for IN starts, subject for OUT)."""
        st = self.stats
        total = float(st.pred_edges.get(pid, 1))
        anchors = float((st.distinct_obj if d == IN else
                         st.distinct_subj).get(pid, 1)) or 1.0
        return max(total / anchors, 1.0)

    @staticmethod
    def _norm(dist: dict, rows: float) -> dict:
        tot = sum(dist.values()) or 1.0
        return {t: c / tot * rows for t, c in dist.items()}

    # ------------------------------------------------------------------
    # step estimation (fine_type-driven, planner.hpp cost model analogue)
    # ------------------------------------------------------------------
    def _estimate_step(self, state: _State, p: Pattern) -> _State | None:
        st = self.stats
        s_b = p.subject in state.vtypes or p.subject > 0
        o_b = p.object in state.vtypes or p.object > 0
        if p.predicate < 0:
            if not (s_b or o_b):
                return None
            # versatile expansion: pessimistic constant fanout
            rows = state.rows * 8.0
            vt = dict(state.vtypes)
            for v in (p.subject, p.predicate, p.object):
                if v < 0 and v not in vt:
                    vt[v] = {0: rows}
            return _State(rows, vt, state.cost + INIT_COST
                          + state.rows * COST_SCAN + rows * COST_PRODUCE,
                          state.plan + [(self._orient(state, p), p)])
        s_var_b = p.subject < 0 and p.subject in state.vtypes
        o_var_b = p.object < 0 and p.object in state.vtypes
        if not (s_var_b or o_var_b):
            return None
        oriented = self._orient(state, p)
        anchor_var = oriented.subject
        anchor_dist = state.vtypes.get(anchor_var, {})
        d = oriented.direction
        # invariant: every bound var's marginal mass tracks the current row
        # count (sum(vtypes[v]) ~= rows); after any step that changes rows,
        # every other var's marginal is rescaled proportionally — without this
        # an already-expanded var keeps its original cardinality and later
        # expansions on it are wildly underestimated.
        if oriented.predicate == TYPE_ID and oriented.object > 0:
            # type filter: keep rows whose anchor type contains the target
            keep_types = set(st.types_containing(oriented.object))
            kept = sum(c for t, c in anchor_dist.items() if t in keep_types)
            total = sum(anchor_dist.values()) or 1.0
            sel = kept / total
            rows = max(state.rows * sel, 0.01)
            vt = _rescale(state.vtypes, sel, skip=anchor_var)
            vt[anchor_var] = {t: c for t, c in anchor_dist.items()
                              if t in keep_types} or {0: rows}
            return _State(rows, vt, state.cost + INIT_COST
                          + state.rows * COST_PROBE, state.plan + [(oriented, p)])
        if oriented.object < 0 and oriented.object not in state.vtypes:
            # expansion: fanout from fine_type over the anchor's marginal
            rows_out = 0.0
            ndist: dict[int, float] = {}
            for t, c in anchor_dist.items():
                ft = st.fine_type.get((t, oriented.predicate, d), {})
                t_pop = float(st.tyscount.get(t, 1)) or 1.0
                fanout = sum(ft.values()) / t_pop
                rows_out += c * fanout
                for nt, ec in ft.items():
                    share = c * fanout * (ec / (sum(ft.values()) or 1.0))
                    ndist[nt] = ndist.get(nt, 0.0) + share
            rows_out = max(rows_out, 0.0)
            factor = rows_out / max(state.rows, 1e-9)
            vt = _rescale(state.vtypes, factor)
            vt[oriented.object] = ndist or {0: rows_out}
            return _State(rows_out, vt, state.cost + INIT_COST
                          + state.rows * COST_SCAN + rows_out * COST_PRODUCE,
                          state.plan + [(oriented, p)])
        # membership filter (k2k / k2c): selectivity from edge density over
        # DISTINCT endpoint populations (pstype/potype are per-edge histograms;
        # their sums equal pred_edges and must not be used as populations)
        pe = float(st.pred_edges.get(oriented.predicate, 1))
        subj_pop = float(st.distinct_subj.get(oriented.predicate, 1)) or 1.0
        obj_pop = float(st.distinct_obj.get(oriented.predicate, 1)) or 1.0
        if oriented.object > 0:
            # known anchor vs one specific const: P(edge to THE const)
            sel = (pe / obj_pop) / subj_pop
        else:
            # two known vars: P(edge between a random pair)
            sel = pe / (subj_pop * obj_pop)
        sel = min(sel, 1.0)
        rows = max(state.rows * sel, 0.01)
        return _State(rows, _rescale(state.vtypes, sel), state.cost + INIT_COST
                      + state.rows * COST_PROBE, state.plan + [(oriented, p)])

    def _orient(self, state: _State, p: Pattern) -> Pattern:
        s_var_b = p.subject < 0 and p.subject in state.vtypes
        pred_var = p.predicate < 0
        if s_var_b or (p.subject > 0 and not pred_var):
            return Pattern(p.subject, p.predicate, OUT, p.object, p.pred_type)
        return Pattern(p.object, p.predicate, IN, p.subject, p.pred_type)


def make_planner(triples, stat_path: str | None = None) -> Planner:
    """Build (or load) stats and return a Planner."""
    import os

    if stat_path and os.path.exists(
            stat_path if stat_path.endswith(".npz") else stat_path + ".npz"):
        return Planner(Stats.load(stat_path))
    st = Stats.generate(triples)
    if stat_path:
        try:
            st.save(stat_path)
        except OSError as e:
            from wukong_tpu.utils.logger import log_warn

            log_warn(f"statfile not saved ({e}); using in-memory stats")
    return Planner(st)
