"""Type-centric cost-based query optimizer.

Mirrors the reference Planner's structure (core/planner.hpp:218-874): DFS
enumeration of pattern orderings with branch-and-bound on estimated cost,
cardinalities derived from the type-centric statistics (stats.py), index-origin
rewriting of the chosen start pattern (the dummy __PREDICATE__ / rdf:type
pattern, planner.hpp:1647-1679), and a final fallback to the greedy heuristic
when estimation fails.

Cardinality model: the reference's **type table** — the JOINT distribution of
variable types as rows of (count, type-per-bound-var) (planner.hpp type_table,
stats.hpp:46-75). Each step transforms the table:

- expansion: every row splits by the anchor type's fine_type neighbor
  distribution (planner.hpp add_type_table rows);
- a type filter keeps exactly the rows whose anchor type contains the target
  — correlations between variables survive, which is what the earlier
  per-var-marginal model lost (it admitted ~3x misestimates on q1/q7);
- membership steps scale each row by an edge-density selectivity conditioned
  on BOTH endpoint types.

Rows are pruned to a bounded table (mass-preserving rescale) the way the
reference merges rare types (stats.hpp merge_type). Cost constants play the
role of planner.hpp:23-29 (AA_full/AA_early/BB_ifor/CC_*), retuned for the
TPU kernel profile where expansion rows dominate and membership filters are
comparatively cheap.
"""

from __future__ import annotations

from dataclasses import dataclass

from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.stats import Stats
from wukong_tpu.sparql.ir import Pattern, PatternGroup, SPARQLQuery
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, PREDICATE_ID, TYPE_ID, is_tpid

# cost weights (planner.hpp:23-29 analogues, TPU-tuned): per scanned row,
# per produced row, per membership probe
COST_SCAN = 1.0
COST_PRODUCE = 2.0
COST_PROBE = 0.5
INIT_COST = 64.0  # per-step fixed dispatch cost

MAX_TTAB_ROWS = 256  # joint-table row cap (reference merges rare types)


@dataclass
class _State:
    rows: float
    vars: tuple  # bound vars, in type-table column order
    ttab: dict  # {(t_1, ..., t_k): count} joint type distribution
    cost: float
    plan: list
    # some executed subset of patterns has EXACTLY zero mass under the
    # (complete) type statistics — the whole conjunction is provably empty
    # (reference is_empty, planner.hpp:1505-1509). Rows are floored for
    # cost arithmetic, so emptiness rides as a separate flag.
    empty: bool = False
    # emptiness proofs are only sound while the joint table is exact as a
    # SET of type combinations: _prune truncation drops combos whose types
    # might survive a later filter, so it clears this and disables proofs
    exact: bool = True


def _prune(ttab: dict) -> dict:
    """Bound the joint table, preserving total mass (merge_type analogue)."""
    if len(ttab) <= MAX_TTAB_ROWS:
        return ttab
    items = sorted(ttab.items(), key=lambda kv: -kv[1])
    kept = dict(items[:MAX_TTAB_ROWS])
    total = sum(ttab.values())
    kept_total = sum(kept.values()) or 1.0
    scale = total / kept_total
    return {k: v * scale for k, v in kept.items()}


class Planner:
    """generate_plan(q) reorders q's patterns by estimated cost (True on success)."""

    def __init__(self, stats: Stats, max_branch: int = 6):
        self.stats = stats
        self.max_branch = max_branch

    # ------------------------------------------------------------------
    def generate_plan(self, q: SPARQLQuery) -> bool:
        pg = q.pattern_group
        if not pg.patterns:
            return True
        try:
            best = self._plan_group(pg)
        except Exception:
            best = None
        if best is None:
            heuristic_plan(q)
            return True
        pg.patterns[:] = [pat for (pat, _src) in best]
        # provably-empty conjunction (reference "identified empty result
        # query", planner.hpp:1505-1509): engines may skip execution. Sound
        # with filters (only remove rows) and OPTIONAL (left join keeps only
        # parent rows), but NOT with UNION — a branch starting from its own
        # index explores independently of the (empty) parent table.
        q.planner_empty = bool(self._best_state is not None
                               and self._best_state.empty
                               and not pg.unions)
        from wukong_tpu.planner.heuristic import bound_vars, plan_seeded_group

        parent_bound = bound_vars(pg)
        for u in pg.unions:
            # anchored branches execute seeded with the parent table, so
            # they order from those bindings; disjoint branches get their
            # own cost-based plan
            if not plan_seeded_group(u, parent_bound):
                sub = SPARQLQuery()
                sub.pattern_group = u
                self.generate_plan(sub)
        return True

    # ------------------------------------------------------------------
    def _plan_group(self, pg: PatternGroup) -> list | None:
        pats = list(pg.patterns)
        self._best_cost = float("inf")
        self._best_plan = None
        self._best_state = None
        for start_state in self._start_candidates(pats):
            self._dfs(start_state, pats)
        return self._best_plan

    def _dfs(self, state: _State, pats: list) -> None:
        if state.cost >= self._best_cost:  # branch and bound
            return
        remaining = [p for p in pats if not self._picked(state, p)]
        if not remaining:
            self._best_cost = state.cost
            self._best_plan = state.plan
            self._best_state = state
            return
        cands = []
        for p in remaining:
            step = self._estimate_step(state, p)
            if step is not None:
                cands.append(step)
        cands.sort(key=lambda s: s.cost)
        for nxt in cands[: self.max_branch]:
            self._dfs(nxt, pats)

    def _picked(self, state: _State, p: Pattern) -> bool:
        return any(src is p for (_, src) in state.plan)

    # ------------------------------------------------------------------
    # start candidates (const start / type index / predicate index)
    # ------------------------------------------------------------------
    def _start_candidates(self, pats: list):
        out = []
        for p in pats:
            if p.predicate < 0:
                # versatile start from a const endpoint
                if p.subject >= NORMAL_ID_START:
                    out.append(self._mk_start(
                        Pattern(p.subject, p.predicate, OUT, p.object), p,
                        var=p.object, dist={0: 8.0}))
                elif p.object >= NORMAL_ID_START:
                    out.append(self._mk_start(
                        Pattern(p.object, p.predicate, IN, p.subject), p,
                        var=p.subject, dist={0: 8.0}))
                continue
            if p.predicate == TYPE_ID and p.subject < 0 and is_tpid(p.object):
                # type-index start: ?X rdf:type T  ->  (T, rdf:type, IN, ?X)
                out.append(self._mk_start(
                    Pattern(p.object, TYPE_ID, IN, p.subject), p,
                    var=p.subject, dist=self._type_index_dist(p.object)))
                continue
            if p.subject >= NORMAL_ID_START and p.object < 0:
                out.append(self._mk_start(
                    Pattern(p.subject, p.predicate, OUT, p.object,
                            p.pred_type), p,
                    var=p.object,
                    dist=self._const_start_dist(p.subject, p.predicate, OUT)))
            if p.object >= NORMAL_ID_START and p.subject < 0:
                out.append(self._mk_start(
                    Pattern(p.object, p.predicate, IN, p.subject,
                            p.pred_type), p,
                    var=p.subject,
                    dist=self._const_start_dist(p.object, p.predicate, IN)))
            if p.subject < 0 and p.object < 0 and p.predicate > 1:
                # predicate-index start (both sides): dummy __PREDICATE__
                out.append(self._mk_start(
                    Pattern(p.predicate, PREDICATE_ID, IN, p.subject), None,
                    var=p.subject,
                    dist=self._pred_index_dist(p.predicate, IN, norm=False)))
        return out

    # start-distribution builders shared by _start_candidates (DFS over
    # parser-form patterns) and estimate_chain (fixed engine-form plans) —
    # the cardinality model must not drift between the two
    def _type_index_dist(self, tpid: int) -> dict:
        st = self.stats
        return {t: float(st.tyscount.get(t, 0))
                for t in st.types_containing(tpid)}

    def _pred_index_dist(self, pid: int, d: int, norm: bool = True) -> dict:
        """Type distribution of a predicate-index scan's bound var. With
        norm=True the mass is rescaled to the distinct anchor count (the
        engine's index list length); norm=False keeps raw edge counts (the
        DFS treats the scan as producing one row per edge endpoint)."""
        st = self.stats
        dist = {t: float(c) for t, c in
                (st.pstype if d == IN else st.potype).get(pid, {}).items()}
        if not norm:
            return dist
        n = float((st.distinct_subj if d == IN
                   else st.distinct_obj).get(pid, 0)) or 1.0
        return self._norm(dist, n) if dist else {0: n}

    def _const_start_dist(self, const: int, pid: int, d: int) -> dict:
        """Neighbor-type distribution of one constant's expansion: the
        const's actual type via fine_type, falling back to the predicate's
        endpoint histogram; mass = the const's average fanout."""
        st = self.stats
        deg = self._const_fanout(pid, d)
        ct = st.type_of(const)
        dist = dict(st.fine_type.get((ct, pid, d), {})) or \
            {t: c for t, c in
             (st.potype if d == OUT else st.pstype).get(pid, {}).items()}
        return self._norm(dist, deg)

    def _mk_start(self, pat: Pattern, consumes, var: int, dist):
        # an exactly-empty start distribution (type with no entities /
        # predicate with no edges) already proves the query empty — the
        # stats enumerate every (type, pred, dir) that occurs in the graph
        empty = not any(c > 0 for c in (dist or {}).values())
        dist = {t: c for t, c in (dist or {}).items() if c > 0} or {0: 1.0}
        rows = sum(dist.values())
        return _State(rows=max(rows, 1.0), vars=(var,),
                      ttab={(t,): c for t, c in dist.items()},
                      cost=INIT_COST + rows * COST_PRODUCE,
                      plan=[(pat, consumes)], empty=empty)

    def _const_fanout(self, pid: int, d: int) -> float:
        """Average neighbor count of one constant: edges / distinct anchors
        (the anchored side is the object for IN starts, subject for OUT)."""
        st = self.stats
        total = float(st.pred_edges.get(pid, 1))
        anchors = float((st.distinct_obj if d == IN else
                         st.distinct_subj).get(pid, 1)) or 1.0
        return max(total / anchors, 1.0)

    @staticmethod
    def _norm(dist: dict, rows: float) -> dict:
        tot = sum(dist.values()) or 1.0
        return {t: c / tot * rows for t, c in dist.items()}

    # ------------------------------------------------------------------
    # step estimation over the joint type table (planner.hpp:218-874)
    # ------------------------------------------------------------------
    def _estimate_step(self, state: _State, p: Pattern,
                       pre_oriented: bool = False) -> _State | None:
        """pre_oriented=True: p is already in engine form (anchor in subject,
        direction selecting the adjacency side) — estimate_chain's case; the
        DFS passes parser-form patterns that _orient normalizes."""
        st = self.stats
        s_var_b = p.subject < 0 and p.subject in state.vars
        o_var_b = p.object < 0 and p.object in state.vars
        if p.predicate < 0:
            if not (s_var_b or o_var_b or p.subject > 0 or p.object > 0):
                return None
            # versatile expansion: pessimistic constant fanout, untyped var
            rows = state.rows * 8.0
            nvars = tuple(v for v in (p.subject, p.predicate, p.object)
                          if v < 0 and v not in state.vars)
            ttab = {types + (0,) * len(nvars): c * 8.0
                    for types, c in state.ttab.items()}
            return _State(rows, state.vars + nvars, ttab,
                          state.cost + INIT_COST + state.rows * COST_SCAN
                          + rows * COST_PRODUCE,
                          state.plan + [(self._orient(state, p), p)],
                          empty=state.empty, exact=state.exact)
        if not (s_var_b or o_var_b):
            return None
        oriented = p if pre_oriented else self._orient(state, p)
        d = oriented.direction
        if oriented.subject > 0:
            # const anchor mid-plan: only membership on a bound object is
            # executable (const_to_known); the const's own type conditions
            # the per-row selectivity
            if not (oriented.object < 0 and oriented.object in state.vars):
                return None
            const_t = st.type_of(oriented.subject)
            ia = None
        else:
            if oriented.subject not in state.vars:
                # pre-oriented chains can anchor on an unbound subject (e.g.
                # user plan_text plans); unestimable, per the None contract
                return None
            const_t = 0
            ia = state.vars.index(oriented.subject)

        def anchor_type(types):
            return const_t if ia is None else types[ia]

        if oriented.predicate == TYPE_ID and oriented.object > 0 \
                and ia is not None:
            # type filter: KEEP exactly the joint rows whose anchor type
            # contains the target — the joint table's whole point: no
            # independence assumption, correlations survive
            keep = set(st.types_containing(oriented.object))
            ttab = {types: c for types, c in state.ttab.items()
                    if types[ia] in keep}
            # zero surviving mass with an exact table = no binding of the
            # anchor var can have the target type -> provably empty. Rows
            # with anchor type 0 (versatile vars of unknown type) could
            # still match, so they void the proof.
            empty = state.empty or (
                state.exact and not ttab
                and all(types[ia] != 0 for types in state.ttab))
            rows = max(sum(ttab.values()), 0.01)
            return _State(rows, state.vars, ttab or {(0,) * len(state.vars): rows},
                          state.cost + INIT_COST + state.rows * COST_PROBE,
                          state.plan + [(oriented, p)],
                          empty=empty, exact=state.exact)

        if oriented.object < 0 and oriented.object not in state.vars:
            if oriented.predicate in (TYPE_ID, PREDICATE_ID):
                # meta-predicate expansion (?x rdf:type ?t, __PREDICATE__):
                # fine_type deliberately excludes rdf:type edges, so a
                # missing entry must NOT read as "no edges" — every typed
                # entity has them. The new var holds type/pred ids (type 0).
                fan = 1.5 if oriented.predicate == TYPE_ID else 8.0
                rows_out = state.rows * fan
                ttab = {types + (0,): c * fan
                        for types, c in state.ttab.items()}
                return _State(rows_out, state.vars + (oriented.object,),
                              ttab,
                              state.cost + INIT_COST + state.rows * COST_SCAN
                              + rows_out * COST_PRODUCE,
                              state.plan + [(oriented, p)],
                              empty=state.empty, exact=state.exact)
            # expansion: each joint row splits by the anchor type's fine_type
            # neighbor distribution
            ttab: dict[tuple, float] = {}
            rows_out = 0.0
            for types, c in state.ttab.items():
                t = types[ia]
                ft = st.fine_type.get((t, oriented.predicate, d), {})
                t_pop = float(st.tyscount.get(t, 1)) or 1.0
                if not ft:
                    # untyped anchor (e.g. versatile var): global pred fanout
                    fan = self._const_fanout(oriented.predicate, d) \
                        if t == 0 else 0.0
                    if fan > 0:
                        key = types + (0,)
                        ttab[key] = ttab.get(key, 0.0) + c * fan
                        rows_out += c * fan
                    continue
                for nt, ec in ft.items():
                    share = c * (ec / t_pop)
                    key = types + (nt,)
                    ttab[key] = ttab.get(key, 0.0) + share
                    rows_out += share
            # zero produced mass is exact: fine_type enumerates every
            # (type, pred, dir) with edges, and untyped anchors (t == 0)
            # contribute a positive fallback fanout, never a false zero
            empty = state.empty or (state.exact and rows_out == 0.0)
            pruned = len(ttab) > MAX_TTAB_ROWS
            rows_out = max(rows_out, 0.0)
            return _State(rows_out, state.vars + (oriented.object,),
                          _prune(ttab) or {(0,) * (len(state.vars) + 1): 0.01},
                          state.cost + INIT_COST + state.rows * COST_SCAN
                          + rows_out * COST_PRODUCE,
                          state.plan + [(oriented, p)],
                          empty=empty, exact=state.exact and not pruned)

        # membership (k2k / k2c): per-row selectivity conditioned on the
        # anchor row's type (and the other endpoint's type for k2k)
        pe = float(st.pred_edges.get(oriented.predicate, 1))
        sp = float(st.distinct_subj.get(oriented.predicate, 1)) or 1.0
        op = float(st.distinct_obj.get(oriented.predicate, 1)) or 1.0
        ttab: dict[tuple, float] = {}
        rows = 0.0
        for types, c in state.ttab.items():
            t = anchor_type(types)
            ft = st.fine_type.get((t, oriented.predicate, d), {})
            t_pop = float(st.tyscount.get(t, 1)) or 1.0
            if oriented.object > 0:  # k2c: edge to THE specific const
                if not ft:  # untyped anchor: global density per const
                    sel = (pe / op) / sp
                else:
                    ct = st.type_of(oriented.object)
                    targets = {ct} if ct else set(ft)
                    ec = sum(v for nt, v in ft.items() if nt in targets)
                    pop = float(sum(st.tyscount.get(nt, 1)
                                    for nt in targets)) or 1.0
                    sel = (ec / t_pop) / pop
            else:  # k2k: edge to the row's specific o-instance
                io = state.vars.index(oriented.object)
                to = types[io]
                if not ft or to == 0:  # untyped endpoint: global density
                    # (to == 0 must not yield an exact 0 — the endpoint's
                    # type is unknown, so a 0 here would be a false
                    # emptiness proof downstream)
                    sel = pe / (sp * op)
                else:
                    ec = float(ft.get(to, 0))
                    pop = float(st.tyscount.get(to, 1)) or 1.0
                    sel = (ec / t_pop) / pop
            sel = min(sel, 1.0)
            if c * sel > 0:
                ttab[types] = ttab.get(types, 0.0) + c * sel
                rows += c * sel
        # zero mass is exact here too: the untyped branches above always
        # yield positive densities, so sel == 0 only comes from exhaustive
        # fine_type entries (no edges of this pred between these types)
        empty = state.empty or (state.exact and rows == 0.0)
        rows = max(rows, 0.01)
        return _State(rows, state.vars,
                      ttab or {(0,) * len(state.vars): rows},
                      state.cost + INIT_COST + state.rows * COST_PROBE,
                      state.plan + [(oriented, p)],
                      empty=empty, exact=state.exact)

    # ------------------------------------------------------------------
    def _walk_chain(self, patterns: list) -> list | None:
        """Step-by-step _State list for an ALREADY-ORDERED pattern list (the
        plan the engine will execute), or None when the chain shape cannot
        be walked. Shared by estimate_chain (capacity sizing) and
        explain_steps (EXPLAIN estimate capture) so the cardinality model
        never drifts between the two consumers."""
        if not patterns:
            return None
        p0 = patterns[0]
        state = None
        if p0.predicate == TYPE_ID and is_tpid(p0.subject) and p0.object < 0:
            # engine-form type-index start: (T, rdf:type, IN, ?X)
            state = self._mk_start(p0, p0, var=p0.object,
                                   dist=self._type_index_dist(p0.subject))
        elif p0.predicate == PREDICATE_ID and p0.object < 0:
            # predicate-index start: rows = distinct anchors of the predicate
            state = self._mk_start(
                p0, p0, var=p0.object,
                dist=self._pred_index_dist(p0.subject, p0.direction))
        elif p0.subject >= NORMAL_ID_START and p0.object < 0:
            state = self._mk_start(
                p0, p0, var=p0.object,
                dist=self._const_start_dist(p0.subject, p0.predicate,
                                            p0.direction))
        if state is None:
            return None
        states = [state]
        for p in patterns[1:]:
            nxt = self._estimate_step(state, p, pre_oriented=True)
            if nxt is None:
                return None
            state = nxt
            states.append(state)
        return states

    def estimate_chain(self, patterns: list) -> list | None:
        """Per-step output-row estimates for an already-ordered pattern list.

        Returns [rows_after_step_k for k in range(len(patterns))], or None if
        the chain shape cannot be walked. This is the joint-type-table model
        of _estimate_step applied to a fixed order — the engine uses it to
        size device binding-table capacities tightly instead of compounding
        per-step fanout safety margins (each 2x over-provision doubles every
        kernel's cost: kernels pay for capacity, not live rows)."""
        states = self._walk_chain(patterns)
        return None if states is None else [st.rows for st in states]

    def estimate_peak_rows(self, patterns: list) -> int | None:
        """Peak intermediate cardinality across an already-ordered chain,
        or None when the shape cannot be walked. The compiled-template
        route chooser gates on this: a whole-plan XLA dispatch only
        amortizes when the binding tables it fuses are large enough
        (``template_min_rows``) to beat the per-step host kernels."""
        ests = self.estimate_chain(patterns)
        if not ests:
            return None
        return int(max(ests))

    def explain_steps(self, patterns: list) -> list | None:
        """EXPLAIN estimate capture: one record per plan step with the
        estimated output cardinality and the cost model's per-step charge
        (the quantities EXPLAIN ANALYZE joins actual rows/wall-time against,
        keyed on step index). Returns None when the plan shape cannot be
        walked — the EXPLAIN surface then renders the plan without
        estimates rather than inventing numbers."""
        states = self._walk_chain(patterns)
        if states is None:
            return None
        out = []
        prev_cost = 0.0
        for st in states:
            out.append({"est_rows": float(st.rows),
                        "est_cost": float(st.cost - prev_cost),
                        "est_cost_cum": float(st.cost),
                        "est_empty": bool(st.empty)})
            prev_cost = st.cost
        return out

    # ------------------------------------------------------------------
    # execution-strategy selection (wukong_tpu/join/): walk vs wcoj
    # ------------------------------------------------------------------
    def choose_strategy(self, patterns: list) -> str:
        """Pick the execution strategy for an ALREADY-ORDERED pattern list.

        ``join_strategy`` knob: ``walk`` forces the walk; ``wcoj`` forces
        the tensor join on every supported shape; ``auto`` (default) routes
        wcoj only when the query graph is cyclic AND the walk's estimated
        peak intermediate cardinality reaches ``wcoj_ratio`` times the
        estimated final fragment size — the wedge-blowup signature that
        worst-case-optimal joins exist to avoid. Acyclic queries always
        walk under auto (their intermediates are already near-fragment).
        Every return value is a member of ``join.JOIN_STRATEGIES`` (the
        ``join-strategy`` analysis gate holds this statically).
        """
        from wukong_tpu.config import Global
        from wukong_tpu.join.qgraph import analyze

        knob = str(Global.join_strategy).strip().lower()
        if knob == "walk":
            return "walk"
        qg = analyze(patterns, stats=self.stats)
        if not qg.supported:
            return "walk"
        if knob == "wcoj":
            return "wcoj"
        if not qg.cyclic:
            return "walk"
        ests = self.estimate_chain(patterns)
        if ests is None:
            # cyclic but unestimable: the walk's blowup is the known risk
            return "wcoj"
        peak, final = max(ests), max(ests[-1], 1.0)
        if (peak >= max(int(Global.wcoj_min_rows), 1)
                and peak / final >= max(float(Global.wcoj_ratio), 1.0)):
            return "wcoj"
        return "walk"

    def choose_join_route(self, patterns: list) -> str:
        """Pick the wcoj LEVEL route for an already-ordered pattern list.

        ``join_device`` knob: ``host`` forces the NumPy kernels;
        ``device`` forces the XLA path on every level; ``auto`` (default)
        routes device only when the estimated candidate volume — the
        chain's summed per-step output rows, the quantity the per-level
        probes scale with — reaches ``join_device_min_candidates``, so a
        padded dispatch is amortized. Unestimable chains stay on host
        (the dispatch cost is certain, the win is not). Every return
        value is a member of ``join.JOIN_ROUTES`` (the ``join-strategy``
        analysis gate holds this statically)."""
        from wukong_tpu.config import Global

        knob = str(Global.join_device).strip().lower()
        if knob == "host":
            return "host"
        if knob == "device":
            return "device"
        try:
            import importlib.util

            if importlib.util.find_spec("jax") is None:
                return "host"
        except Exception:
            return "host"
        ests = self.estimate_chain(patterns)
        if ests is None:
            return "host"
        if sum(ests) >= max(int(Global.join_device_min_candidates), 1):
            return "device"
        return "host"

    def _orient(self, state: _State, p: Pattern) -> Pattern:
        s_var_b = p.subject < 0 and p.subject in state.vars
        pred_var = p.predicate < 0
        if s_var_b or (p.subject > 0 and not pred_var):
            return Pattern(p.subject, p.predicate, OUT, p.object, p.pred_type)
        return Pattern(p.object, p.predicate, IN, p.subject, p.pred_type)


def make_planner(triples, stat_path: str | None = None) -> Planner:
    """Build (or load) stats and return a Planner."""
    import os

    if stat_path and os.path.exists(
            stat_path if stat_path.endswith(".npz") else stat_path + ".npz"):
        return Planner(Stats.load(stat_path))
    st = Stats.generate(triples)
    if stat_path:
        try:
            st.save(stat_path)
        except OSError as e:
            from wukong_tpu.utils.logger import log_warn

            log_warn(f"statfile not saved ({e}); using in-memory stats")
    return Planner(st)
