"""User-defined query plans (.fmt files) — planner-off mode.

Mirrors Planner::set_plan / set_direction (core/planner.hpp:1647-1755):
each line is "<pattern#> <dir>" (1-based pattern number in the parsed query);
dirs: '>' OUT as written, '<' IN (swap subject/object),
'<<' predicate-index start IN, '>>' predicate-index start OUT
(subject becomes the predicate id, predicate becomes __PREDICATE__).
A '<' on a type pattern starts from the type index (subject becomes the type
id const with predicate rdf:type). Lines may repeat a pattern (re-executed as
a filter step) and nested UNION/OPTIONAL blocks recurse.
"""

from __future__ import annotations

from wukong_tpu.sparql.ir import Pattern, PatternGroup
from wukong_tpu.types import IN, OUT, PREDICATE_ID
from wukong_tpu.utils.logger import log_error, log_warn


def set_plan(group: PatternGroup, fmt_text: str, ptypes_pos: list | None = None) -> bool:
    """Apply a plan to a pattern group. Returns False on malformed input."""
    lines = iter(fmt_text.splitlines())
    return _set_plan_group(group, lines, ptypes_pos)


def _set_plan_group(group: PatternGroup, lines, ptypes_pos) -> bool:
    orders: list[int] = []
    dirs: list[str] = []
    nunions = noptionals = 0
    for raw in lines:
        line = raw.strip()
        if not line or line.startswith("#") or line == "{":
            continue
        if line == "}":
            break
        low = line.lower()
        if low.startswith("union"):
            if not _set_plan_group(group.unions[nunions], lines, None):
                return False
            nunions += 1
            continue
        if low.startswith("optional"):
            if not _set_plan_group(group.optional[noptionals], lines, None):
                return False
            noptionals += 1
            continue
        parts = line.split()
        try:
            orders.append(int(parts[0]))
        except (ValueError, IndexError):
            log_error(f"bad plan line: {line!r}")
            return False
        dirs.append(parts[1] if len(parts) > 1 else ">")

    if len(orders) < len(group.patterns):
        log_error("wrong format file content (fewer plan lines than patterns)")
        return False
    bad = [o for o in orders if not (1 <= o <= len(group.patterns))]
    if bad:
        log_error(f"plan pattern numbers out of range: {bad} "
                  f"(query has {len(group.patterns)} patterns)")
        return False
    _set_direction(group, orders, dirs, ptypes_pos)
    return True


def _set_direction(group: PatternGroup, orders, dirs, ptypes_pos) -> None:
    out = []
    # remap %placeholder slots to their new pattern positions (planner.hpp
    # set_ptypes_pos): a placeholder at original pattern k moves with it.
    pos_remap = {}
    for i, order in enumerate(orders):
        src = group.patterns[order - 1]
        p = Pattern(src.subject, src.predicate, src.direction, src.object,
                    src.pred_type)
        d = dirs[i]
        if d == "<":
            p.direction = IN
            p.subject, p.object = p.object, p.subject
        elif d == ">":
            p.direction = OUT
        elif d == "<<":
            p.direction = IN
            p.object = p.subject
            p.subject = p.predicate
            p.predicate = PREDICATE_ID
        elif d == ">>":
            # object keeps the original object var (the index's OUT side)
            p.direction = OUT
            p.subject = p.predicate
            p.predicate = PREDICATE_ID
        else:
            log_warn(f"unknown plan direction {d!r}, treating as '>'")
            p.direction = OUT
        if ptypes_pos is not None:
            for slot, (pi, fld) in enumerate(ptypes_pos):
                if pi == order - 1:
                    newfld = fld
                    if d == "<":
                        newfld = "subject" if fld == "object" else "object"
                    pos_remap[slot] = (len(out), newfld)
        out.append(p)
    group.patterns[:] = out
    if ptypes_pos is not None:
        for slot, np_ in pos_remap.items():
            ptypes_pos[slot] = np_
