"""Type-centric statistics for the cost-based optimizer.

Mirrors the reference's Stats (core/stats.hpp): per-type entity counts
(`tyscount`), predicate -> subject-type / object-type histograms
(`pstype`/`potype`), and the fine-grained (type, pred, dir) -> neighbor-type
histogram (`fine_type`) — stats.hpp:658-869 walks gstore buckets; here the
whole computation is vectorized over the triple array.

Vertices with multiple types or no type get *complex types* synthesized from
their type-set / predicate-set composition (stats.hpp:46-75 type_t,
get_simple_type 642-655): complex ids are negative to stay clear of real type
ids, and `members_of` exposes the base types a complex type contains (so a
type filter can keep matching complex types).

Persisted to a stat file like the reference's `<input>/statfile`
(stats.hpp:585-640) — ours is an npz bundle.
"""

from __future__ import annotations

import json
from collections import defaultdict

import numpy as np

from wukong_tpu.types import IN, NORMAL_ID_START, OUT, TYPE_ID


class Stats:
    def __init__(self):
        self.tyscount: dict[int, int] = {}  # type id -> #entities
        self.pstype: dict[int, dict[int, int]] = {}  # pid -> {stype: count}
        self.potype: dict[int, dict[int, int]] = {}  # pid -> {otype: count}
        # (type, pid, dir) -> {neighbor_type: edge count}
        self.fine_type: dict[tuple, dict[int, int]] = {}
        self.pred_edges: dict[int, int] = {}  # pid -> total triples
        self.distinct_subj: dict[int, int] = {}  # pid -> #distinct subjects
        self.distinct_obj: dict[int, int] = {}  # pid -> #distinct objects
        # complex type composition: complex id (<0) -> frozenset(base type ids)
        self.complex_members: dict[int, frozenset] = {}
        self.vtype: np.ndarray | None = None  # entity -> (simple|complex) type
        self.vtype_ids: np.ndarray | None = None  # sorted entity ids for vtype

    # ------------------------------------------------------------------
    def type_of(self, vid: int) -> int:
        i = np.searchsorted(self.vtype_ids, vid)
        if i < len(self.vtype_ids) and self.vtype_ids[i] == vid:
            return int(self.vtype[i])
        return 0

    def types_containing(self, base_type: int) -> list[int]:
        """All (simple + complex) type ids whose members include base_type."""
        out = [base_type] if base_type in self.tyscount else []
        for cid, members in self.complex_members.items():
            if base_type in members:
                out.append(cid)
        return out

    def count_containing(self, base_type: int) -> int:
        return sum(self.tyscount.get(t, 0) for t in self.types_containing(base_type))

    # ------------------------------------------------------------------
    @staticmethod
    def generate(triples: np.ndarray) -> "Stats":
        """Build statistics from the full [M,3] id-triple array."""
        st = Stats()
        s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
        is_type = p == TYPE_ID

        # ---- per-vertex simple/complex type ------------------------------
        ts, to = s[is_type], o[is_type]
        order = np.argsort(ts, kind="stable")
        ts, to = ts[order], to[order]
        uniq_v, starts = np.unique(ts, return_index=True)
        bounds = np.append(starts, len(ts))
        complex_ids: dict[frozenset, int] = {}
        next_complex = -1
        simple_counts: dict[int, int] = defaultdict(int)
        if len(uniq_v) == len(ts):
            # every vertex single-typed (all LUBM-shaped data): the
            # per-vertex frozenset loop is O(V) Python objects — at
            # LUBM-10240 (220 M typed vertices) it OOM-killed the host;
            # the vectorized equivalent is two array ops
            typed_types = to[starts].astype(np.int64)
            for t, c in zip(*np.unique(typed_types, return_counts=True)):
                simple_counts[int(t)] += int(c)
        else:
            vtypes: list[int] = []
            for i, v in enumerate(uniq_v):
                tset = frozenset(int(x) for x in to[bounds[i]:bounds[i + 1]])
                if len(tset) == 1:
                    t = next(iter(tset))
                else:
                    if tset not in complex_ids:
                        complex_ids[tset] = next_complex
                        next_complex -= 1
                    t = complex_ids[tset]
                vtypes.append(t)
                simple_counts[t] += 1
            typed_types = np.asarray(vtypes, dtype=np.int64)
        # untyped vertices: complex type from their out-predicate set
        all_vs = np.unique(np.concatenate(
            [s, o[o >= NORMAL_ID_START]]))
        untyped = np.setdiff1d(all_vs, uniq_v)
        untyped_types = np.empty(0, dtype=np.int64)
        if len(untyped):
            norm = ~is_type
            so_, po_ = s[norm], p[norm]
            # untyped subjects actually carrying out-edges (in LUBM-shaped
            # data the untyped set is literal pools with NO out-edges, so
            # this mask is empty and the whole branch is one shared class).
            # ONE membership pass serves both the branch decision and the
            # vectorized path below — each isin sorts the full edge list
            keep = np.isin(so_, untyped)
            n_out_subj = len(np.unique(so_[keep])) if keep.any() else 0
            if n_out_subj > 200_000:
                # vectorized signature path: group by out-predicate SET
                # via a commutative 64-bit mix over unique (s, p) pairs —
                # the per-vertex frozenset loop at this cardinality is
                # Python-object OOM territory
                from wukong_tpu.utils.mathutil import hash_u64

                # pack (s, p) into one int64: pred ids < 2^17 (NORMAL_ID_
                # START) by construction, subject ids < 2^31 -> 48 bits
                code = np.unique((so_[keep].astype(np.int64) << 17)
                                 | po_[keep].astype(np.int64))
                cs_, cp_ = code >> 17, code & ((1 << 17) - 1)
                upids = np.unique(cp_)
                hmap = np.asarray([hash_u64(int(x)) for x in upids],
                                  dtype=np.uint64)
                mixed = hmap[np.searchsorted(upids, cp_)]
                uv2, ustarts2 = np.unique(cs_, return_index=True)
                sig = np.add.reduceat(mixed, ustarts2)  # commutative mix
                sgu, sinv = np.unique(sig, return_inverse=True)
                sig_cids = np.arange(next_complex,
                                     next_complex - len(sgu), -1,
                                     dtype=np.int64)
                for k in range(len(sgu)):
                    # representative member set is informational only —
                    # the loop path also strips ("p", x) tuples to {}
                    complex_ids[frozenset({("sig", int(sgu[k]))})] = \
                        int(sig_cids[k])
                next_complex -= len(sgu)
                cid_by_subject = sig_cids[sinv]  # aligned with uv2
                pos2 = np.searchsorted(uv2, untyped)
                pos2c = np.clip(pos2, 0, max(len(uv2) - 1, 0))
                found2 = ((pos2 < len(uv2)) & (len(uv2) > 0)
                          & (uv2[pos2c] == untyped))
                empty_cid = 0
                if not found2.all():
                    # no-out-edge literals: one shared class, minted only
                    # when such vertices exist (the loop path allocates on
                    # first use; a phantom zero-member class would leak
                    # into complex_members/statfiles)
                    key = frozenset()
                    if key not in complex_ids:
                        complex_ids[key] = next_complex
                        next_complex -= 1
                    empty_cid = complex_ids[key]
                untyped_types = np.where(
                    found2, cid_by_subject[pos2c] if len(uv2) else 0,
                    empty_cid).astype(np.int64)
                for t, c in zip(*np.unique(untyped_types,
                                           return_counts=True)):
                    simple_counts[int(t)] += int(c)
            elif n_out_subj == 0:
                # all-literal untyped set: one shared empty-pset class
                key = frozenset()
                if key not in complex_ids:
                    complex_ids[key] = next_complex
                    next_complex -= 1
                untyped_types = np.full(len(untyped), complex_ids[key],
                                        dtype=np.int64)
                simple_counts[complex_ids[key]] += len(untyped)
            else:
                order2 = np.argsort(so_, kind="stable")
                so2, po2 = so_[order2], po_[order2]
                uv, ustarts = np.unique(so2, return_index=True)
                ubounds = np.append(ustarts, len(so2))
                pos = np.searchsorted(uv, untyped)
                uvt: list[int] = []
                for v, j in zip(untyped, pos):
                    if j < len(uv) and uv[j] == v:
                        pset = frozenset(
                            int(x) for x in po2[ubounds[j]:ubounds[j + 1]])
                    else:
                        pset = frozenset()
                    key = frozenset({("p", x) for x in pset})
                    if key not in complex_ids:
                        complex_ids[key] = next_complex
                        next_complex -= 1
                    uvt.append(complex_ids[key])
                    simple_counts[complex_ids[key]] += 1
                untyped_types = np.asarray(uvt, dtype=np.int64)
        st.vtype_ids = np.concatenate([uniq_v, untyped]).astype(np.int64)
        st.vtype = np.concatenate([typed_types, untyped_types])
        order3 = np.argsort(st.vtype_ids)
        st.vtype_ids = st.vtype_ids[order3]
        st.vtype = st.vtype[order3]
        st.tyscount = dict(simple_counts)
        st.complex_members = {
            cid: frozenset(x for x in key if not isinstance(x, tuple))
            for key, cid in complex_ids.items()}

        # ---- predicate histograms ----------------------------------------
        norm = ~is_type
        sn, pn, on = s[norm], p[norm], o[norm]
        stype = st._lookup_types(sn)
        otype = st._lookup_types(on)
        for pid in np.unique(pn):
            m = pn == pid
            st.pred_edges[int(pid)] = int(m.sum())
            st.distinct_subj[int(pid)] = int(len(np.unique(sn[m])))
            st.distinct_obj[int(pid)] = int(len(np.unique(on[m])))
            st.pstype[int(pid)] = _hist(stype[m])
            st.potype[int(pid)] = _hist(otype[m])
            for t, c in _hist_pairs(stype[m], otype[m]).items():
                st.fine_type.setdefault((t[0], int(pid), OUT), {})
                st.fine_type[(t[0], int(pid), OUT)][t[1]] = \
                    st.fine_type[(t[0], int(pid), OUT)].get(t[1], 0) + c
                st.fine_type.setdefault((t[1], int(pid), IN), {})
                st.fine_type[(t[1], int(pid), IN)][t[0]] = \
                    st.fine_type[(t[1], int(pid), IN)].get(t[0], 0) + c
        # rdf:type participates as a predicate too (k2c type filters)
        st.pred_edges[int(TYPE_ID)] = int(is_type.sum())
        st.pstype[int(TYPE_ID)] = _hist(st._lookup_types(s[is_type]))
        return st

    def _lookup_types(self, vids: np.ndarray) -> np.ndarray:
        idx = np.searchsorted(self.vtype_ids, vids)
        idx = np.clip(idx, 0, max(len(self.vtype_ids) - 1, 0))
        found = self.vtype_ids[idx] == vids
        return np.where(found, self.vtype[idx], 0)

    # ------------------------------------------------------------------
    def save(self, path: str) -> None:
        meta = {
            "tyscount": {str(k): v for k, v in self.tyscount.items()},
            "pstype": {str(k): {str(a): b for a, b in v.items()}
                       for k, v in self.pstype.items()},
            "potype": {str(k): {str(a): b for a, b in v.items()}
                       for k, v in self.potype.items()},
            "fine_type": [[list(k), {str(a): b for a, b in v.items()}]
                          for k, v in self.fine_type.items()],
            "pred_edges": {str(k): v for k, v in self.pred_edges.items()},
            "distinct_subj": {str(k): v for k, v in self.distinct_subj.items()},
            "distinct_obj": {str(k): v for k, v in self.distinct_obj.items()},
            "complex_members": {str(k): sorted(v) for k, v in
                                self.complex_members.items()},
        }
        np.savez(path, _meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
                 vtype=self.vtype, vtype_ids=self.vtype_ids)

    @staticmethod
    def load(path: str) -> "Stats":
        z = np.load(path if path.endswith(".npz") else path + ".npz")
        meta = json.loads(bytes(z["_meta"]).decode())
        st = Stats()
        st.tyscount = {int(k): v for k, v in meta["tyscount"].items()}
        st.pstype = {int(k): {int(a): b for a, b in v.items()}
                     for k, v in meta["pstype"].items()}
        st.potype = {int(k): {int(a): b for a, b in v.items()}
                     for k, v in meta["potype"].items()}
        st.fine_type = {tuple(k): {int(a): b for a, b in v.items()}
                        for k, v in meta["fine_type"]}
        st.pred_edges = {int(k): v for k, v in meta["pred_edges"].items()}
        st.distinct_subj = {int(k): v for k, v in
                            meta.get("distinct_subj", {}).items()}
        st.distinct_obj = {int(k): v for k, v in
                           meta.get("distinct_obj", {}).items()}
        st.complex_members = {int(k): frozenset(v) for k, v in
                              meta["complex_members"].items()}
        st.vtype = z["vtype"]
        st.vtype_ids = z["vtype_ids"]
        return st


def _hist(arr: np.ndarray) -> dict[int, int]:
    u, c = np.unique(arr, return_counts=True)
    return {int(a): int(b) for a, b in zip(u, c)}


def _hist_pairs(a: np.ndarray, b: np.ndarray) -> dict[tuple, int]:
    if len(a) == 0:
        return {}
    order = np.lexsort((b, a))
    aa, bb = a[order], b[order]
    new = np.ones(len(aa), dtype=bool)
    new[1:] = (aa[1:] != aa[:-1]) | (bb[1:] != bb[:-1])
    starts = np.flatnonzero(new)
    counts = np.diff(np.append(starts, len(aa)))
    return {(int(aa[i]), int(bb[i])): int(c) for i, c in zip(starts, counts)}
