from wukong_tpu.runtime.monitor import Monitor  # noqa: F401
from wukong_tpu.runtime.proxy import Proxy  # noqa: F401
