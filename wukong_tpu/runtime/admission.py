"""The admission control plane (ISSUE 16): the decision half of the
tenant SLO plane.

PR 10 built the measurement substrate — per-tenant SLOTrackers,
multi-window burn rates, and the ``ADMISSION_INPUTS`` overload signal
bus (obs/slo.py). This module is the actuator that finally *acts* on
those signals, consulted at the proxy admission point (the reference
system's proxy/engine split exists exactly so the frontend can make
load decisions before work reaches the engines):

- :class:`AdmissionController` — per-tenant quotas (token-bucket q/s,
  in-flight caps, aggregate row budgets, declared via the
  ``admission_quotas`` knob) plus the three-rung overload degrade
  ladder. Every signal it reads comes through
  ``obs.slo.read_admission_input`` and is declared in the literal
  ``CONSUMED_INPUTS`` tuple below (the serve/result_cache.py consumer
  contract, held statically by the ``admission-gate`` analysis plugin).
- **Degrade before drop**: overload shedding walks a ladder — rung 1
  DEFERS the query past the batch window (closed-loop clients slow
  down, congestion drains), rung 2 serves PARTIAL results through the
  PR 1 ``mark_partial``/Deadline machinery (a tightened deadline + row
  budget stamped at admission), rung 3 REJECTS with a structured
  ``CAPACITY_EXCEEDED`` reply carrying a retry-after hint. The ladder
  applies lowest-weight-first (``rung = level - 2*rank``): bulk is
  deferred at level 1 and partialed at level 2 *before* silver is first
  touched at level 3, and the top weight class is never ladder-degraded
  at all — protected tenants stay SLO-compliant while bulk absorbs the
  damage. Quota breaches degrade the same way: a token shortfall the
  bucket will refill within the defer window defers instead of
  rejecting.
- :class:`FairQueue` — deficit-round-robin weighted-fair scheduling
  over per-tenant sub-queues, layered UNDER the existing
  interactive/stream/batch/rebuild/heavy lanes by the engine pool: when
  armed, default-lane submissions land in per-tenant sub-queues and
  engines drain them by weight (a hostile bulk flood can no longer
  starve gold's interactive traffic). Priority inheritance: an item
  carrying ``owner_tenant`` (a standing query's maintenance work,
  stream/continuous.py) is queued and weighted as its OWNER, so gold's
  standing-query deltas run at gold's weight instead of the bottom of
  the stream lane.
- Congestion signal: the per-lane queue-delay EWMAs (plus aggregate
  in-flight and lane depth vs capacity) feed :meth:`overload_level`,
  which selects the ladder rung.

Shed outcomes flow through the existing ``wukong_shed_total`` cause
counters (the literal ``SHED_CAUSES`` closed set below — the admit gate
verifies every cause is declared AND has a call site) and the cluster
event journal (``admission.shed`` / ``admission.quota`` kinds, one
event per tenant+cause per second, never a storm).

Default OFF (``enable_admission``): every hook degrades to one knob
check and the serving path is byte-unchanged (the ``migration_enable``
actuator posture; BENCH_SERVE.json ``detail.overhead_guard`` pins the
on/off p50 bands overlapping).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.events import emit_event
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.slo import (
    EWMA_ALPHA,
    maybe_note_shed,
    read_admission_input,
    tenant_label,
)
from wukong_tpu.utils.timer import get_usec

#: every overload-bus signal this controller reads — each element must
#: be an ``ADMISSION_INPUTS`` key (obs/slo.py), and every
#: ``read_admission_input`` call site below must name one of these.
#: The admission-gate analysis plugin holds both containments literal.
CONSUMED_INPUTS = (
    "lane_queue_delay_ewma",
    "lane_depth",
    "pool_utilization",
    "tenant_inflight",
    "tenant_arrival_rate",
    "shed_by_cause",
)

#: the closed set of shed causes this plane may charge to
#: ``wukong_shed_total`` — one per ladder rung plus the quota breach.
#: The admit gate verifies every literal cause at a note_shed call site
#: here is declared, and every declared cause has >=1 call site.
SHED_CAUSES = (
    "admission_defer",
    "admission_partial",
    "admission_reject",
    "admission_quota",
)

#: ladder rung names, index = rung (0 admits)
_RUNGS = ("admit", "defer", "partial", "reject")

#: at most one journaled event per (kind, tenant, cause) per this many
#: usec — a shed storm is one timeline entry, not a thousand
EVENT_COOLDOWN_US = 1_000_000

#: overload-level recompute interval: the level is derived from EWMAs,
#: so reusing it for 2ms decides identically and keeps the armed
#: plane's per-admit cost to a clock read instead of the signal scans
_LEVEL_TTL_US = 2_000

# both admission locks guard dict/float updates only and never call out
# while held (signal reads happen before, metrics/events after) —
# innermost by construction, and the admit gate requires them declared
declare_leaf("admission.state")
declare_leaf("admission.queue")

_M_DECISIONS = get_registry().counter(
    "wukong_admission_decisions_total",
    "Admission decisions by outcome and tenant",
    labels=("decision", "tenant"))
_M_LEVEL = get_registry().gauge(
    "wukong_admission_overload_level",
    "Current overload level (0 calm .. 3 shedding)")


# ---------------------------------------------------------------------------
# quotas
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission contract: DRR/shed weight, token-bucket
    q/s quota, in-flight cap, and aggregate intermediate-row budget
    (rows/s across all its queries). 0 disables that limit."""

    tenant: str
    weight: int = 1
    qps: float = 0.0
    inflight: int = 0
    rows_per_s: int = 0


def parse_quotas(text: str) -> dict[str, TenantQuota]:
    """Parse the ``admission_quotas`` knob: ";"-separated
    ``<tenant>:<weight>:<qps>:<inflight>:<rows_per_s>`` entries.
    Malformed entries are a config error, not a silent mis-arm."""
    out: dict[str, TenantQuota] = {}
    for ent in (text or "").split(";"):
        ent = ent.strip()
        if not ent:
            continue
        parts = ent.split(":")
        if len(parts) != 5:
            raise ValueError(
                f"bad admission_quotas entry {ent!r} (want "
                "tenant:weight:qps:inflight:rows_per_s)")
        t = parts[0].strip()
        w = int(parts[1])
        if not t or w < 1:
            raise ValueError(
                f"bad admission_quotas entry {ent!r} (weight >= 1)")
        out[t] = TenantQuota(t, w, float(parts[2]), int(parts[3]),
                             int(parts[4]))
    return out


def effective_tenant(obj) -> str:
    """The identity an item is scheduled AS: its owner when it is
    maintenance work for a standing query (priority inheritance), else
    its own tenant stamp, else the default tenant."""
    t = getattr(obj, "owner_tenant", None)
    if not t:
        t = getattr(obj, "tenant", None)
    return str(t) if t else "default"


# ---------------------------------------------------------------------------
# decisions
# ---------------------------------------------------------------------------

class Decision:
    """One admission verdict. ``action`` is an ``_RUNGS`` member;
    ``wait_s`` is the rung-1 defer the CALLER sleeps (the controller
    never blocks under its lock); ``retry_after_s`` rides the rung-3
    ``CAPACITY_EXCEEDED`` reply."""

    __slots__ = ("action", "cause", "tenant", "wait_s", "retry_after_s",
                 "level", "reason")

    def __init__(self, action: str, tenant: str, cause: str | None = None,
                 wait_s: float = 0.0, retry_after_s: float = 0.0,
                 level: int = 0, reason: str = ""):
        self.action = action
        self.tenant = tenant
        self.cause = cause
        self.wait_s = wait_s
        self.retry_after_s = retry_after_s
        self.level = level
        self.reason = reason

    @property
    def admitted(self) -> bool:
        return self.action in ("admit", "defer", "partial")

    def apply(self, q) -> None:
        """Stamp a rung-2 PARTIAL admission onto a prepared query: the
        tightened deadline + row budget whose expiry the PR 1
        ``mark_partial`` machinery converts into a complete=False reply
        with the rows produced so far."""
        if self.action != "partial":
            return
        from wukong_tpu.runtime.resilience import Deadline

        q.deadline = Deadline(
            max(int(Global.admission_partial_deadline_ms), 1),
            max(int(Global.admission_partial_budget_rows), 0))


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class _TenantState:
    """Per-tenant quota state: the token bucket + the aggregate-row
    EWMA. All fields guarded by the controller's state lock."""

    __slots__ = ("tokens", "last_refill_us", "rows_rate", "last_rows_us")

    def __init__(self, burst: float):
        self.tokens = burst
        self.last_refill_us = 0
        self.rows_rate = 0.0
        self.last_rows_us = 0


class AdmissionController:
    """Per-tenant quotas + the overload degrade ladder, consulted at
    the proxy admission point (after ``_admit`` notes the arrival, so
    the tenant's in-flight signal already includes the query under
    decision). Reads ONLY ``CONSUMED_INPUTS`` signals."""

    def __init__(self, clock=None):
        self._lock = make_lock("admission.state")
        self._tenants: dict[str, _TenantState] = {}  # guarded by: _lock
        self._decisions: dict = {}  # guarded by: _lock
        self._last_event: dict = {}  # guarded by: _lock
        # quota-parse cache: an immutable (src, parsed) pair swapped
        # wholesale, so weight()/quota_for() stay lock-free (the fair
        # queue and the heavy-lane cap consult them under pool locks)
        self._qcache: tuple = ("", {})  # lock-free: atomic tuple swap
        self._clock = clock or get_usec  # lock-free: injectable (tests)
        self.last_level = 0  # lock-free: int gauge feed, monotonic GIL
        # (stamp_us, level): the computed overload level, reused within
        # _LEVEL_TTL_US so the armed plane's per-query cost stays flat
        self._level_cache: tuple = (-_LEVEL_TTL_US, 0)  # lock-free: tuple swap

    # -- quotas (lock-free reads) --------------------------------------
    def _quota_map(self) -> dict[str, TenantQuota]:
        src = str(Global.admission_quotas)
        cached_src, cached = self._qcache
        if cached_src == src:
            return cached
        parsed = parse_quotas(src)
        self._qcache = (src, parsed)  # benign race: idempotent re-parse
        return parsed

    def quota_for(self, tenant: str) -> TenantQuota:
        q = self._quota_map().get(tenant)
        if q is None:
            q = TenantQuota(tenant,
                            max(int(Global.admission_default_weight), 1))
        return q

    def weight(self, tenant: str) -> int:
        return self.quota_for(tenant).weight

    def heavy_cap_for(self, tenant: str, cap: int, holders: dict) -> int:
        """Per-tenant share of the heavy lane's ``cap`` slots: weighted
        by quota weight across the tenants currently holding slots plus
        the requester (work-conserving — a lone tenant gets the whole
        lane). Pure function of the quota map: safe under pool locks."""
        active = set(holders) | {tenant}
        total_w = sum(self.weight(t) for t in active) or 1
        return max((cap * self.weight(tenant)) // total_w, 1)

    # -- overload level -------------------------------------------------
    def _inflight_cap(self) -> int:
        cap = int(Global.admission_max_inflight)
        if cap > 0:
            return cap
        # derived capacity: 4x the live engine count when a pool runs
        # (structural config, not a telemetry signal), else a fixed 8
        # for the direct-execution serving path
        try:
            from wukong_tpu.runtime.scheduler import _live_engine_count

            n = _live_engine_count()
        except Exception:
            n = 0
        return 4 * n if n > 0 else 8

    def overload_level(self) -> int:
        """0 calm .. 3 shedding, from the congestion signals: the worst
        per-lane queue-delay EWMA vs ``admission_delay_budget_us``, and
        aggregate in-flight + queued depth vs the in-flight ceiling.
        Each doubling past budget raises the level one rung.

        Recomputed at most once per ``_LEVEL_TTL_US`` — the inputs are
        EWMAs, so a 2ms-stale level decides identically while keeping
        the armed plane's per-query hot path to a clock read (the
        uncached walk costs ~15us of signal scans per admit)."""
        stamp, lvl = self._level_cache
        now = self._clock()
        if 0 <= now - stamp < _LEVEL_TTL_US:
            return lvl
        delays = read_admission_input("lane_queue_delay_ewma")
        depths = read_admission_input("lane_depth")
        inflight = read_admission_input("tenant_inflight")
        budget = max(int(Global.admission_delay_budget_us), 1)
        cap = max(self._inflight_cap(), 1)
        x = max(
            (max(delays.values()) if delays else 0.0) / budget,
            sum(inflight.values()) / cap if inflight else 0.0,
            sum(depths.values()) / cap if depths else 0.0,
        )
        level = 0 if x < 1.0 else 1 if x < 2.0 else 2 if x < 4.0 else 3
        self._level_cache = (now, level)  # benign race: idempotent
        self.last_level = level
        _M_LEVEL.set(level)
        return level

    def _rank(self, tenant: str) -> tuple[int, int]:
        """(weight rank, top rank) among the active tenants — quota-
        declared ones plus whoever the arrival signal currently sees.
        Rank 0 is the lowest weight class (shed first)."""
        active = set(self._quota_map()) | {tenant}
        arrivals = read_admission_input("tenant_arrival_rate")
        active.update(t for t, r in arrivals.items() if r > 0)
        weights = sorted({self.weight(t) for t in active})
        return weights.index(self.weight(tenant)), len(weights) - 1

    # -- the admission verdict ------------------------------------------
    def admit(self, tenant, cached: bool = False) -> Decision:
        """One query's verdict. ``cached`` marks a result-cache fast
        hit: it consumes no engine capacity, so only the q/s + in-flight
        quotas apply (the ladder never degrades a hit). Signal reads
        happen before the state lock, metrics/events after — the state
        lock stays a leaf."""
        ten = tenant_label(tenant)
        quota = self.quota_for(ten)
        now = self._clock()
        defer_s = self._defer_s()

        # quota signals read outside the lock
        inflight = (read_admission_input("tenant_inflight").get(ten, 0)
                    if quota.inflight > 0 else 0)

        verdict: Decision | None = None
        with self._lock:
            st = self._tenants.get(ten)
            if st is None:
                st = self._tenants[ten] = _TenantState(
                    self._burst(quota))
                st.last_refill_us = now
            if quota.qps > 0:
                self._refill(st, quota, now)
                if st.tokens >= 1.0:
                    st.tokens -= 1.0
                else:
                    wait_s = (1.0 - st.tokens) / quota.qps
                    if wait_s <= defer_s:
                        # degrade before drop: the bucket refills within
                        # the defer window — pre-charge it and wait
                        st.tokens -= 1.0
                        verdict = Decision(
                            "defer", ten, "admission_defer",
                            wait_s=wait_s, reason="quota_qps")
                    else:
                        verdict = Decision(
                            "reject", ten, "admission_quota",
                            retry_after_s=max(
                                wait_s,
                                float(Global.admission_retry_after_s)),
                            reason="quota_qps")
            if verdict is None and quota.inflight > 0 \
                    and inflight > quota.inflight:
                verdict = Decision(
                    "reject", ten, "admission_quota",
                    retry_after_s=float(Global.admission_retry_after_s),
                    reason="quota_inflight")
            if verdict is None and quota.rows_per_s > 0 \
                    and st.rows_rate > quota.rows_per_s and not cached:
                # over the aggregate row budget: this tenant's replies
                # degrade to partials until the rate decays back under
                verdict = Decision("partial", ten, "admission_partial",
                                   reason="quota_rows")
        if verdict is None and not cached:
            verdict = self._ladder(ten)
        if verdict is None:
            verdict = Decision("admit", ten)
        self._record(verdict)
        return verdict

    def _ladder(self, ten: str) -> Decision | None:
        """The lowest-weight-first degrade ladder. The top weight class
        is never ladder-degraded (its protection is the point of the
        plane; its own quotas and deadlines still apply), and each
        weight class runs two rungs behind the one below it — bulk is
        partialed before silver is first touched."""
        level = self.overload_level()
        if level <= 0:
            return None
        rank, top = self._rank(ten)
        if rank >= top:
            return None  # protected: the highest active weight class
        rung = min(level - 2 * rank, 3)
        if rung <= 0:
            return None
        action = _RUNGS[rung]
        if action == "defer":
            return Decision("defer", ten, "admission_defer",
                            wait_s=self._defer_s(), level=level,
                            reason="overload")
        if action == "partial":
            return Decision("partial", ten, "admission_partial",
                            level=level, reason="overload")
        return Decision(
            "reject", ten, "admission_reject",
            retry_after_s=float(Global.admission_retry_after_s),
            level=level, reason="overload")

    # -- bucket / rate plumbing -----------------------------------------
    @staticmethod
    def _burst(quota: TenantQuota) -> float:
        return max(quota.qps * max(float(Global.admission_burst_x), 1.0),
                   1.0)

    def _refill(self, st: _TenantState, quota: TenantQuota,
                now: int) -> None:
        dt = max(now - st.last_refill_us, 0) / 1e6
        st.last_refill_us = now
        st.tokens = min(st.tokens + dt * quota.qps, self._burst(quota))

    @staticmethod
    def _defer_s() -> float:
        ms = int(Global.admission_defer_ms)
        if ms > 0:
            return ms / 1e3
        return 2.0 * max(int(Global.batch_window_us), 0) / 1e6 or 0.002

    def note_reply(self, tenant, rows: int) -> None:
        """Reply-side aggregate-row accounting (the proxy's reply
        observation point): folds this reply's result rows into the
        tenant's rows/s EWMA — the signal the row-budget quota gates
        on."""
        ten = tenant_label(tenant)
        now = self._clock()
        with self._lock:
            st = self._tenants.get(ten)
            if st is None:
                st = self._tenants[ten] = _TenantState(
                    self._burst(self.quota_for(ten)))
                st.last_refill_us = now
            if st.last_rows_us:
                gap_s = max(now - st.last_rows_us, 1) / 1e6
                inst = rows / gap_s
                st.rows_rate = (EWMA_ALPHA * inst
                                + (1 - EWMA_ALPHA) * st.rows_rate)
            st.last_rows_us = now

    # -- bookkeeping ------------------------------------------------------
    def _record(self, d: Decision) -> None:
        emit = False
        with self._lock:
            k = (d.action, d.tenant)
            self._decisions[k] = self._decisions.get(k, 0) + 1
            if d.action != "admit":
                kind = ("admission.quota" if d.cause == "admission_quota"
                        else "admission.shed")
                ek = (kind, d.tenant, d.cause)
                now = self._clock()
                if now - self._last_event.get(ek, -EVENT_COOLDOWN_US) \
                        >= EVENT_COOLDOWN_US:
                    self._last_event[ek] = now
                    emit = True
        if d.action == "admit":
            _M_DECISIONS.labels(decision="admit", tenant=d.tenant).inc()
            return
        # shed charge + journal entry OUTSIDE the state lock (both take
        # their own leaf locks)
        _M_DECISIONS.labels(decision=d.action, tenant=d.tenant).inc()
        if d.cause == "admission_defer":
            maybe_note_shed("admission_defer", d.tenant)
        elif d.cause == "admission_partial":
            maybe_note_shed("admission_partial", d.tenant)
        elif d.cause == "admission_quota":
            maybe_note_shed("admission_quota", d.tenant)
        else:
            maybe_note_shed("admission_reject", d.tenant)
        if emit:
            kind = ("admission.quota" if d.cause == "admission_quota"
                    else "admission.shed")
            emit_event(kind, tenant=d.tenant, rung=d.action,
                       cause=d.cause, level=d.level, reason=d.reason,
                       retry_after_s=round(d.retry_after_s, 3))

    def report(self) -> dict:
        """The /admission body: quotas, per-tenant bucket state,
        decision counts, and the live overload view (every signal read
        through the declared accessor)."""
        with self._lock:
            tenants = {t: {"tokens": round(st.tokens, 2),
                           "rows_rate": round(st.rows_rate, 1)}
                       for t, st in self._tenants.items()}
            decisions = {f"{a}/{t}": n
                         for (a, t), n in self._decisions.items()}
        return {
            "enabled": bool(Global.enable_admission),
            "level": self.overload_level(),
            "inflight_cap": self._inflight_cap(),
            "quotas": {t: {"weight": q.weight, "qps": q.qps,
                           "inflight": q.inflight,
                           "rows_per_s": q.rows_per_s}
                       for t, q in self._quota_map().items()},
            "default_weight": max(int(Global.admission_default_weight), 1),
            "tenants": tenants,
            "decisions": decisions,
            "signals": {
                "lane_queue_delay_ewma":
                    read_admission_input("lane_queue_delay_ewma"),
                "lane_depth": read_admission_input("lane_depth"),
                "pool_utilization":
                    read_admission_input("pool_utilization"),
                "tenant_inflight":
                    read_admission_input("tenant_inflight"),
                "tenant_arrival_rate":
                    read_admission_input("tenant_arrival_rate"),
                "shed_by_cause": read_admission_input("shed_by_cause"),
            },
            "consumed_inputs": list(CONSUMED_INPUTS),
        }

    def reset(self) -> None:
        """Drop controller state (tests / scenario runs)."""
        with self._lock:
            self._tenants.clear()
            self._decisions.clear()
            self._last_event.clear()
        self._level_cache = (-_LEVEL_TTL_US, 0)
        self.last_level = 0


# ---------------------------------------------------------------------------
# weighted-fair queueing (DRR over per-tenant sub-queues)
# ---------------------------------------------------------------------------

class FairQueue:
    """Deficit-round-robin over per-tenant sub-queues.

    The engine pool layers this UNDER its lanes when admission is armed:
    default-lane submissions are pushed with their effective tenant (the
    owner, for standing-query maintenance — priority inheritance) and a
    weight the CALLER resolves (the queue never calls out under its
    lock, keeping ``admission.queue`` a leaf). Each tenant at the head
    of the round earns ``admission_drr_quantum x weight`` credits; one
    credit drains one item — a weight-8 tenant drains 8 items per round
    while a weight-1 flood drains 1, so fairness holds under a hostile
    bulk flood without starving anyone (every active tenant earns
    credit every round)."""

    def __init__(self):
        self._lock = make_lock("admission.queue")
        self._queues: dict[str, deque] = {}  # guarded by: _lock
        self._order: deque = deque()  # guarded by: _lock
        self._deficit: dict[str, float] = {}  # guarded by: _lock
        self._weights: dict[str, int] = {}  # guarded by: _lock
        self._size = 0  # guarded by: _lock

    def push(self, tenant: str, item, weight: int = 1) -> None:
        with self._lock:
            dq = self._queues.get(tenant)
            if dq is None:
                dq = self._queues[tenant] = deque()
                self._order.append(tenant)
                self._deficit.setdefault(tenant, 0.0)
            self._weights[tenant] = max(int(weight), 1)
            dq.append(item)
            self._size += 1

    def pop(self):
        """One DRR pop, or None when empty. Bounded: two passes over
        the active round always yield an item when any queue is
        non-empty (a tenant with an empty queue leaves the round and
        forfeits its deficit — credit never accumulates while idle)."""
        q = max(int(Global.admission_drr_quantum), 1)
        with self._lock:
            if self._size == 0:
                return None
            for _ in range(2 * len(self._order) + 1):
                if not self._order:
                    return None
                t = self._order[0]
                dq = self._queues.get(t)
                if not dq:
                    self._order.popleft()
                    self._queues.pop(t, None)
                    self._deficit.pop(t, None)
                    continue
                if self._deficit.get(t, 0.0) >= 1.0:
                    self._deficit[t] -= 1.0
                    self._size -= 1
                    return dq.popleft()
                self._deficit[t] = (self._deficit.get(t, 0.0)
                                    + q * self._weights.get(t, 1))
                self._order.rotate(-1)
            # defensive: quantum*weight >= 1 makes this unreachable
            for dq in self._queues.values():
                if dq:
                    self._size -= 1
                    return dq.popleft()
            return None

    def __len__(self) -> int:
        with self._lock:
            return self._size

    def depths(self) -> dict[str, int]:
        with self._lock:
            return {t: len(dq) for t, dq in self._queues.items() if dq}


# ---------------------------------------------------------------------------
# process-wide instance + the zero-touch hook
# ---------------------------------------------------------------------------

_controller = AdmissionController()


def get_admission() -> AdmissionController:
    return _controller


def maybe_admission() -> AdmissionController | None:
    """The serving path's hook: one knob check when the plane is off."""
    if not Global.enable_admission:
        return None
    return _controller


# ---------------------------------------------------------------------------
# the /admission report (endpoint + console verb + Monitor line)
# ---------------------------------------------------------------------------

def render_admission(k: int | None = None) -> tuple[str, dict]:
    """(plain-text table, JSON dict) for the /admission endpoint and
    the ``admission`` console verb."""
    rep = _controller.report()
    kk = k if k is not None else max(int(Global.top_k), 1)

    lines = ["wukong-admission  (quotas + degrade ladder)", ""]
    lines.append(f"enabled {str(rep['enabled']).lower()}  "
                 f"overload_level {rep['level']}  "
                 f"inflight_cap {rep['inflight_cap']}")
    lines.append("")
    lines.append(f"{'tenant':<14} {'weight':>6} {'qps':>8} {'infl':>5} "
                 f"{'rows/s':>9} {'tokens':>8} {'rows_rate':>10}")
    quotas = rep["quotas"] or {}
    shown = 0
    for t in sorted(set(quotas) | set(rep["tenants"])):
        if shown >= kk:
            break
        shown += 1
        qd = quotas.get(t)
        st = rep["tenants"].get(t, {})
        lines.append(
            f"{t:<14.14} "
            f"{(qd['weight'] if qd else rep['default_weight']):>6} "
            f"{(qd['qps'] if qd else 0):>8g} "
            f"{(qd['inflight'] if qd else 0):>5} "
            f"{(qd['rows_per_s'] if qd else 0):>9} "
            f"{st.get('tokens', '-'):>8} {st.get('rows_rate', '-'):>10}")
    if not shown:
        lines.append("  (no quotas declared, no tenants seen)")
    if rep["decisions"]:
        lines.append("")
        lines.append("DECISIONS")
        for key, n in sorted(rep["decisions"].items()):
            lines.append(f"  {key}: {n:,}")
    sig = rep["signals"]
    lines.append("")
    lines.append(f"SIGNALS  pool_utilization {sig['pool_utilization']:.0%}")
    for lane, v in sorted(sig["lane_queue_delay_ewma"].items()):
        d = sig["lane_depth"].get(lane)
        lines.append(f"  lane[{lane}]: delay_ewma {v:,.0f}us"
                     + (f", depth {d}" if d is not None else ""))
    for cause, n in sorted(sig["shed_by_cause"].items()):
        lines.append(f"  shed[{cause}]: {n:,}")
    for t in sorted(sig["tenant_inflight"]):
        lines.append(
            f"  tenant[{t}]: inflight {sig['tenant_inflight'][t]}, "
            f"arrival {sig['tenant_arrival_rate'].get(t, 0.0):,.1f} q/s")
    return "\n".join(lines) + "\n", rep
