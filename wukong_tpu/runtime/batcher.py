"""Continuous micro-batching for the serving path (Orca-style coalescing).

The engine layer already amortizes compilation and device syncs across query
batches (``TPUEngine.execute_batch`` / ``MergeExecutor.run_batch_const_many``
— how the emulator reaches its headline throughput), but the *serving* path
(proxy -> engine) executed one query per dispatch, so live traffic never saw
that win. This module closes the gap:

- :func:`template_signature` / :class:`PlanCache` — the proxy-level plan
  cache: repeated template *shapes* (pattern structure with normal-id
  constants abstracted) reuse the optimizer's plan as a positional recipe,
  keyed on signature + store version (dynamic inserts / stream commits bump
  the version, so stale plans can never be applied).
- :func:`batchable` / :func:`fused_key` — the compatibility test and group
  key: queries whose planned chains differ ONLY in the start constant (the
  same shape discipline ``TPUEngine._check_batch_const`` enforces) may fuse.
- :class:`QueryBatcher` — the adaptive coalescer between the proxy and the
  engines: compatible queries arriving within ``batch_window_us`` (or until
  ``batch_max_size``) fuse into ONE chain dispatch over a qid-stamped
  binding table; results are scattered back to each caller's future.
  Incompatible or deadline-tight queries bypass untouched, and with
  ``enable_batching`` off (the default) the serving path never reaches this
  module at all.
- :class:`FusedGroup` — the dispatch unit: builds the fused query (start
  constant rewritten to a seeded known var next to a qid column), runs it on
  the CPU or TPU engine (both handle seeded chains), splits the result table
  by qid, applies per-member deadline/budget accounting (one member's
  timeout degrades only that member), and falls back to per-query execution
  when the fused dispatch fails or the batch breaker is open.
- :func:`heavy_batchable` / :class:`HeavyGroup` — the HEAVY lane (the
  Wukong+G posture: index-origin traffic batches onto the accelerator
  instead of serializing one-at-a-time on one engine): identical
  index-origin blind templates coalesce into ONE sliced device dispatch
  (``TPUEngine.execute_batch_index``, slice mode) whose per-slice counts
  sum to the query total and settle every waiter; dispatches over an index
  list past ``heavy_split_threshold`` split across pool engines by slice
  range (``mt_factor``/``mt_tid`` copies) with a gather barrier that
  reassembles byte-identical per-member results and re-runs a failed slice
  inline (an engine death degrades one slice, never strands a waiter).
  Fused heavy groups ride the scheduler's weighted ``heavy`` lane so they
  can never occupy every engine (``heavy_lane_pct``).

Row-order fidelity: the CPU/TPU kernels expand row-major and filter
in-place, so a member's rows in the fused table appear contiguously and in
exactly the order its own sequential execution would produce — batched
results are byte-identical to unbatched ones (tests/test_batcher.py pins
this against the independent BGP oracle; tests/test_heavy.py pins the
heavy counts the same way).
"""

from __future__ import annotations

import threading

import numpy as np

from wukong_tpu.analysis.lockdep import declare_leaf, make_condition, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs import activate, get_recorder, get_registry, maybe_start_trace
from wukong_tpu.obs.slo import maybe_note_shed
from wukong_tpu.runtime.resilience import CircuitBreaker, mark_partial
from wukong_tpu.sparql.ir import Pattern, SPARQLQuery
from wukong_tpu.types import NORMAL_ID_START, PREDICATE_ID, TYPE_ID, AttrType
from wukong_tpu.utils.errors import (
    BudgetExceeded,
    ErrorCode,
    QueryTimeout,
    WukongError,
)
from wukong_tpu.utils.logger import log_warn
from wukong_tpu.utils.lru import LRUCache
from wukong_tpu.utils.timer import get_usec

_SID = int(AttrType.SID_t)

# batcher observability (README metrics table): occupancy + flush reasons
# are the knobs' feedback loop — a window that always flushes at size 1
# is pure added latency, one that always hits batch_max_size could go wider
_M_SUBMITTED = get_registry().counter(
    "wukong_batch_submitted_total", "Queries admitted into the batcher")
_M_BYPASS = get_registry().counter(
    "wukong_batch_bypass_total",
    "Queries that skipped the batcher", labels=("reason",))
_M_FLUSH = get_registry().counter(
    "wukong_batch_flush_total", "Group flushes", labels=("reason",))
_M_FUSED = get_registry().counter(
    "wukong_batch_fused_queries_total", "Queries served by a fused dispatch")
_M_FALLBACK = get_registry().counter(
    "wukong_batch_fallback_total",
    "Fused dispatches degraded to per-query execution", labels=("reason",))
_M_MEMBER_TIMEOUT = get_registry().counter(
    "wukong_batch_member_timeouts_total",
    "Members individually degraded by their own deadline/budget")
_M_OCCUPANCY = get_registry().histogram(
    "wukong_batch_occupancy", "Group size at flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_M_PLAN_CACHE = get_registry().counter(
    "wukong_plan_cache_total",
    "Plan cache outcomes (hit/miss per lookup; uncacheable per refused "
    "shape; invalidated per entry dropped by a stale recipe or a clear)",
    labels=("result",))
_M_PARSE_CACHE = get_registry().counter(
    "wukong_parse_cache_total",
    "Parse cache outcomes (hit/miss per lookup; uncacheable per "
    "unpicklable parse artifact)", labels=("result",))

# heavy-lane observability: fused heavy dispatch counts, split fan-out, and
# the group-size histogram feed the /top lane view and the Monitor's
# rolling heavy-lane line
_M_HEAVY_FUSED = get_registry().counter(
    "wukong_batch_heavy_fused_total",
    "Queries served by a fused heavy (index-origin) dispatch")
_M_HEAVY_DISPATCH = get_registry().counter(
    "wukong_batch_heavy_dispatch_total",
    "Fused heavy dispatches", labels=("mode",))
_M_HEAVY_SLICES = get_registry().counter(
    "wukong_batch_heavy_slices_total",
    "Slice parts dispatched by split heavy groups")
_M_HEAVY_FALLBACK = get_registry().counter(
    "wukong_batch_heavy_fallback_total",
    "Heavy-lane degradations", labels=("reason",))
_M_HEAVY_OCC = get_registry().histogram(
    "wukong_batch_heavy_occupancy", "Heavy group size at flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
# split-vs-no-split decisions per fused heavy dispatch: the observable
# behind heavy_split_threshold tuning (bench.py --serve-mixed prints the
# counts so the threshold can be re-tuned against real worlds)
_M_HEAVY_SPLIT = get_registry().counter(
    "wukong_batch_heavy_split_total",
    "Fused heavy dispatch split decisions", labels=("decision",))


# ---------------------------------------------------------------------------
# template signatures + the plan cache
# ---------------------------------------------------------------------------

def template_signature(q: SPARQLQuery):
    """Pre-plan template signature: the pattern structure with normal-id
    constants abstracted out. Two queries with the same signature may share
    one plan (any valid join order yields the same result set). Returns
    None for shapes the plan cache does not cover (unions/optionals plan
    recursively; attr patterns ride along fine)."""
    pg = q.pattern_group
    if pg.unions or pg.optional or not pg.patterns:
        return None

    def elem(v: int):
        if v < 0:
            return ("v", v)
        if v >= NORMAL_ID_START:
            return "C"  # abstracted: the template's variable constant
        return ("k", v)  # type ids / specials: structural, kept concrete

    return tuple(
        (elem(p.subject),
         p.predicate if p.predicate >= 0 else ("v", p.predicate),
         int(p.direction), elem(p.object), int(p.pred_type))
        for p in pg.patterns)


def build_plan_recipe(parsed_patterns: list, q: SPARQLQuery):
    """Encode a planned query as a positional recipe over its parsed
    (pre-plan) patterns, so the plan can be replayed onto any same-signature
    query with different constants. Returns None when the plan is not
    safely replayable (planner-proved-empty plans depend on the concrete
    constants; duplicated abstracted constants are positionally ambiguous).
    """
    if q.planner_empty or q.corun_enabled:
        return None
    # parsed value -> positions; field index 0/1/2 = subject/predicate/object
    slots: dict[int, list] = {}
    for i, (s, p, _d, o, _t) in enumerate(parsed_patterns):
        for fi, v in ((0, s), (1, p), (2, o)):
            if v >= 0:
                slots.setdefault(v, []).append((i, fi))

    def enc(v: int):
        if v < 0:
            return ("v", v)
        sl = slots.get(v)
        if sl is None:
            # plan-introduced structural ids only (index-start rewrites)
            return ("lit", v) if v in (PREDICATE_ID, TYPE_ID) else None
        # positions that are concrete in the signature (predicates, type
        # ids) pin the value — no substitution needed
        if any(fi == 1 or v < NORMAL_ID_START for (_i, fi) in sl):
            return ("lit", v)
        if len(sl) > 1:
            return None  # ambiguous duplicate of an abstracted constant
        return ("slot", sl[0])

    recipe = []
    for pat in q.pattern_group.patterns:
        es, ep, eo = enc(pat.subject), enc(pat.predicate), enc(pat.object)
        if es is None or ep is None or eo is None:
            return None
        recipe.append((es, ep, int(pat.direction), eo, int(pat.pred_type)))
    return tuple(recipe)


def apply_plan_recipe(q: SPARQLQuery, recipe) -> bool:
    """Replay a cached plan recipe onto a freshly parsed same-signature
    query. Builds the new pattern list fully before swapping it in."""
    pats = q.pattern_group.patterns

    def dec(e):
        kind, val = e
        if kind in ("v", "lit"):
            return val
        i, fi = val
        p = pats[i]
        return (p.subject, p.predicate, p.object)[fi]

    try:
        new = [Pattern(dec(es), dec(ep), d, dec(eo), pt)
               for (es, ep, d, eo, pt) in recipe]
    except (IndexError, TypeError):  # stale/foreign recipe: replan
        return False
    q.pattern_group.patterns[:] = new
    return True


class PlanCache:
    """Template signature + store version -> plan recipe (bounded LRU).

    Keying on the store version makes dynamic inserts / stream commits
    self-invalidating: the bumped version simply never matches a stale
    entry, and the LRU evicts the dead keys."""

    def __init__(self, maxsize: int | None = None):
        self._lru = LRUCache(maxsize or Global.plan_cache_size)

    def lookup(self, q: SPARQLQuery, sig, version: int) -> bool:
        if sig is None:
            return False
        recipe = self._lru.get((sig, version))
        if recipe is None:
            _M_PLAN_CACHE.labels(result="miss").inc()
            return False
        if not apply_plan_recipe(q, recipe):
            # an entry existed but could not apply (stale/foreign recipe):
            # that is an invalidation event, not a cold miss — drop it so
            # the next lookup misses cleanly instead of re-failing
            self._lru.pop((sig, version))
            _M_PLAN_CACHE.labels(result="invalidated").inc()
            return False
        _M_PLAN_CACHE.labels(result="hit").inc()
        return True

    def record(self, parsed_patterns, q: SPARQLQuery, sig, version: int) -> None:
        if sig is None:
            return
        recipe = build_plan_recipe(parsed_patterns, q)
        if recipe is not None:
            self._lru.put((sig, version), recipe)
        else:
            # planner-empty / corun / ambiguous-const shapes: the plan is
            # not safely replayable — the serving-cache observatory
            # mirrors exactly this refusal set (obs/reuse.py classify)
            _M_PLAN_CACHE.labels(result="uncacheable").inc()

    def put_aux(self, kind: str, sig, version, value) -> None:
        """Overwrite one auxiliary plan fact (the WCOJ measured-blowup
        feedback path: an execution-time measurement replaces the
        estimate-derived memo under the SAME key, so the next
        ``aux()`` lookup serves the corrected decision)."""
        if sig is None:
            return
        self._lru.put((kind, sig, version), value)

    def aux(self, kind: str, sig, version, compute):
        """Memoized per-template auxiliary plan facts (device slice count,
        lane classification): keyed like a plan recipe on signature + store
        version, so a dynamic insert / stream commit makes stale entries
        unreachable the same way. ``sig`` None computes uncached."""
        if sig is None:
            return compute()
        key = (kind, sig, version)
        v = self._lru.get(key)
        if v is None:
            v = compute()
            self._lru.put(key, v)
        return v

    def clear(self) -> None:
        n = len(self._lru)
        if n:
            # a store-change clear (dynamic load / stream commit /
            # restore) invalidates every cached recipe and aux fact
            _M_PLAN_CACHE.labels(result="invalidated").inc(n)
        self._lru.clear()

    def stats(self) -> dict:
        return self._lru.stats()


def snapshot_patterns(q: SPARQLQuery) -> list:
    """Pre-plan pattern snapshot for build_plan_recipe (plan mutates the
    list in place)."""
    return [(p.subject, p.predicate, p.direction, p.object, p.pred_type)
            for p in q.pattern_group.patterns]


# ---------------------------------------------------------------------------
# batchability + group key
# ---------------------------------------------------------------------------

def batchable(q: SPARQLQuery) -> bool:
    """True when a PLANNED query may join a fused group: a const-start
    chain of const-SID-predicate steps, each anchored on a bound column —
    the ``_check_batch_const`` shape — with no result-shaping modifiers
    (those apply per member and would be wrong on the fused table)."""
    pg = q.pattern_group
    if pg.unions or pg.optional:
        return False
    if q.distinct or q.orders or q.limit >= 0 or q.offset > 0:
        return False
    if q.mt_factor > 1 or q.planner_empty or q.corun_enabled:
        return False
    pats = pg.patterns
    if not pats:
        return False
    c0 = pats[0].subject
    if c0 < NORMAL_ID_START:  # needs a plain const start (not index/type)
        return False
    if pats[0].object >= 0:  # first step must bind a fresh var
        return False
    known = {c0}
    for k, p in enumerate(pats):
        if p.predicate < 0 or p.pred_type != _SID:
            return False
        if k == 0:
            if p.subject != c0:
                return False
        elif p.subject == c0:
            # mid-chain re-anchor on the start constant: sequential
            # execution runs const_to_known, which needs a bound object
            if not (p.object < 0 and p.object in known):
                return False
        elif not (p.subject < 0 and p.subject in known):
            return False
        for v in (p.subject, p.object):
            if v < 0:
                known.add(v)
    return True


def fused_key(q: SPARQLQuery):
    """Group key for a planned batchable query: every occurrence of the
    start constant abstracted, everything else (predicates, other
    constants, filters, projection, blind mode) concrete — members of one
    group differ ONLY in where they start."""
    pats = q.pattern_group.patterns
    c0 = pats[0].subject

    def el(v: int):
        return "<start>" if v == c0 else v

    return (tuple((el(p.subject), p.predicate, int(p.direction),
                   el(p.object), int(p.pred_type)) for p in pats),
            repr(q.pattern_group.filters),
            tuple(q.result.required_vars),
            bool(q.result.blind))


def heavy_batchable(q: SPARQLQuery) -> bool:
    """True when a PLANNED query may join a fused HEAVY group: an
    index-origin chain of const-SID steps anchored on bound columns (the
    ``TPUEngine._check_batch_index`` shape), blind (the sliced device
    dispatch returns per-slice row counts, not tables), with no filters or
    result-shaping modifiers (both would need the materialized table)."""
    pg = q.pattern_group
    if pg.unions or pg.optional or pg.filters:
        return False
    if not q.result.blind:
        return False
    if q.distinct or q.orders or q.limit >= 0 or q.offset > 0:
        return False
    if q.mt_factor > 1 or q.planner_empty or q.corun_enabled:
        return False
    pats = pg.patterns
    if not pats:
        return False
    try:
        if not q.start_from_index():
            return False
    except WukongError:
        return False
    p0 = pats[0]
    if p0.predicate not in (PREDICATE_ID, TYPE_ID) or p0.object >= 0:
        return False
    known = {p0.object}
    for k, p in enumerate(pats):
        if p.predicate < 0 or p.pred_type != _SID:
            return False
        if k > 0:
            if not (p.subject < 0 and p.subject in known):
                return False
            if p.object < 0:
                known.add(p.object)
    return True


def heavy_key(q: SPARQLQuery):
    """Group key for a planned heavy-batchable query: the concrete pattern
    chain. Index-origin queries carry no per-member start constant, so
    members of one heavy group are the SAME template instance — one sliced
    dispatch computes the chain once and settles every waiter (the light
    path's coalescing win becomes request collapsing here)."""
    return ("heavy", tuple(
        (p.subject, p.predicate, int(p.direction), p.object,
         int(p.pred_type)) for p in q.pattern_group.patterns))


# ---------------------------------------------------------------------------
# the fused dispatch unit
# ---------------------------------------------------------------------------

class _Pending:
    """One caller's slot in a group: the planned query, its resilience
    context, and the future the serving thread blocks on."""

    __slots__ = ("q", "deadline", "trace", "event", "error", "t0_us")

    def __init__(self, q: SPARQLQuery):
        self.q = q
        self.deadline = getattr(q, "deadline", None)
        self.trace = getattr(q, "trace", None)
        self.event = threading.Event()
        self.error: BaseException | None = None
        self.t0_us = get_usec()

    def wait(self, timeout: float | None = None) -> SPARQLQuery:
        if not self.event.wait(timeout):
            raise TimeoutError("batched query still pending")
        if self.error is not None:
            raise self.error
        return self.q


def _fused_deadline(members: list):
    """The fused chain's Deadline: the LOOSEST member wall-clock (a tight
    member is settled per-member after the dispatch, never failing the
    group) and the SUM of member row budgets — present only when every
    member carries the respective constraint."""
    from wukong_tpu.runtime.resilience import Deadline

    rems, budgets, no_wall = [], [], False
    for m in members:
        if m.deadline is None:
            return None  # an unconstrained member: the group is too
        rem = m.deadline.remaining_s()
        if rem is None:
            no_wall = True  # that member has a budget but no wall clock
        else:
            rems.append(rem)
        budgets.append(m.deadline.budget_rows)
    timeout_ms = 0 if (no_wall or not rems) else int(max(rems) * 1e3) + 1
    budget = sum(budgets) if budgets and all(b > 0 for b in budgets) else 0
    if timeout_ms <= 0 and budget <= 0:
        return None
    return Deadline(timeout_ms, budget)


class FusedGroup:
    """A flushed group of same-template queries, executed as one unit.

    The engine pool's ``batch`` lane pops a group whole (work stealing
    cannot split it) and calls :meth:`run` with the popping engine; an
    inline dispatch (no pool) passes the batcher's own engine."""

    is_fused_group = True
    lane = "batch"  # which pool lane flushed groups ride
    BREAKER_SITE = "batch.dispatch"  # CircuitBreaker + settlement key

    def __init__(self, members: list, batcher: "QueryBatcher",
                 engine=None, reason: str = "window", key=None):
        self.members = members
        self.batcher = batcher
        self.engine = engine  # preferred engine (the TPU path), or None
        self.reason = reason
        # group key for per-template iteration chaining (heavy lane):
        # same-key arrivals accumulate while THIS dispatch runs and flush
        # the moment it completes. None = no chaining (light groups keep
        # the global iteration-boundary drain).
        self.key = key
        # owning tenant (groups coalesce per-template; the first member
        # names the group) — the pool's per-tenant heavy-lane slot
        # accounting (_heavy_pick_locked) keys on this tag
        self.tenant = (getattr(getattr(members[0], "q", None), "tenant",
                               None) or "default") if members else "default"
        # in-flight accounting settled exactly once; the flag needs its
        # own lock because run()'s finally (engine thread) can race
        # fail_all() from the scheduler's death handler or the flusher —
        # an unserialized check-then-set double-decremented the batcher's
        # _inflight count (found by the guarded-by gate)
        self._note_lock = make_lock("batch.group")
        self._noted = False  # guarded by: _note_lock

    # -- completion plumbing -------------------------------------------
    @staticmethod
    def _finish(m: _Pending) -> None:
        m.event.set()

    def _note_once(self) -> None:
        with self._note_lock:
            if self._noted:
                return
            self._noted = True
        # outside the group lock: _note_done takes the batcher condition
        self.batcher._note_done(self.key)

    def fail_all(self, exc: BaseException) -> None:
        """Infrastructure failure (dead pool / engine-thread death): the
        waiters must never strand."""
        for m in self.members:
            if not m.event.is_set():
                m.error = exc
                m.event.set()
        self._note_once()

    # -- execution ------------------------------------------------------
    def run(self, engine=None) -> None:
        try:
            self._run_impl(engine)
        except BaseException as e:  # the waiters must never strand
            self.fail_all(e)
            raise
        finally:
            self._note_once()

    def _run_impl(self, engine) -> None:
        b = self.batcher
        live = []
        for m in self.members:
            if m.deadline is not None and m.deadline.expired():
                # shed in the batch queue: mirror the pool's load shedding
                # (structured timeout, group unaffected)
                _M_MEMBER_TIMEOUT.inc()
                maybe_note_shed("batch_window",
                                getattr(m.q, "tenant", "default"))
                mark_partial(m.q, QueryTimeout("deadline expired in batch window"))
                self._finish(m)
            else:
                live.append(m)
        if not live:
            return
        if len(live) == 1 and not self._fuse_solo(live[0]):
            self._run_single(live[0], engine)
            return
        if not b.breaker.allow(self.BREAKER_SITE):
            # breaker open: don't pay the fused failure again — serve the
            # members per-query until the half-open probe closes it
            self._count_fallback("breaker_open")
            for m in live:
                self._run_single(m, engine)
            return
        fq = None
        try:
            fq = self._run_fused(live, engine)
        except Exception as e:
            b.breaker.record_failure(self.BREAKER_SITE)
            self._count_fallback("dispatch_error")
            log_warn(f"fused batch dispatch failed ({e!r:.120}); "
                     f"degrading {len(live)} queries to per-query execution")
            for m in live:
                self._run_single(m, engine)
            return
        if fq.result.status_code != ErrorCode.SUCCESS:
            # QueryTimeout/BudgetExceeded/ShardUnavailable surface as the
            # fused reply status — same degradation: per-query execution
            # settles each member against its own deadline/breakers
            b.breaker.record_failure(self.BREAKER_SITE)
            self._count_fallback(fq.result.status_code.name.lower())
            for m in live:
                self._run_single(m, engine)
            return
        b.breaker.record_success(self.BREAKER_SITE)
        self._scatter(fq, live)

    def _fuse_solo(self, m: _Pending) -> bool:
        """May a lone live member still take the fused path? The light
        fused query adds only overhead at size 1; the heavy lane overrides
        this — a single huge index-origin query still profits from the
        sliced/split dispatch."""
        return False

    @staticmethod
    def _count_fallback(reason: str) -> None:
        _M_FALLBACK.labels(reason=reason).inc()

    def _run_single(self, m: _Pending, engine) -> None:
        """Per-query degradation path (and the natural size-1 flush)."""
        eng = self.engine or engine or self.batcher.cpu
        try:
            eng.execute(m.q, from_proxy=True)
        except Exception as e:  # engine contract: errors become the reply;
            m.error = e        # anything else is infrastructure
        self._finish(m)

    def _run_fused(self, live: list, engine):
        """Build + dispatch the fused query: [qid, start-const] seed table,
        start constant rewritten to a seeded known var, one chain run."""
        eng = self.engine or engine or self.batcher.cpu
        q0 = live[0].q
        pats0 = q0.pattern_group.patterns
        c0 = pats0[0].subject
        consts = np.asarray(
            [m.q.pattern_group.patterns[0].subject for m in live],
            dtype=np.int64)
        B = len(live)

        low = min((v for p in pats0 for v in (p.subject, p.predicate, p.object)
                   if v < 0), default=0)
        vq, vs = low - 1, low - 2
        fq = SPARQLQuery()
        fq.pattern_group.patterns = [
            Pattern(vs if p.subject == c0 else p.subject, p.predicate,
                    p.direction, vs if p.object == c0 else p.object,
                    p.pred_type)
            for p in pats0]
        fq.pattern_group.filters = q0.pattern_group.filters
        res = fq.result
        res.nvars = q0.result.nvars + 2
        res.set_table(np.column_stack(
            [np.arange(B, dtype=np.int64), consts]))
        res.add_var2col(vq, 0)
        res.add_var2col(vs, 1)
        res.blind = False  # the fused table IS the members' results
        fq.deadline = _fused_deadline(live)

        # batch.dispatch span: its own sampled trace for the flight
        # recorder, plus a linking event on every member trace
        ftrace = maybe_start_trace(kind="batch")
        gid = ftrace.trace_id if ftrace is not None else None
        member_tids = [m.trace.trace_id for m in live if m.trace is not None]
        for m in live:
            if m.trace is not None:
                m.trace.event("batch.dispatch", group=gid, size=B,
                              reason=self.reason)
        t0 = get_usec()
        if ftrace is None:
            eng.execute(fq, from_proxy=False)
        else:
            fq.trace = ftrace
            with activate(ftrace):
                with ftrace.span("batch.dispatch", size=B,
                                 reason=self.reason, members=member_tids):
                    eng.execute(fq, from_proxy=False)
            get_recorder().on_complete(ftrace, fq.result.status_code)
        # latency attribution (obs/profile.py): a member's execution
        # happened inside THIS fused dispatch, not on its own trace —
        # stamp the dispatch span's duration on every member so
        # decompose() can attribute the member's execute component
        # through its FusedGroup (works whether or not the group's own
        # trace was sampled)
        dispatch_us = get_usec() - t0
        for m in live:
            if m.trace is not None:
                m.trace.event("batch.settled", group=gid,
                              dispatch_us=dispatch_us)
        return fq

    def _scatter(self, fq: SPARQLQuery, live: list) -> None:
        """Split the fused table by qid and settle each member against its
        own deadline/budget — one member's expiry degrades only itself."""
        tbl = np.asarray(fq.result.table)
        C = fq.result.col_num
        member_v2c = {v: c - 2 for v, c in fq.result.v2c_map.items()
                      if c >= 2}
        qids = tbl[:, 0] if len(tbl) else np.empty(0, dtype=np.int64)
        _M_FUSED.inc(len(live))
        for i, m in enumerate(live):
            rows = (tbl[qids == i][:, 2:] if len(tbl)
                    else np.empty((0, max(C - 2, 0)), dtype=np.int64))
            res = m.q.result
            res.v2c_map = dict(member_v2c)
            res.set_table(np.ascontiguousarray(rows).astype(np.int64))
            res.col_num = max(C - 2, 0)
            m.q.pattern_step = len(m.q.pattern_group.patterns)
            try:
                if m.deadline is not None:
                    m.deadline.charge_rows(res.nrows, "batch.dispatch")
                    m.deadline.check("batch.dispatch")
                self.batcher.cpu._final_process(m.q)
            except (QueryTimeout, BudgetExceeded) as e:
                _M_MEMBER_TIMEOUT.inc()
                maybe_note_shed("batch_settle",
                                getattr(m.q, "tenant", "default"))
                mark_partial(m.q, e)
            except Exception as e:
                m.error = e
            self._finish(m)


# ---------------------------------------------------------------------------
# the heavy lane: fused index-origin dispatches with slice-range splitting
# ---------------------------------------------------------------------------

# the slice claim flag is a pure check-and-set under its own lock — innermost
declare_leaf("batch.slice")

#: short grace before the gather thread claims a still-PENDING slice and
#: runs it inline: pool engines normally pop within ~ms (wake-on-submit),
#: so a slice not started after this is better done here than waited on
SLICE_CLAIM_GRACE_S = 0.02
#: how long the gather barrier waits for a RUNNING slice before declaring
#: the dispatch wedged (a dead/stuck engine must never strand the group)
HEAVY_GATHER_WAIT_S = 30.0


class _HeavySlice:
    """One slice-range part of a split heavy dispatch.

    A fire-and-forget pool item (lane=``heavy``, the batch lane's
    run/fail_all contract) claimable exactly ONCE: the gather thread runs
    stragglers inline without double execution, and a pool engine popping
    an already-claimed slice no-ops. An engine-thread death mid-dispatch
    reaches :meth:`fail_all` via the scheduler's death handler, so the
    gather barrier always wakes — it then re-runs the failed slice inline
    (fallback per-slice, never a stranded waiter)."""

    lane = "heavy"
    # a slice continues an ALREADY-ADMITTED group (which holds the lane's
    # weighted slot): the scheduler pops it cap-exempt, or a cap of 1
    # would deadlock the gather behind its own group's slot
    heavy_continuation = True

    __slots__ = ("group", "fq", "b", "event", "error", "total",
                 "_claim_lock", "_claimed")

    def __init__(self, group: "HeavyGroup", fq: SPARQLQuery, b: int):
        self.group = group
        self.fq = fq  # mt-sliced carrier query (this part's slice range)
        self.b = b
        self.event = threading.Event()
        self.error: BaseException | None = None
        self.total = 0
        self._claim_lock = make_lock("batch.slice")
        self._claimed = False  # guarded by: _claim_lock

    def claim(self) -> bool:
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def run(self, engine=None) -> None:
        """Pool-engine entry (and the gather thread's inline entry)."""
        if not self.claim():
            return  # already run inline by the gather thread
        self._execute()

    def _execute(self) -> None:
        ok = False
        try:
            self.total = self.group._run_slice(self.fq, self.b)
            ok = True
        except Exception as e:
            self.error = e
        finally:
            if not ok and self.error is None:
                # a thread-killing BaseException still executes this
                # finally: the gather barrier must see a failure, not a
                # zero-count success
                self.error = RuntimeError("heavy slice aborted")
            self.event.set()

    def fail_all(self, exc: BaseException) -> None:
        """Scheduler death-handler / dead-pool contract."""
        if not self.event.is_set():
            self.error = exc
            self.event.set()


class HeavyGroup(FusedGroup):
    """A flushed group of IDENTICAL index-origin (heavy) templates.

    One sliced device dispatch (``execute_batch_index``, slice mode)
    computes the chain once; the summed per-slice counts settle every
    member against its own deadline/budget (blind semantics — heavy
    serving traffic never ships result tables). Dispatches whose index
    list reaches ``heavy_split_threshold`` split across pool engines by
    slice range (``mt_factor`` copies) behind a gather barrier."""

    lane = "heavy"
    BREAKER_SITE = "batch.heavy.dispatch"

    def _fuse_solo(self, m: _Pending) -> bool:
        # a single huge heavy query still splits across engines; below the
        # split threshold, plain execution is strictly cheaper
        return self._split_factor(m.q) > 1

    @staticmethod
    def _count_fallback(reason: str) -> None:
        _M_HEAVY_FALLBACK.labels(reason=reason).inc()

    # -- dispatch -------------------------------------------------------
    def _split_factor(self, q0: SPARQLQuery) -> int:
        """How many slice-range parts this dispatch fans out to: bounded
        by ``heavy_split_max`` and the pool's live engine count, and only
        past ``heavy_split_threshold`` index rows (small scans would pay
        the fan-out for nothing). Memoized per group — solo dispatches ask
        once in _fuse_solo and again in _run_fused."""
        s = getattr(self, "_split_s", None)
        if s is None:
            s = self._split_s = self._split_factor_impl(q0)
        return s

    def _split_factor_impl(self, q0: SPARQLQuery) -> int:
        if self.batcher.tpu is None or Global.heavy_split_max <= 1:
            return 1
        pool = self.batcher.pool()
        if pool is None:
            return 1
        p0 = q0.pattern_group.patterns[0]
        try:
            real = len(self.batcher.tpu.g.get_index(p0.subject, p0.direction))
        except Exception:
            return 1
        if real < max(int(Global.heavy_split_threshold), 1):
            return 1
        return max(min(int(Global.heavy_split_max), pool.alive_count()), 1)

    def _carrier(self, q0: SPARQLQuery, S: int, k: int,
                 deadline) -> SPARQLQuery:
        """A lightweight execution carrier sharing q0's (read-only) planned
        patterns: the member query itself is never mutated by the fused
        dispatch. S/k select this carrier's slice range (mt semantics)."""
        fq = SPARQLQuery()
        fq.pattern_group.patterns = list(q0.pattern_group.patterns)
        fq.planner_empty = q0.planner_empty
        fq.result.blind = True
        fq.mt_factor, fq.mt_tid = S, k
        fq.deadline = deadline
        return fq

    def _run_slice(self, fq: SPARQLQuery, b: int) -> int:
        """One sliced device dispatch; returns its summed row count."""
        from wukong_tpu.runtime import faults

        faults.site("batch.heavy.dispatch")
        counts = self.batcher.tpu.execute_batch_index(fq, b, slice_mode=True)
        return int(np.asarray(counts).sum())

    def _run_split(self, q0: SPARQLQuery, b: int, S: int, deadline) -> int:
        """Fan the dispatch out to S slice-range parts across the pool's
        heavy lane and gather. The gather thread contributes slice 0
        itself; stragglers the pool never picked up are claimed and run
        inline; a failed slice (engine death, injected fault) is re-run
        inline — per-slice fallback, so one dead engine costs one retry,
        not the whole group."""
        pool = self.batcher.pool()
        slices = [_HeavySlice(self, self._carrier(q0, S, k, deadline), b)
                  for k in range(S)]
        _M_HEAVY_DISPATCH.labels(mode="split").inc()
        _M_HEAVY_SLICES.inc(S)
        for s in slices[1:]:
            try:
                pool.submit(s, lane="heavy")
            except Exception:
                pass  # claimed and run inline below
        slices[0].run(None)  # the gather thread works its own share first
        for s in slices[1:]:
            if not s.event.wait(SLICE_CLAIM_GRACE_S):
                if s.claim():  # not started yet: run the straggler inline
                    s._execute()
                elif not s.event.wait(HEAVY_GATHER_WAIT_S):
                    raise RuntimeError(
                        "heavy gather barrier timed out on a claimed slice")
        for s in slices:
            if s.error is not None:
                # per-slice fallback: one inline retry on the gather
                # thread; a second failure degrades the whole group to
                # per-query execution via the caller's error path
                self._count_fallback("slice_retry")
                log_warn(f"heavy slice failed ({s.error!r:.120}); "
                         "re-running the slice inline")
                s.error = None
                s.total = self._run_slice(s.fq, s.b)
        return sum(s.total for s in slices)

    def _run_fused(self, live: list, engine):
        """One fused heavy dispatch for the whole group. Returns a carrier
        query whose ``_heavy_total`` is the chain's row count (blind) —
        the base class's status check + :meth:`_scatter` settle it."""
        if self.batcher.tpu is None:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "heavy fusion needs a device engine")
        q0 = live[0].q
        B = len(live)
        b = self.batcher.heavy_b(q0)
        S = self._split_factor(q0)
        dl = _fused_deadline(live)

        ftrace = maybe_start_trace(kind="batch")
        gid = ftrace.trace_id if ftrace is not None else None
        member_tids = [m.trace.trace_id for m in live if m.trace is not None]
        for m in live:
            if m.trace is not None:
                m.trace.event("batch.dispatch", group=gid, size=B,
                              reason=self.reason, lane="heavy")

        _M_HEAVY_SPLIT.labels(
            decision="split" if S > 1 else "no_split").inc()

        def dispatch() -> int:
            if S > 1:
                return self._run_split(q0, b, S, dl)
            _M_HEAVY_DISPATCH.labels(mode="single").inc()
            return self._run_slice(self._carrier(q0, 1, 0, dl), b)

        t0 = get_usec()
        if ftrace is None:
            total = dispatch()
        else:
            with activate(ftrace):
                with ftrace.span("batch.dispatch", size=B, lane="heavy",
                                 reason=self.reason, members=member_tids,
                                 slices=S):
                    total = dispatch()
            get_recorder().on_complete(ftrace, ErrorCode.SUCCESS)
        dispatch_us = get_usec() - t0
        for m in live:
            if m.trace is not None:
                m.trace.event("batch.settled", group=gid,
                              dispatch_us=dispatch_us)
        fq = SPARQLQuery()
        fq._heavy_total = total
        return fq

    def _scatter(self, fq: SPARQLQuery, live: list) -> None:
        """Settle every member with the fused count (blind semantics) —
        per-member deadline/budget accounting mirrors the light path."""
        total = int(getattr(fq, "_heavy_total", 0))
        _M_HEAVY_FUSED.inc(len(live))
        for m in live:
            res = m.q.result
            res.nrows = total
            m.q.pattern_step = len(m.q.pattern_group.patterns)
            try:
                if m.deadline is not None:
                    m.deadline.charge_rows(total, "batch.heavy.dispatch")
                    m.deadline.check("batch.heavy.dispatch")
                self.batcher.cpu._final_process(m.q)
            except (QueryTimeout, BudgetExceeded) as e:
                _M_MEMBER_TIMEOUT.inc()
                maybe_note_shed("batch_settle",
                                getattr(m.q, "tenant", "default"))
                mark_partial(m.q, e)
            except Exception as e:
                m.error = e
            self._finish(m)


# ---------------------------------------------------------------------------
# the batcher
# ---------------------------------------------------------------------------

class _OpenGroup:
    __slots__ = ("members", "flush_at_us", "heavy", "chained")

    def __init__(self, flush_at_us: int, heavy: bool = False):
        self.members: list[_Pending] = []
        self.flush_at_us = flush_at_us
        self.heavy = heavy
        # True once the same-key dispatch this group queued behind has
        # completed: the flusher releases it immediately (reason "chain")
        self.chained = False


class QueryBatcher:
    """Adaptive request coalescer between the proxy and the engines.

    ``offer(q)`` admits a planned query and returns its :class:`_Pending`
    future, or None when the query must bypass (incompatible shape /
    deadline too tight) — the caller then executes it directly. A
    background flusher dispatches groups at ``batch_window_us`` age;
    ``batch_max_size`` flushes immediately. Groups ride the engine pool's
    ``batch`` lane when a pool is running (drained as a unit), else they
    run inline on the flusher thread.
    """

    def __init__(self, cpu_engine, tpu_engine=None, pool=None,
                 suggest_heavy_b=None):
        self.cpu = cpu_engine
        self.tpu = tpu_engine
        self._pool = pool  # object, or zero-arg callable returning one/None
        # plan-cache-backed heavy slice sizing (proxy.heavy_index_batch);
        # None falls back to an uncached suggest_index_batch call
        self._suggest_heavy_b = suggest_heavy_b
        self.breaker = CircuitBreaker()
        self._lock = make_condition("batcher.groups")
        self._groups: dict = {}  # guarded by: _lock
        # dispatches currently executing: the continuous-batching signal —
        # while one runs, arrivals accumulate; when idle, a lone query
        # flushes immediately instead of paying the window
        self._inflight = 0  # guarded by: _lock
        # per-template in-flight dispatch counts (heavy iteration
        # chaining): while a heavy template's dispatch runs, same-key
        # arrivals accumulate past their window and flush the moment it
        # completes — with steady light traffic the GLOBAL inflight count
        # never hits 0, so the drain_now boundary alone would leave heavy
        # groups flushing at window age (occupancy ~1, no collapsing)
        self._inflight_keys: dict = {}  # guarded by: _lock
        self._drain_now = False  # guarded by: _lock
        self._stopped = False  # guarded by: _lock
        self._thread = threading.Thread(target=self._flusher, daemon=True,
                                        name="batcher-flush")
        self._thread.start()

    # ------------------------------------------------------------------
    def pool(self):
        """The engine pool (resolving the lazy callable), or None."""
        return self._pool() if callable(self._pool) else self._pool

    def heavy_b(self, q: SPARQLQuery) -> int:
        """Device slice count for a heavy dispatch: the plan-cache-backed
        sizing when the proxy wired one in, else a direct (uncached)
        suggest_index_batch capped by ``heavy_batch_max``."""
        if self._suggest_heavy_b is not None:
            return max(int(self._suggest_heavy_b(q)), 1)
        if self.tpu is None:
            return 1
        cap = max(int(Global.heavy_batch_max), 1)
        return max(min(self.tpu.suggest_index_batch(q, cap=cap), cap), 1)

    # ------------------------------------------------------------------
    def offer(self, q: SPARQLQuery) -> _Pending | None:
        """Admit a planned query; None means bypass (caller dispatches)."""
        if self.cpu is None:
            return None
        dl = getattr(q, "deadline", None)
        if dl is not None:
            if dl.budget_rows > 0:
                # per-STEP intermediate-row budgets cannot be attributed to
                # members inside a fused chain (a member's blowup would be
                # subsidized by the group's summed budget) — budgeted
                # queries keep exact sequential enforcement
                _M_BYPASS.labels(reason="budget").inc()
                return None
            rem = dl.remaining_s()
            if rem is not None and rem < (
                    Global.batch_deadline_bypass_factor
                    * Global.batch_window_us / 1e6):
                _M_BYPASS.labels(reason="deadline").inc()
                return None
        heavy = False
        if batchable(q):
            if getattr(q, "lane", "light") == "heavy":
                # plan-time heavy routing (optimizer cardinality estimate):
                # a wide const-start template must not drag a light fused
                # group — it executes alone on the direct path
                _M_BYPASS.labels(reason="heavy_route").inc()
                return None
        elif (Global.heavy_lane and self.tpu is not None
                and Global.enable_tpu and heavy_batchable(q)):
            # enable_tpu is the device kill switch: the sliced heavy
            # dispatch has no host formulation, so host-pinned serving
            # keeps index-origin traffic on the direct path
            heavy = True
        else:
            _M_BYPASS.labels(reason="shape").inc()
            return None
        p = _Pending(q)
        key = heavy_key(q) if heavy else fused_key(q)
        to_flush = None
        reason = "size"
        with self._lock:
            # stop-check INSIDE the admit critical section: close() flips
            # _stopped and drains _groups under this same lock, so an
            # admit can never slip in after the final flush and strand
            # its waiter (a separate pre-check left that window open)
            if self._stopped:
                return None
            grp = self._groups.get(key)
            if grp is None:
                grp = self._groups[key] = _OpenGroup(
                    get_usec() + max(int(Global.batch_window_us), 0),
                    heavy=heavy)
            grp.members.append(p)
            if len(grp.members) >= max(int(Global.batch_max_size), 1):
                to_flush = self._groups.pop(key)
            elif self._inflight == 0 and len(grp.members) == 1 \
                    and len(self._groups) == 1:
                # iteration-level batching: nothing is executing and nothing
                # else is queued — waiting out the window would only add
                # latency. Dispatch now; queries arriving DURING this
                # dispatch accumulate into the next group (that overlap is
                # where the coalescing comes from under load).
                to_flush = self._groups.pop(key)
                reason = "idle"
            else:
                self._lock.notify()
        _M_SUBMITTED.inc()
        if to_flush is not None:
            self._dispatch(to_flush.members, reason=reason,
                           heavy=to_flush.heavy,
                           key=key if to_flush.heavy else None)
        return p

    # ------------------------------------------------------------------
    def _flusher(self) -> None:
        while True:
            try:
                if self._flusher_tick():
                    return
            except Exception as e:  # the flusher must never die: waiters
                log_warn(f"batch flusher error: {e!r}")  # depend on it

    def _flusher_tick(self) -> bool:
        """One flusher iteration; True = stop."""
        while True:
            due = []
            reason = "window"
            with self._lock:
                if self._stopped:
                    return True
                now = get_usec()
                next_due = None
                if self._drain_now and self._inflight == 0:
                    # iteration boundary: take everything that queued
                    # behind the dispatch that just finished
                    due = [(k, self._groups.pop(k), "idle")
                           for k in list(self._groups)]
                else:
                    for key in list(self._groups):
                        grp = self._groups[key]
                        if grp.heavy and self._inflight_keys.get(key):
                            # same-template heavy dispatch in flight:
                            # chain — _note_done marks this group due the
                            # moment the dispatch completes
                            continue
                        if grp.flush_at_us <= now:
                            due.append((key, self._groups.pop(key),
                                        "chain" if grp.chained else reason))
                        elif next_due is None or grp.flush_at_us < next_due:
                            next_due = grp.flush_at_us
                self._drain_now = False
                if not due:
                    self._lock.wait(
                        None if next_due is None
                        else max(next_due - now, 50) / 1e6)
                    continue
            for key, grp, why in due:
                try:
                    self._dispatch(grp.members, reason=why,
                                   heavy=grp.heavy,
                                   key=key if grp.heavy else None)
                except Exception as e:  # settle, never strand a waiter
                    for m in grp.members:
                        if not m.event.is_set():
                            m.error = e
                            m.event.set()

    def _note_done(self, key=None) -> None:
        """A dispatch finished. If it was the last one in flight, wake the
        flusher to release the groups that accumulated while it ran — the
        next iteration starts NOW with whatever queued (Orca-style
        iteration-level scheduling); the window is only the upper bound on
        wait. The flusher (not this stack) dispatches, so back-to-back
        iterations never recurse.

        ``key`` (heavy groups) additionally closes THAT template's
        iteration: the same-key group that chained behind this dispatch is
        marked due and the flusher releases it immediately (reason
        ``chain``) — per-template continuous batching, which is where
        heavy request collapsing comes from under mixed load (the global
        inflight count never reaches 0 while light traffic flows). The
        FLUSHER dispatches, not this stack: with no pool the dispatch
        would run inline here, and steady same-template traffic would
        recurse chain-into-chain without bound.
        """
        with self._lock:
            self._inflight = max(self._inflight - 1, 0)
            if key is not None:
                n = self._inflight_keys.get(key, 0) - 1
                if n > 0:
                    self._inflight_keys[key] = n
                else:
                    self._inflight_keys.pop(key, None)
                    grp = self._groups.get(key)
                    if grp is not None and grp.members:
                        grp.chained = True
                        grp.flush_at_us = 0  # due now
                        self._lock.notify()
            if self._inflight == 0 and self._groups:
                self._drain_now = True
                self._lock.notify()

    def _dispatch(self, members: list, reason: str,
                  heavy: bool = False, key=None) -> None:
        _M_FLUSH.labels(reason=reason).inc()
        (_M_HEAVY_OCC if heavy else _M_OCCUPANCY).observe(len(members))
        with self._lock:
            self._inflight += 1
            if key is not None:
                self._inflight_keys[key] = \
                    self._inflight_keys.get(key, 0) + 1
        engine = (self.tpu if (Global.enable_tpu and self.tpu is not None)
                  else None)
        cls = HeavyGroup if heavy else FusedGroup
        group = cls(members, self, engine=engine, reason=reason, key=key)
        # from here the group owns settlement: every path below ends in
        # run()'s finally or fail_all(), both of which _note_once — the
        # inflight/key counts incremented above can never leak (a leaked
        # key would wedge that template's chaining forever)
        try:
            pool = self.pool()
        except Exception as e:  # a hostile pool callable must not strand
            group.fail_all(e)
            return
        if pool is not None:
            try:
                pool.submit(group, lane=group.lane)
                return
            except Exception as e:
                log_warn(f"batch lane submit failed ({e!r}); running inline")
        try:
            group.run(None)
        except Exception:
            pass  # members are settled (fail_all) inside run()

    # ------------------------------------------------------------------
    def flush(self) -> None:
        """Flush every open group now (drain; tests and shutdown)."""
        with self._lock:
            due = list(self._groups.items())
            self._groups.clear()
        for key, grp in due:
            self._dispatch(grp.members, reason="drain", heavy=grp.heavy,
                           key=key if grp.heavy else None)

    def close(self) -> None:
        with self._lock:
            self._stopped = True
            self._lock.notify_all()
        self.flush()
        self._thread.join(timeout=2)

    def stats(self) -> dict:
        with self._lock:
            return {"open_groups": len(self._groups),
                    "queued": sum(len(g.members)
                                  for g in self._groups.values())}
