"""Thread→core binding (reference: core/bind.hpp).

The reference discovers the NUMA topology with hwloc (load_node_topo,
bind.hpp:81-127), builds a default one-core-per-thread assignment, optionally
overrides it from a `core.bind` file (one NUMA node per line, thread ids
listed in binding order — bind.hpp:129-169), and pins each proxy/engine
pthread with sched_setaffinity (bind.hpp:171-183).

Here the host runtime is a Python thread pool (runtime/scheduler.py), but the
semantics are the same: discover nodes from sysfs (`/sys/devices/system/node`),
map engine tids to cores (default round-robin, or a user `core.bind` file with
the reference's format), and pin via `os.sched_setaffinity` — a direct wrapper
over the same syscall hwloc uses. On hosts without the syscall (macOS) or with
a single core the binder degrades to a no-op, matching the reference's
`enable_binding` gate (bind.hpp:68).
"""

from __future__ import annotations

import glob
import os
import re

from wukong_tpu.utils.logger import log_debug, log_error, log_warn

_HAS_AFFINITY = hasattr(os, "sched_setaffinity")


def _parse_cpulist(text: str) -> list[int]:
    """Parse a sysfs cpulist ("0-3,8,10-11") into a sorted core list."""
    cores: list[int] = []
    for part in text.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            cores.extend(range(int(lo), int(hi) + 1))
        else:
            cores.append(int(part))
    return cores


class CoreBinder:
    """NUMA topology + tid→core map + setaffinity pinning."""

    def __init__(self):
        self.cpu_topo: list[list[int]] = []  # per-NUMA-node core lists
        self.default_bindings: list[int] = []  # flat node-major core order
        self.core_bindings: dict[int, int] = {}  # user tid -> core
        self.enabled = False
        self.load_node_topo()

    # -- topology ------------------------------------------------------
    def load_node_topo(self) -> None:
        """Discover NUMA nodes from sysfs; fall back to one flat node built
        from the process affinity mask (the hwloc PU fallback,
        bind.hpp:108-122)."""
        self.cpu_topo = []
        self.default_bindings = []
        nodes = sorted(glob.glob("/sys/devices/system/node/node[0-9]*"),
                       key=lambda p: int(re.search(r"(\d+)$", p).group(1)))
        usable = (set(os.sched_getaffinity(0)) if _HAS_AFFINITY
                  else set(range(os.cpu_count() or 1)))
        for nd in nodes:
            try:
                with open(os.path.join(nd, "cpulist")) as f:
                    cores = [c for c in _parse_cpulist(f.read()) if c in usable]
            except OSError:
                continue
            if cores:
                self.cpu_topo.append(cores)
        if not self.cpu_topo:
            self.cpu_topo = [sorted(usable)]
        for node in self.cpu_topo:
            self.default_bindings.extend(node)
        log_debug(f"TOPO: {len(self.cpu_topo)} nodes, "
                  f"{len(self.default_bindings)} cores")

    @property
    def num_cores(self) -> int:
        return len(self.default_bindings)

    # -- binding file --------------------------------------------------
    def load_core_binding(self, fname: str) -> bool:
        """`core.bind` format (bind.hpp:129-169): one NUMA node per line;
        the numbers are THREAD ids in binding order, mapped onto that node's
        cores round-robin. '#' lines are comments."""
        try:
            f = open(fname)
        except OSError:
            log_error(f"{fname} does not exist.")
            return False
        nnodes = len(self.cpu_topo)
        node_i = 0
        nbs = 0
        with f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                cores = self.cpu_topo[node_i % nnodes]
                for j, tok in enumerate(line.split()):
                    self.core_bindings[int(tok)] = cores[j % len(cores)]
                    nbs += 1
                node_i += 1
        if node_i < nnodes:
            log_warn("core.bind does not use all NUMA nodes")
        elif node_i > nnodes:
            log_warn("core.bind exceeds the number of NUMA nodes")
        from wukong_tpu.config import Global

        if nbs < getattr(Global, "num_engines", 0):
            log_warn("#engines (config) exceeds #bindings (core.bind)")
        self.enabled = True
        return True

    def core_of(self, tid: int) -> int | None:
        """Core for thread tid: user map first, else default round-robin."""
        if not self.default_bindings:
            return None
        if tid in self.core_bindings:
            return self.core_bindings[tid]
        return self.default_bindings[tid % len(self.default_bindings)]

    # -- pinning -------------------------------------------------------
    def bind_to_core(self, core: int) -> bool:
        """Pin the CURRENT thread to one core (bind.hpp:171-183)."""
        if not _HAS_AFFINITY:
            return False
        try:
            os.sched_setaffinity(0, {core})
            return True
        except OSError as e:
            log_error(f"failed to set affinity (core {core}): {e}")
            return False

    def bind_thread(self, tid: int) -> bool:
        """Pin the current thread according to tid's assignment; no-op when
        binding is disabled or the host has a single usable core."""
        if not self.enabled or self.num_cores <= 1:
            return False
        core = self.core_of(tid)
        return core is not None and self.bind_to_core(core)

    def bind_to_all(self) -> bool:
        """Release the current thread to every discovered core (the
        unbind path, bind.hpp:194-205)."""
        if not _HAS_AFFINITY or not self.default_bindings:
            return False
        try:
            os.sched_setaffinity(0, set(self.default_bindings))
            return True
        except OSError as e:
            log_error(f"failed to reset affinity: {e}")
            return False

    def get_core_binding(self) -> set[int]:
        return set(os.sched_getaffinity(0)) if _HAS_AFFINITY else set()

    def unbind_to_core(self) -> set[int]:
        """Record + release the current binding (bind.hpp:207-216)."""
        prev = self.get_core_binding()
        self.bind_to_all()
        return prev


_binder: CoreBinder | None = None


def get_binder() -> CoreBinder:
    global _binder
    if _binder is None:
        _binder = CoreBinder()
    return _binder
