"""Interactive console / CLI (reference: core/console.hpp:99-108, 893-992).

Commands (console.hpp:960-985): help, quit, config, logger, sparql, sparql-emu,
load, gsck, load-stat, store-stat. One-shot mode via -c. The reference runs the
console on every proxy across servers; in the TPU build one driver process owns
the mesh, so the console is a single REPL over the Proxy.
"""

from __future__ import annotations

import argparse
import shlex
import sys

from wukong_tpu.config import Global, load_config, reload_config
from wukong_tpu.utils.errors import WukongError
from wukong_tpu.utils.logger import log_error, log_info, set_log_level

HELP = """\
help                         print help info
quit                         quit from the console
config <-v | -l <file> | -s <string>>   show/load/set config
logger <level>               set log level (0..7)
sparql -f <file> [-m <f>] [-n <n>] [-p <plan>] [-N] [-v <n>] [-d cpu|tpu|dist]
       [-t <tenant>]         run a single SPARQL query (as <tenant>)
sparql -b <file>             run a batch of `sparql` commands from a file
sparql-emu -f <mix_config> [-d <sec>] [-w <sec>] [-b <batch>] [-p <inflight>]
                             run the open-loop throughput emulator
load -d <dir>                dynamic (incremental) load
gsck [-i] [-n]               check store integrity
load-stat [-f <file>]        load optimizer statistics
store-stat [-f <file>]       store optimizer statistics
trace [-q <qid|id>] [-n <k>] [-o <file>]
                             flight recorder: list recent traces, print one
                             query's span tree by qid/trace id, or export
                             Chrome trace JSON (open in ui.perfetto.dev)
explain <-f <file> | -q <text>> [-p <plan>]
                             EXPLAIN: plan tree + per-step cost/cardinality
                             estimates (no execution)
analyze <-f <file> | -q <text>> [-d cpu|tpu|dist] [-j]
                             EXPLAIN ANALYZE: execute under a forced trace,
                             join estimated vs actual per-step rows / wall
                             time / fetches + latency decomposition
top [-k <n>] [-j]            hot shards / templates / lanes (like top(1);
                             also served at GET /top on the metrics port)
slo [-k <n>] [-j]            per-tenant SLO compliance / error budgets /
                             burn rates + the overload signal bus (also
                             served at GET /slo on the metrics port)
admission [-k <n>] [-j]      admission control plane: overload level,
                             per-tenant quotas/weights, decision counts,
                             consumed congestion signals (also
                             GET /admission)
history [-k <n>] [-w <sec>] [-j]
                             metrics trend windows from the time-series
                             ring: counter rates, histogram percentiles,
                             gauges (also GET /history)
events [-k <n>] [-s <shard>] [-K <kind>] [-j]
                             cluster event journal: breaker trips,
                             failovers, heals, WAL/checkpoint lifecycle,
                             SLO burns (also GET /events)
cache [-k <n>] [-j]          serving plane + observatory: real result-
                             cache hit rate/bytes/views, shadow hit rate,
                             template popularity + cacheability verdicts,
                             invalidation trend (also GET /cache)
device [-k <n>] [-j]         device-cost observatory: per-site XLA
                             dispatch counts + padding efficiency,
                             cold/warm compile split, jit variant counts,
                             device-resident bytes vs budget
                             (also GET /device)
plan [-j] [-n]               observe-only placement advisor: run one
                             sweep and print the MigrationPlan + shard
                             lineage (-n skips the fresh sweep; also
                             GET /plan)
migrate [-j] | migrate -abort | migrate -s
                             live shard migration: sweep the advisor and
                             EXECUTE its MigrationPlan (clone/catch-up/
                             cutover/retire; migration_enable must be
                             on). -abort rolls the in-flight migration
                             back to the donor; -s prints actuator status
metrics [-j]                 dump the metrics registry (Prometheus text, -j JSON)
checkpoint                   write one atomic checkpoint (partitions + stream
                             state) to checkpoint_dir; truncates covered WAL
recover [-d <shard>]         restore newest checkpoint + replay the WAL tail;
                             -d runs the kill-and-recover drill against one
                             shard instead (requires --dist)
"""


class Console:
    def __init__(self, proxy, stats_path: str | None = None):
        self.proxy = proxy
        self.stats_path = stats_path

    def run_command(self, line: str) -> bool:
        """Execute one command; returns False to quit."""
        try:
            args = shlex.split(line)
        except ValueError as e:
            log_error(f"bad command: {e}")
            return True
        if not args:
            return True
        cmd, rest = args[0], args[1:]
        try:
            if cmd in ("quit", "q", "exit"):
                return False
            if cmd == "help":
                print(HELP)
            elif cmd == "config":
                self._config(rest)
            elif cmd == "logger":
                set_log_level(int(rest[0]))
            elif cmd == "sparql":
                self._sparql(rest)
            elif cmd == "sparql-emu":
                self._emu(rest)
            elif cmd == "load":
                ap = argparse.ArgumentParser(prog="load")
                ap.add_argument("-d", required=True)
                ap.add_argument("-c", action="store_true")
                ns = ap.parse_args(rest)
                self.proxy.dynamic_load_data(ns.d, ns.c)
            elif cmd == "gsck":
                index = "-i" in rest or not rest
                normal = "-n" in rest or not rest
                self.proxy.gstore_check(index, normal)
            elif cmd == "load-stat":
                self._stat(rest, load=True)
            elif cmd == "store-stat":
                self._stat(rest, load=False)
            elif cmd == "trace":
                self._trace(rest)
            elif cmd in ("explain", "analyze"):
                self._explain(rest, analyze=cmd == "analyze")
            elif cmd == "top":
                self._top(rest)
            elif cmd == "slo":
                self._slo(rest)
            elif cmd == "admission":
                self._admission(rest)
            elif cmd == "history":
                self._history(rest)
            elif cmd == "events":
                self._events(rest)
            elif cmd == "cache":
                self._cache(rest)
            elif cmd == "device":
                self._device(rest)
            elif cmd == "plan":
                self._plan_verb(rest)
            elif cmd == "migrate":
                self._migrate(rest)
            elif cmd == "metrics":
                self._metrics(rest)
            elif cmd == "checkpoint":
                log_info(f"checkpoint written: {self.proxy.checkpoint()}")
            elif cmd == "recover":
                self._recover(rest)
            else:
                log_error(f"unknown command: {cmd} (try 'help')")
        except WukongError as e:
            log_error(str(e))
        except SystemExit:
            pass  # argparse error inside a command
        return True

    # ------------------------------------------------------------------
    def _config(self, rest) -> None:
        if not rest or rest[0] == "-v":
            print(Global.dump())
        elif rest[0] == "-l":
            load_config(rest[1])
            self._apply_observatory_knobs()
        elif rest[0] == "-s":
            reload_config(" ".join(rest[1:]).replace("=", " "))
            self._apply_observatory_knobs()
        else:
            log_error("usage: config <-v | -l <file> | -s <key value>>")

    def _apply_observatory_knobs(self) -> None:
        """The observatory knobs are runtime-mutable in BOTH directions:
        the sampler/advisor/actuator threads check their knob per tick
        (on->off), but a flip from off to on after boot needs the
        idempotent starters re-invoked — without this, `config -s
        enable_tsdb true` (or `migration_enable true`) would silently
        never act until a restart."""
        from wukong_tpu.obs.placement import maybe_start_advisor
        from wukong_tpu.obs.tsdb import maybe_start_tsdb
        from wukong_tpu.runtime.migration import maybe_start_migration

        maybe_start_tsdb()
        sstore = getattr(self.proxy.dist, "sstore", None) \
            if self.proxy.dist is not None else None
        if maybe_start_migration(sstore, owner=self.proxy) is None:
            maybe_start_advisor(sstore)

    def _sparql(self, rest) -> None:
        ap = argparse.ArgumentParser(prog="sparql")
        ap.add_argument("-f", default=None)
        ap.add_argument("-b", default=None,
                        help="batch file: one `sparql ...` command per line "
                             "(console.hpp:151, exclusive with -f)")
        ap.add_argument("-m", type=int, default=1)
        ap.add_argument("-n", type=int, default=1)
        ap.add_argument("-p", default=None)
        ap.add_argument("-N", action="store_true", help="non-blind (ship results)")
        ap.add_argument("-v", type=int, default=0, help="print first N rows")
        ap.add_argument("-d", default=None, choices=["cpu", "tpu", "dist"])
        ap.add_argument("-t", default="default",
                        help="tenant identity stamped on the query "
                             "(obs/slo.py accounting)")
        ns = ap.parse_args(rest)
        if (ns.f is None) == (ns.b is None):
            log_error("single mode (-f) and batch mode (-b) are exclusive "
                      "— pass exactly one")
            return
        if ns.b is not None:
            if getattr(self, "_in_batch", False):
                log_error("nested batch files are not allowed")
                return
            try:
                lines = open(ns.b).read().splitlines()
            except OSError as e:
                log_error(f"cannot read batch file: {e}")
                return
            log_info("Batch-mode start ...")
            self._in_batch = True
            try:
                for line in lines:
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    log_info(f"Run the command: {line}")
                    self.run_command(line)
            finally:
                self._in_batch = False
            return
        text = open(ns.f).read()
        plan = open(ns.p).read() if ns.p else None
        blind = None if not (ns.N or ns.v) else False
        self.proxy.run_single_query(text, repeats=ns.n, plan_text=plan,
                                    mt_factor=ns.m, device=ns.d, blind=blind,
                                    print_results=ns.v, tenant=ns.t)

    def _emu(self, rest) -> None:
        from wukong_tpu.obs import maybe_device_trace
        from wukong_tpu.runtime.emulator import Emulator, load_mix_config

        ap = argparse.ArgumentParser(prog="sparql-emu")
        ap.add_argument("-f", required=True)
        ap.add_argument("-d", type=float, default=5.0)
        ap.add_argument("-w", type=float, default=1.0)
        ap.add_argument("-b", type=int, default=None)
        ap.add_argument("-p", type=int, default=None,
                        help="in-flight cap across the engine pool")
        ns = ap.parse_args(rest)
        mix = load_mix_config(ns.f, self.proxy.str_server)
        # WUKONG_XPROF_DIR scopes the JAX profiler around the whole run
        # (XProf/TensorBoard view of the device side); off by default
        with maybe_device_trace():
            Emulator(self.proxy).run(mix, duration_s=ns.d, warmup_s=ns.w,
                                     batch=ns.b, parallel=ns.p)

    # ------------------------------------------------------------------
    def _trace(self, rest) -> None:
        """Flight-recorder verbs (report path: console prints directly)."""
        from wukong_tpu.obs import get_recorder, write_chrome_trace

        ap = argparse.ArgumentParser(prog="trace")
        ap.add_argument("-q", default=None,
                        help="fetch one trace by qid or trace id")
        ap.add_argument("-n", type=int, default=16,
                        help="how many recent traces to list/export")
        ap.add_argument("-o", default=None,
                        help="export Chrome trace JSON to this path")
        ns = ap.parse_args(rest)
        rec = get_recorder()
        if ns.o is not None:
            traces = ([rec.find(ns.q)] if ns.q is not None
                      else rec.last(ns.n))
            traces = [t for t in traces if t is not None]
            if not traces:
                log_error("no traces recorded (enable_tracing on?)")
                return
            print(f"wrote {len(traces)} trace(s) to "
                  f"{write_chrome_trace(ns.o, traces)}")
            return
        if ns.q is not None:
            tr = rec.find(ns.q)
            if tr is None:
                log_error(f"no trace for {ns.q!r} in the flight recorder")
                return
            print(f"trace {tr.trace_id} qid={tr.qid} kind={tr.kind} "
                  f"status={tr.status} dur={tr.dur_us:,}us")
            if tr.text:
                print(f"  query: {' '.join(tr.text.split())[:120]}")
            for sp in tr.spans:
                pad = "  " * (sp.depth + 1)
                attrs = " ".join(f"{k}={v}" for k, v in sp.attrs.items())
                print(f"{pad}{sp.name} {sp.dur_us:,}us"
                      + (f" [{attrs}]" if attrs else ""))
                for (_t, name, a) in sp.events:
                    ev = " ".join(f"{k}={v}" for k, v in a.items())
                    print(f"{pad}  ! {name}" + (f" [{ev}]" if ev else ""))
            return
        traces = rec.last(ns.n)
        if not traces:
            log_error("flight recorder is empty (enable_tracing on?)")
            return
        for tr in traces:
            print(f"{tr.trace_id}  qid={tr.qid:<6} {tr.kind:<7} "
                  f"{tr.status:<16} {tr.dur_us:>10,}us "
                  f"{len(tr.spans):>3} spans")
        if rec.dumps:
            print(f"({len(rec.dumps)} auto-dumped: "
                  + ", ".join(f"{r}:{t.trace_id}"
                              for r, t in list(rec.dumps)[-8:]) + ")")

    def _explain(self, rest, analyze: bool) -> None:
        """explain / analyze: the EXPLAIN (ANALYZE) surface over
        Proxy.explain_query (obs/profile.py)."""
        import json

        prog = "analyze" if analyze else "explain"
        ap = argparse.ArgumentParser(prog=prog)
        ap.add_argument("-f", default=None, help="query file")
        ap.add_argument("-q", default=None, help="inline query text")
        ap.add_argument("-d", default=None, choices=["cpu", "tpu", "dist"])
        ap.add_argument("-p", default=None, help="user plan file (EXPLAIN)")
        ap.add_argument("-j", action="store_true",
                        help="print the structured JSON report")
        ns = ap.parse_args(rest)
        if (ns.f is None) == (ns.q is None):
            log_error(f"usage: {prog} <-f <file> | -q <text>>")
            return
        try:
            text = open(ns.f).read() if ns.f else ns.q
            plan = open(ns.p).read() if ns.p else None
        except OSError as e:  # a typo'd path must not kill the REPL
            log_error(f"cannot read file: {e}")
            return
        report = self.proxy.explain_query(text, analyze=analyze,
                                          device=ns.d, plan_text=plan)
        if ns.j:
            print(json.dumps({k: v for k, v in report.items()
                              if k != "rendered"},
                             indent=1, sort_keys=True, default=str))
        else:
            print(report["rendered"])

    def _top(self, rest) -> None:
        """top: hot shards / templates / lanes (the /top endpoint's body)."""
        from wukong_tpu.obs.profile import render_top

        ap = argparse.ArgumentParser(prog="top")
        ap.add_argument("-k", type=int, default=None,
                        help="rows per section (default: the top_k knob)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_top(ns.k))

    @staticmethod
    def _print_report(json_out: bool, text: str, js: dict) -> None:
        """The shared (text, JSON) epilogue of every report verb."""
        if json_out:
            import json

            print(json.dumps(js, indent=1, sort_keys=True, default=str))
        else:
            print(text, end="")

    def _slo(self, rest) -> None:
        """slo: per-tenant compliance / error budgets / burn rates + the
        overload signal bus (the /slo endpoint's body)."""
        from wukong_tpu.obs.slo import render_slo

        ap = argparse.ArgumentParser(prog="slo")
        ap.add_argument("-k", type=int, default=None,
                        help="tenant rows shown (default: the top_k knob)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_slo(ns.k))

    def _history(self, rest) -> None:
        """history: metrics trend windows from the time-series ring
        (the /history endpoint's body)."""
        from wukong_tpu.obs.tsdb import render_history

        ap = argparse.ArgumentParser(prog="history")
        ap.add_argument("-k", type=int, default=None,
                        help="rows per section (default: the top_k knob)")
        ap.add_argument("-w", type=float, default=None,
                        help="trend window seconds (default: retention)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_history(ns.k, ns.w))

    def _events(self, rest) -> None:
        """events: the cluster event journal (the /events body)."""
        from wukong_tpu.obs.events import render_events

        ap = argparse.ArgumentParser(prog="events")
        ap.add_argument("-k", type=int, default=None,
                        help="events shown (default: 4x the top_k knob)")
        ap.add_argument("-s", type=int, default=None, metavar="shard",
                        help="only events correlated to this shard")
        ap.add_argument("-K", default=None, metavar="kind",
                        help="only events of this kind")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_events(ns.k, shard=ns.s,
                                                kind=ns.K))

    def _admission(self, rest) -> None:
        """admission: the admission control plane (the /admission body)."""
        from wukong_tpu.runtime.admission import render_admission

        ap = argparse.ArgumentParser(prog="admission")
        ap.add_argument("-k", type=int, default=None,
                        help="tenant rows shown (default: the top_k knob)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_admission(ns.k))

    def _cache(self, rest) -> None:
        """cache: the serving plane + observatory (the /cache body)."""
        from wukong_tpu.obs.reuse import render_cache

        ap = argparse.ArgumentParser(prog="cache")
        ap.add_argument("-k", type=int, default=None,
                        help="template rows shown (default: the top_k knob)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_cache(ns.k))

    def _device(self, rest) -> None:
        """device: the device-cost observatory (the /device body)."""
        from wukong_tpu.obs.device import render_device

        ap = argparse.ArgumentParser(prog="device")
        ap.add_argument("-k", type=int, default=None,
                        help="dispatch rows shown (default: the top_k knob)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        self._print_report(ns.j, *render_device(ns.k))

    def _plan_verb(self, rest) -> None:
        """plan: one observe-only placement-advisor sweep + the last
        MigrationPlan and shard lineage (the /plan body)."""
        from wukong_tpu.obs.placement import get_advisor, render_plan

        ap = argparse.ArgumentParser(prog="plan")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ap.add_argument("-n", action="store_true",
                        help="no fresh sweep: print the last plan only")
        ns = ap.parse_args(rest)
        sstore = getattr(self.proxy.dist, "sstore", None) \
            if self.proxy.dist is not None else None
        if sstore is not None:
            get_advisor().attach_store(sstore)
        self._print_report(ns.j, *render_plan(advise=not ns.n))

    def _migrate(self, rest) -> None:
        """migrate: one actuator round — sweep the advisor, execute the
        MigrationPlan it emits (migration_enable must be on). -abort
        rolls the in-flight migration back; -s prints status only."""
        import json

        from wukong_tpu.obs.placement import get_advisor
        from wukong_tpu.runtime.migration import get_migrator

        ap = argparse.ArgumentParser(prog="migrate", prefix_chars="-")
        ap.add_argument("-abort", dest="abort", action="store_true",
                        help="abort the in-flight migration")
        ap.add_argument("-s", dest="status", action="store_true",
                        help="actuator status only (no sweep)")
        ap.add_argument("-j", action="store_true", help="JSON output")
        ns = ap.parse_args(rest)
        mig = get_migrator()
        sstore = getattr(self.proxy.dist, "sstore", None) \
            if self.proxy.dist is not None else None
        if sstore is not None:
            mig.attach(sstore=sstore, owner=self.proxy)
            get_advisor().attach_store(sstore)
        if ns.abort:
            job = mig.abort(cause="operator")
            log_info(f"migration {job.plan.plan_id} aborted"
                     if job is not None else "no migration in flight")
            return
        if ns.status:
            if ns.j:
                print(json.dumps(mig.status(), indent=1, sort_keys=True,
                                 default=str))
            else:
                log_info(f"migration actuator: {mig.status()}")
            return
        plan = get_advisor().advise_once()
        if plan is None:
            log_info("no MigrationPlan to execute (advisor: "
                     f"{get_advisor().status()['decision']})")
            return
        job = mig.run_plan(plan)
        if ns.j:
            print(json.dumps(job.to_dict(), indent=1, sort_keys=True,
                             default=str))
        else:
            log_info(f"migration {job.plan.plan_id} {job.phase}: shard "
                     f"{job.plan.donor_shard} -> host "
                     f"{job.plan.recipient_host} "
                     f"({job.bytes_moved:,} bytes)")

    def _recover(self, rest) -> None:
        """recover: boot-style checkpoint+WAL restore. recover -d <shard>:
        the kill-and-recover drill — force that primary down, prove
        failover keeps results complete, heal, verify."""
        ap = argparse.ArgumentParser(prog="recover")
        ap.add_argument("-d", "--drill", type=int, default=None,
                        metavar="shard")
        ns = ap.parse_args(rest)
        if ns.drill is None:
            stats = self.proxy.recover()
            log_info(f"recovered: checkpoint={stats['checkpoint']} "
                     f"replayed={stats['replayed']} epoch={stats['epoch']}")
            return
        from wukong_tpu.runtime.emulator import Emulator

        report = Emulator(self.proxy).run_drill(shard=ns.drill)
        log_info(f"drill report: {report}")

    def _metrics(self, rest) -> None:
        from wukong_tpu.obs import get_registry

        if "-j" in rest:
            import json

            print(json.dumps(get_registry().snapshot(), indent=1,
                             sort_keys=True))
        else:
            print(get_registry().render_prometheus(), end="")

    def _stat(self, rest, load: bool) -> None:
        """load-stat / store-stat: persist optimizer statistics
        (console.hpp:977-980 -> stats.hpp:585-640)."""
        from wukong_tpu.planner.stats import Stats

        path = rest[rest.index("-f") + 1] if "-f" in rest else self.stats_path
        if path is None:
            log_error("no statfile path (use -f <file>)")
            return
        if load:
            from wukong_tpu.planner.optimizer import Planner

            self.proxy.planner = Planner(Stats.load(path))
            log_info(f"statistics loaded from {path}")
        else:
            if self.proxy.planner is None:
                log_error("no planner statistics to store")
                return
            self.proxy.planner.stats.save(path)
            log_info(f"statistics stored to {path}")

    # ------------------------------------------------------------------
    def repl(self) -> None:
        log_info("wukong-tpu console — 'help' for commands")
        while True:
            try:
                line = input("wukong> ")
            except (EOFError, KeyboardInterrupt):
                break
            if not self.run_command(line):
                break


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="wukong-tpu: TPU-native RDF store + SPARQL engine")
    ap.add_argument("config", help="config file path")
    ap.add_argument("dataset", help="dataset directory (id-format)")
    ap.add_argument("-c", "--command", default=None,
                    help="one-shot command, then exit")
    ap.add_argument("-w", "--workers", type=int, default=None,
                    help="graph partitions (default: 1, or device count with --dist)")
    ap.add_argument("--dist", action="store_true",
                    help="partition across all visible devices")
    ap.add_argument("-b", "--bind", default=None, metavar="core.bind",
                    help="enable thread->core binding from a core.bind file "
                         "(reference: wukong -b, bind.hpp)")
    args = ap.parse_args(argv)
    from wukong_tpu.utils.jaxenv import respect_platform_env

    respect_platform_env()
    # cold-start economics (round-4 verdict Weak #3): compiled chains
    # persist across processes, so a restarted console re-loads programs
    # in ~ms instead of re-paying multi-second compiles
    from wukong_tpu.utils.compilecache import setup_persistent_cache

    setup_persistent_cache()

    load_config(args.config, num_workers=args.workers)
    if args.bind is not None:
        # after load_config: the binding sanity check reads Global.num_engines
        from wukong_tpu.runtime.bind import get_binder

        get_binder().load_core_binding(args.bind)
    from wukong_tpu.engine.cpu import CPUEngine
    from wukong_tpu.engine.tpu import TPUEngine
    from wukong_tpu.loader.base import load_dataset
    from wukong_tpu.store.string_server import StringServer
    from wukong_tpu.runtime.proxy import Proxy

    import os as _os

    from wukong_tpu.loader.base import load_attr_triples, load_triples
    from wukong_tpu.store.gstore import build_partition

    from wukong_tpu.loader.hdfs import resolve_dataset_dir

    args.dataset = resolve_dataset_dir(args.dataset)  # hdfs:// -> staged dir
    ss = StringServer(args.dataset)
    # one read of the triple files serves the partitions, the host fallback
    # store, and stats generation
    triples = load_triples(args.dataset)
    attrs = load_attr_triples(args.dataset)
    g = build_partition(triples, 0, 1, attrs)
    if args.dist:
        import jax

        from wukong_tpu.parallel.dist_engine import DistEngine
        from wukong_tpu.parallel.mesh import make_mesh

        n = args.workers or len(jax.devices())
        stores = [build_partition(triples, i, n, attrs) for i in range(n)]
        dist = DistEngine(stores, ss, make_mesh(n))
        proxy = Proxy(g, ss, CPUEngine(g, ss),
                      TPUEngine(g, ss) if Global.enable_tpu else None, dist)
    else:
        proxy = Proxy(g, ss, CPUEngine(g, ss),
                      TPUEngine(g, ss) if Global.enable_tpu else None)

    if Global.enable_planner:
        from wukong_tpu.planner.optimizer import make_planner

        statfile = _os.path.join(args.dataset, "statfile")
        proxy.planner = make_planner(
            None if _os.path.exists(statfile + ".npz") else triples, statfile)
        if proxy.tpu is not None:
            proxy.tpu.stats = proxy.planner.stats  # capacity estimation
    del triples

    console = Console(proxy, stats_path=_os.path.join(args.dataset, "statfile"))
    if args.command is not None:
        console.run_command(args.command)
    else:
        console.repl()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
