"""Open-loop throughput emulator — `sparql-emu` (reference: proxy.hpp:391-545).

Parses a mix config (N light templates + M heavy queries with integer weights,
console format `<path> <weight>` after an "<nlights> <nheavies>" header), fills
template candidates from the store's indexes, then drives an open loop for a
duration, reporting throughput and a per-class latency CDF.

Two execution paths:
- host path: per-instance CPU-engine execution (reference parity)
- device path: instances of one template batch into a single compiled TPU
  chain (TPUEngine.execute_batch) — the emulator's batch dimension IS the TPU
  win (SURVEY §7.6): B=device_batch queries per dispatch.

The host path honors the `query_deadline_ms` / `query_budget_rows` resilience
knobs per instance, like the proxy path: queue-expired queries are shed by
the pool, mid-query expiry yields a partial result. Compiled device batches
are all-or-nothing dispatches and carry no per-query deadline.
"""

from __future__ import annotations

import os
import time

import numpy as np

from wukong_tpu.config import Global
from wukong_tpu.obs import (
    activate,
    get_recorder,
    maybe_start_snapshotter,
    maybe_start_trace,
    write_chrome_trace,
)
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.runtime.monitor import Monitor
from wukong_tpu.runtime.resilience import Deadline
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.utils.errors import (
    BudgetExceeded,
    ErrorCode,
    QueryTimeout,
    WukongError,
)
from wukong_tpu.utils.logger import log_info
from wukong_tpu.utils.timer import get_usec


class MixConfig:
    def __init__(self, templates, heavies, weights):
        self.templates = templates  # list[SPARQLTemplate]
        self.heavies = heavies  # list[str] query texts
        self.weights = np.asarray(weights, dtype=np.float64)


def load_mix_config(path: str, str_server) -> MixConfig:
    base = os.path.dirname(os.path.dirname(path.rstrip("/")))
    with open(path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    nlights, nheavies = (int(x) for x in lines[0].split())
    entries = []
    for ln in lines[1:1 + nlights + nheavies]:
        parts = ln.split()
        entries.append((parts[0], int(parts[1])))
    templates, heavies, weights = [], [], []
    for i, (qpath, w) in enumerate(entries):
        # mix-config paths are relative to the suite root (scripts/ dir)
        for root in (os.path.dirname(path), base,
                     "/root/reference/scripts", ""):
            cand = os.path.join(root, qpath) if root else qpath
            if os.path.exists(cand):
                qpath = cand
                break
        text = open(qpath).read()
        if i < nlights:
            templates.append(Parser(str_server).parse_template(text))
        else:
            heavies.append(text)
        weights.append(w)
    return MixConfig(templates, heavies, weights)


def _probe_read(g):
    """A real host-side partition read with a measurable payload: the
    partition's largest index list (what an index-origin staging fetches),
    falling back to an empty array. Shared by the hot-spot and rebalance
    drills — the rebalance oracle compares THESE bytes across phases."""
    best = max(((k, v) for k, v in g.index.items() if len(v)),
               key=lambda kv: len(kv[1]), default=None)
    return (np.asarray(best[1]) if best is not None
            else np.empty(0, np.int64))


def _replies_identical(qa, qb) -> bool:
    """Byte-level reply equality for the cached read-mostly drill: a
    cache-served reply must be indistinguishable from the uncached
    execution — status, row/column counts, the table's bytes, and the
    projection map all compare."""
    ra, rb = qa.result, qb.result
    return (ra.status_code == rb.status_code
            and bool(ra.complete) == bool(rb.complete)
            and int(ra.nrows) == int(rb.nrows)
            and int(ra.col_num) == int(rb.col_num)
            and ra.v2c_map == rb.v2c_map
            and np.array_equal(np.asarray(ra.table), np.asarray(rb.table)))


def _zipf_drive(sstore, hot: int, n_ops: int, zipf_a: float, rng,
                what: str) -> None:
    """Drive ``n_ops`` probe fetches whose shard choice follows a
    Zipf(``zipf_a``) law rotated onto ``hot`` (rank-0 mass lands on the
    hot shard, the tail spreads over the cold ones), through the normal
    resilience fetch path. One skew model shared by the hot-spot
    measurement and the rebalance drill's post-move replay — the
    pre/post imbalance comparison is only meaningful because both runs
    draw from the SAME law."""
    D = sstore.D
    w = 1.0 / np.power(np.arange(1, D + 1, dtype=np.float64), zipf_a)
    w /= w.sum()
    order = [(hot + j) % D for j in range(D)]
    for r in rng.choice(D, size=int(n_ops), p=w):
        sstore._fetch_shard(order[int(r)], _probe_read, what)


class Emulator:
    # consecutive mixed-flight (W>1 cross-class) failures a class may cause
    # before it is pinned to W=1: de-warming alone lets a class re-warm via
    # its (succeeding) single-class batch and rejoin the mix, so a
    # persistently-failing W-fold footprint oscillates warm->fail forever,
    # paying the overflow retries every cycle (round-4 advisor)
    MIXED_FAIL_LIMIT = 3

    def __init__(self, proxy):
        self.proxy = proxy
        self.monitor = Monitor()
        # per-run latency counters stay private, but breaker state and
        # stream epochs live on the proxy monitor — adopt them so the
        # open loop's rolling report (the only maybe_print_thpt caller)
        # actually surfaces them
        self.monitor.share_observability(proxy.monitor)

    # ------------------------------------------------------------------
    def run(self, mix: MixConfig, duration_s: float = 5.0, warmup_s: float = 1.0,
            batch: int | None = None, seed: int = 0,
            parallel: int | None = None) -> dict:
        """Open loop for `duration_s` keeping up to `parallel` queries in
        flight across the host engine pool (the reference's `-p` cap,
        proxy.hpp:477-525); returns {thpt, cdf per class}.

        Device-batchable classes run as synchronous compiled batches (the
        batch dimension IS the pipeline there): light templates through
        execute_batch, index-origin heavies through execute_batch_index."""
        for tmpl in mix.templates:
            self.proxy.fill_template(tmpl)
        rng = np.random.default_rng(seed)
        probs = mix.weights / mix.weights.sum()
        nclasses = len(mix.templates) + len(mix.heavies)
        use_tpu = (self.proxy.tpu is not None and Global.enable_tpu)
        B = batch or Global.device_batch
        p_cap = max(parallel or Global.num_engines, 1)
        self._p_cap = p_cap
        pool = self.proxy.engine_pool()

        # pre-plan one query per class (remembering the instantiated
        # placeholder value so _batchable can confirm the plan starts from it)
        planned = []
        for tmpl in mix.templates:
            q = tmpl.instantiate(rng)
            inst_const = getattr(q.pattern_group.patterns[tmpl.pos[0][0]],
                                 tmpl.pos[0][1]) if tmpl.pos else None
            self._plan(q)
            q._inst_const = inst_const
            planned.append(("light", tmpl, q))
        for text in mix.heavies:
            q = Parser(self.proxy.str_server).parse(text)
            self._plan(q)
            planned.append(("heavy", None, q))

        self._planned = planned
        self._probs = probs
        self._mixed_fail: dict[int, int] = {}
        # explicit per-class heavy routing (replaces the old mutable
        # q._heavy_b sentinel on the query object): "device" rides the
        # compiled batch path with the plan-cache-backed slice count,
        # "pool" is the recorded fall-back decision after a device failure
        self._heavy_route: dict[int, str] = {}
        self._served = 0

        # precompile every device-batchable class BEFORE the measurement
        # window (round-4 verdict Weak #2: lazily compiling inside the window
        # made the wall number ~40x below the warm per-class latencies; the
        # reference's open loop measures steady state, proxy.hpp:391-545).
        # Each warmup batch also learns the class's capacity classes.
        t_wall0 = get_usec()
        precompiled = 0
        if use_tpu and os.environ.get("WUKONG_EMU_PRECOMPILE", "1") != "0":
            for kind, tmpl, q0 in planned:
                if kind != "light" or not self._batchable(tmpl, q0):
                    continue
                try:
                    self.proxy.tpu.execute_batch(
                        q0, self._draw_consts(tmpl, rng, B))
                    q0._many_warm = True
                    precompiled += 1
                except (WukongError, RuntimeError) as e:
                    q0._inst_const = None  # pool-only, with correct blame
                    log_info(f"sparql-emu: precompile degraded a class "
                             f"to the pool ({e!r:.120})")
            if precompiled:
                log_info(f"sparql-emu: precompiled {precompiled} device "
                         f"classes in {(get_usec() - t_wall0) / 1e6:.1f}s")
        self.monitor.start_thpt()
        t_end = get_usec() + int((duration_s + warmup_s) * 1e6)
        t_measure = get_usec() + int(warmup_s * 1e6)
        warm = True
        inflight: dict[int, tuple] = {}
        # how each class is measured: device-batch latencies are
        # batch_time/B, NOT pool round-trips — label them (round-2 Weak #6)
        self.class_mode: dict[int, str] = {}
        errors = shed = 0
        first_error: Exception | None = None
        while get_usec() < t_end or inflight:
            if warm and get_usec() >= t_measure:
                self.monitor.start_thpt()
                warm = False
            submitted = False
            while len(inflight) < p_cap and get_usec() < t_end:
                cls = int(rng.choice(nclasses, p=probs))
                kind, tmpl, q0 = planned[cls]
                if use_tpu and self._device_batch(kind, tmpl, q0, rng, B, cls):
                    self.class_mode[cls] = "device-batch"
                    submitted = True
                    break  # a sync batch ran — let the outer loop poll/print
                import copy

                if tmpl is not None:
                    q = tmpl.instantiate(rng)
                    self._plan(q)
                else:
                    q = copy.deepcopy(q0)  # heavy classes reuse the cached plan
                q.result.blind = True
                # per-query deadline/budget from the resilience knobs, like
                # the proxy path (queue-expired queries are shed by the
                # pool; mid-query expiry degrades to a partial result).
                # Attached per INSTANCE, never on the cached q0 — a deadline
                # is wall-clock state that must start at submit time.
                q.deadline = Deadline.from_config()
                # sampled per-instance trace (queue + engine spans) when
                # tracing is enabled; completions feed the flight recorder
                q.trace = maybe_start_trace(kind="emu")
                prev = self.class_mode.get(cls)
                # a class that device-batched earlier and now rides the pool
                # has MIXED samples — the label must say so, not claim either
                self.class_mode[cls] = ("pool" if prev in (None, "pool")
                                        else "mixed")
                inflight[pool.submit(q)] = (cls, get_usec(), q.trace)
                submitted = True
            done = pool.poll()
            for qid, out in done:
                info = inflight.pop(qid, None)
                if info is None:  # stale completion from an aborted prior run
                    continue
                cls, t0, qtrace = info
                if qtrace is not None:
                    status = (getattr(out, "code", "ERROR")
                              if isinstance(out, Exception)
                              else out.result.status_code)
                    get_recorder().on_complete(qtrace, status)
                if isinstance(out, Exception):
                    if isinstance(out, (QueryTimeout, BudgetExceeded)):
                        # deadline/budget load shedding is the resilience
                        # knobs working as intended, not an engine crash
                        shed += 1
                        continue
                    # engine crashes must not count as served queries
                    errors += 1
                    first_error = first_error or out
                    continue
                self._served += 1
                self.monitor.add_latency(get_usec() - t0, qtype=cls)
            if not submitted and not done:
                time.sleep(0.0002)  # open loop idle tick
            self.monitor.maybe_print_thpt()

        thpt = self.monitor.thpt()
        if shed:
            from wukong_tpu.utils.logger import log_warn

            log_warn(f"sparql-emu: {shed} queries shed by deadline/budget")
        if errors:
            from wukong_tpu.utils.logger import log_warn

            log_warn(f"sparql-emu: {errors} queries crashed "
                     f"(first: {first_error!r})")
            if thpt == 0:
                raise RuntimeError(
                    f"sparql-emu: every query failed: {first_error!r}")
        # warm_qps is the steady-state number (measured window only, every
        # device class precompiled before it); wall_qps divides EVERY served
        # query by the full wall including precompile + warmup — retained for
        # honesty (round-4 verdict Weak #2: the two differed ~40x when
        # compiles happened inside the window)
        wall_s = (get_usec() - t_wall0) / 1e6
        wall_qps = self._served / wall_s if wall_s > 0 else 0.0
        log_info(f"sparql-emu: {thpt:,.0f} q/s steady over {duration_s}s "
                 f"(wall {wall_qps:,.0f} q/s incl. "
                 f"{precompiled}-class precompile; "
                 f"{'TPU batch + ' if use_tpu else ''}pool p={p_cap})")
        self.monitor.print_cdf(labels=self.class_mode)
        chrome = os.environ.get("WUKONG_TRACE_CHROME")
        if chrome:
            # per-emulator-run Chrome trace-event export: every trace the
            # flight recorder holds (this run's sampled queries + stream
            # epochs), Perfetto-loadable
            log_info("sparql-emu: Chrome trace written to "
                     f"{write_chrome_trace(chrome, get_recorder().last())}")
        return {"thpt_qps": thpt, "warm_qps": thpt,
                "wall_qps": round(wall_qps, 1),
                "precompiled_classes": precompiled, "errors": errors,
                "shed": shed,
                "class_mode": dict(self.class_mode),
                "cdf": {c: self.monitor.cdf(c) for c in range(nclasses)}}

    def _plan(self, q) -> None:
        """Proxy's plan path: type-centric Planner when available (it also
        sets planner_empty short-circuits), else the greedy heuristic."""
        if self.proxy.planner is not None and Global.enable_planner:
            if self.proxy.planner.generate_plan(q):
                return
        heuristic_plan(q)

    @staticmethod
    def _traced_flight(fn, **attrs):
        """One device-batch flight under a sampled ``batch.dispatch`` span
        (ROADMAP follow-up f: W>1 flights used to trace only per-instance
        pool queries). The untraced path is one config check + ``fn()``."""
        ftr = maybe_start_trace(kind="device_batch")
        if ftr is None:
            return fn()
        with activate(ftr):
            sp = ftr.start_span("batch.dispatch", **attrs)
            try:
                out = fn()
            except Exception:
                ftr.end_span(sp, status="ERROR")
                get_recorder().on_complete(ftr, "ERROR")
                raise
            ftr.end_span(sp)
        get_recorder().on_complete(ftr, ErrorCode.SUCCESS)
        return out

    def _device_batch(self, kind, tmpl, q0, rng, B: int, cls: int) -> bool:
        """Try the synchronous compiled-batch path; True when it ran."""
        if kind == "light" and self._batchable(tmpl, q0):
            tpu = self.proxy.tpu
            # once the class's first batch has learned its capacities, ride
            # the in-flight window: W batches in one device flight, so the
            # ~45-70 ms sync amortizes over W*B queries — the device path's
            # honoring of the `-p` in-flight cap (round-2 Weak #6). The
            # window draws from ALL warm batchable light classes by mix
            # weight (proxy.hpp:477-525's open loop interleaves classes
            # freely), not W copies of one class — one sync serves the mix.
            W = 1
            if getattr(q0, "_many_warm", False) and self._p_cap > 1 \
                    and self._mixed_fail.get(cls, 0) < self.MIXED_FAIL_LIMIT:
                W = min(self._p_cap, 8)  # bound live batch tables
            t0 = get_usec()
            if W > 1:
                pool_cls = [c for c, (k2, t2, p2) in
                            enumerate(self._planned)
                            if k2 == "light"
                            and getattr(p2, "_many_warm", False)
                            and self._batchable(t2, p2)
                            and tpu.merge.supports(p2)
                            and self._mixed_fail.get(c, 0)
                            < self.MIXED_FAIL_LIMIT]
                if cls not in pool_cls:
                    pool_cls = [cls]
                w = self._probs[pool_cls] / self._probs[pool_cls].sum()
                draws = [int(c) for c in rng.choice(pool_cls, size=W, p=w)]
                if cls not in draws:
                    draws[0] = cls  # the chosen class always rides
                jobs = [(self._planned[c][2],
                         self._draw_consts(self._planned[c][1], rng, B))
                        for c in draws]
                try:
                    self._traced_flight(
                        lambda: tpu.execute_batch_mixed(jobs),
                        mode="mixed", W=W, B=B, classes=sorted(set(draws)))
                except (WukongError, RuntimeError):
                    # the failure could come from ANY drawn class's chain —
                    # de-warm them ALL (each re-warms through its own
                    # single-class batch, where a genuinely bad class fails
                    # alone and is disabled with correct blame) instead of
                    # permanently disabling the chosen class on a possibly
                    # innocent verdict. Consecutive mixed failures count
                    # against every participant: at MIXED_FAIL_LIMIT a class
                    # stops joining W>1 flights (it would otherwise re-warm
                    # and oscillate warm->fail forever when the W-fold
                    # footprint itself is what fails, round-4 advisor)
                    for c in set(draws):
                        self._mixed_fail[c] = self._mixed_fail.get(c, 0) + 1
                        self._planned[c][2]._many_warm = False
                    return False
                for c in set(draws):
                    self._mixed_fail[c] = 0
                dt_q = (get_usec() - t0) / (B * W)
                self._served += B * W
                for c in set(draws):
                    self.monitor.add_latency(
                        dt_q, qtype=c, count=B * draws.count(c))
                    self.class_mode[c] = "device-batch"
                return True
            try:
                self._traced_flight(
                    lambda: tpu.execute_batch(
                        q0, self._draw_consts(tmpl, rng, B)),
                    mode="const", W=1, B=B, classes=[cls])
                q0._many_warm = True
                served = B
                if self._mixed_fail.get(cls, 0) >= self.MIXED_FAIL_LIMIT:
                    # parole after a clean single-class batch: one credit,
                    # so an innocent class co-drawn with a culprit rejoins
                    # the mix (and resets to 0 on its first clean flight),
                    # while a true culprit re-pins after ONE more failure
                    # instead of three
                    self._mixed_fail[cls] = self.MIXED_FAIL_LIMIT - 1
            except (WukongError, RuntimeError):
                # RuntimeError covers XLA RESOURCE_EXHAUSTED from the
                # batch footprint — degrade this class to the pool rather
                # than aborting the run
                q0._inst_const = None  # disables _batchable next rounds
                return False
            self._served += served
            self.monitor.add_latency((get_usec() - t0) / served, qtype=cls,
                                     count=served)
            return True
        if kind == "heavy" and q0.start_from_index() \
                and self._heavy_route.get(cls, "device") == "device":
            # slice count from the plan cache (signature + store version),
            # not a mutable attribute on the shared query object
            bh = self.proxy.heavy_index_batch(q0)
            W = 1
            if getattr(q0, "_many_warm", False) and self._p_cap > 1:
                W = min(self._p_cap, 4)  # heavy tables are large; small window
            t0 = get_usec()
            try:
                if W > 1:
                    self._traced_flight(
                        lambda: self.proxy.tpu.execute_batch_index_many(
                            q0, bh, W),
                        mode="index", W=W, B=bh, classes=[cls])
                else:
                    self._traced_flight(
                        lambda: self.proxy.tpu.execute_batch_index(q0, bh),
                        mode="index", W=1, B=bh, classes=[cls])
                    q0._many_warm = True
            except (WukongError, RuntimeError):
                # RuntimeError: XLA OOM from the W-fold window footprint.
                # Record the route decision explicitly (was the q0._heavy_b
                # = -1 sentinel): this class rides the pool from now on.
                self._heavy_route[cls] = "pool"
                return False
            self._served += bh * W
            self.monitor.add_latency((get_usec() - t0) / (bh * W), qtype=cls,
                                     count=bh * W)
            return True
        return False

    # ------------------------------------------------------------------
    def run_serving(self, texts: list, duration_s: float = 5.0,
                    warmup_s: float = 0.5, clients: int = 4,
                    seed: int = 0, weights=None, classes=None) -> dict:
        """Serving-path throughput: ``clients`` closed-loop threads each
        submit one query TEXT at a time through the proxy serving entry
        (parse cache -> plan cache -> batcher-or-direct -> engine) and
        wait for the reply — live traffic, not the compiled-batch emulator
        path. Batching behavior follows ``Global.enable_batching``; the
        before/after pair of this number is `bench.py --serve-batched`'s
        headline. Starts the periodic metrics snapshotter when the
        ``metrics_snapshot_s`` knob asks for one (long-soak observability).

        ``weights`` (aligned with ``texts``) draws a weighted mix instead
        of uniform; ``classes`` (aligned ints, e.g. 0=light 1=heavy) adds
        a per-class qps/latency breakdown to the result — the mixed
        light+heavy benchmark's surface (`bench.py --serve-mixed`).
        """
        import threading

        # NOTE: the pool is not force-started here — fused groups ride the
        # batch lane when a pool is already running (stream/emulator
        # mixes) and dispatch inline on the batcher's flusher thread
        # otherwise. Since the idle relax deepened to a 20ms-capped
        # exponential backoff (scheduler.IDLE_SNOOZE_MAX_US, ROADMAP
        # follow-up i — before/after in BENCH_SERVE.json idle_backoff), a
        # co-located idle pool no longer starves the fused dispatches, so
        # callers that keep the pool started are fine too.
        snap = maybe_start_snapshotter()
        stop = threading.Event()
        served = [0] * clients
        errors = [0] * clients
        lat: list[list] = [[] for _ in range(clients)]
        t_measure = [0.0]
        p = None
        if weights is not None:
            p = np.asarray(weights, dtype=np.float64)
            p = p / p.sum()

        def client(k: int) -> None:
            rng = np.random.default_rng(seed + k)
            while not stop.is_set():
                i = (int(rng.choice(len(texts), p=p)) if p is not None
                     else int(rng.integers(0, len(texts))))
                text = texts[i]
                t0 = get_usec()
                try:
                    q = self.proxy.serve_query(text, blind=True)
                    if q.result.status_code != ErrorCode.SUCCESS:
                        errors[k] += 1
                        continue
                except Exception:
                    errors[k] += 1
                    continue
                if time.monotonic() >= t_measure[0]:
                    served[k] += 1
                    lat[k].append((i, get_usec() - t0))

        threads = [threading.Thread(target=client, args=(k,), daemon=True,
                                    name=f"serve-client-{k}")
                   for k in range(clients)]
        t_measure[0] = time.monotonic() + warmup_s
        for t in threads:
            t.start()
        time.sleep(warmup_s + duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if snap is not None:
            snap.stop()
        n = sum(served)
        all_lat = sorted(dt for xs in lat for (_i, dt) in xs)
        qps = n / duration_s if duration_s > 0 else 0.0
        p50 = all_lat[len(all_lat) // 2] if all_lat else 0
        p99 = all_lat[int(len(all_lat) * 0.99)] if all_lat else 0
        log_info(f"serve: {qps:,.0f} q/s over {duration_s}s "
                 f"({clients} clients, batching="
                 f"{'on' if Global.enable_batching else 'off'}, "
                 f"p50 {p50:,}us, p99 {p99:,}us, "
                 f"{sum(errors)} errors)")
        out = {"qps": round(qps, 1), "served": n, "errors": sum(errors),
               "clients": clients, "duration_s": duration_s,
               "batching": bool(Global.enable_batching),
               "p50_us": int(p50), "p99_us": int(p99)}
        if classes is not None:
            by_class: dict[int, list] = {}
            for xs in lat:
                for i, dt in xs:
                    by_class.setdefault(int(classes[i]), []).append(dt)
            out["by_class"] = {}
            for c, vals in sorted(by_class.items()):
                vals.sort()
                out["by_class"][c] = {
                    "served": len(vals),
                    "qps": round(len(vals) / duration_s, 1),
                    "p50_us": int(vals[len(vals) // 2]),
                    "p99_us": int(vals[int(len(vals) * 0.99)]),
                }
        return out

    def run_graphrag(self, graph_texts: list, hybrid_template: str,
                     anchors: list, duration_s: float = 3.0,
                     warmup_s: float = 0.5, clients: int = 4,
                     seed: int = 0, zipf_a: float = 1.2,
                     hybrid_frac: float = 0.5) -> dict:
        """GraphRAG mixed-workload drive: closed-loop clients submit a
        blend of pure graph queries and hybrid graph+vector queries
        through the live serving path. Each hybrid query instantiates
        ``hybrid_template`` (``{anchor}`` placeholder) with a Zipfian-
        popular anchor — the retrieval-augmented access pattern, where a
        few hot entities anchor most similarity lookups, so the result
        cache and knn route memos see realistic skew instead of uniform
        mush. Returns overall + per-kind q/s and latency percentiles
        (`bench.py --graphrag`'s hybrid_qps headline)."""
        import threading

        snap = maybe_start_snapshotter()
        stop = threading.Event()
        served: list[list] = [[] for _ in range(clients)]  # (kind, dt)
        errors = [0] * clients
        t_measure = [0.0]
        # Zipf anchor popularity: rank r drawn with p ∝ 1/r^a, capped to
        # the anchor list (np.random zipf is unbounded — resample by mod)
        ranks = np.arange(1, len(anchors) + 1, dtype=np.float64)
        pz = ranks ** -float(zipf_a)
        pz /= pz.sum()

        def client(k: int) -> None:
            rng = np.random.default_rng(seed + k)
            while not stop.is_set():
                hybrid = bool(rng.random() < hybrid_frac)
                if hybrid:
                    a = anchors[int(rng.choice(len(anchors), p=pz))]
                    # plain token replace — SPARQL's own braces would
                    # trip str.format's field parser
                    text = hybrid_template.replace("{anchor}", a)
                else:
                    text = graph_texts[int(rng.integers(0,
                                                        len(graph_texts)))]
                t0 = get_usec()
                try:
                    q = self.proxy.serve_query(text, blind=True)
                    if q.result.status_code != ErrorCode.SUCCESS:
                        errors[k] += 1
                        continue
                except Exception:
                    errors[k] += 1
                    continue
                if time.monotonic() >= t_measure[0]:
                    served[k].append((hybrid, get_usec() - t0))

        threads = [threading.Thread(target=client, args=(k,), daemon=True,
                                    name=f"graphrag-client-{k}")
                   for k in range(clients)]
        t_measure[0] = time.monotonic() + warmup_s
        for t in threads:
            t.start()
        time.sleep(warmup_s + duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if snap is not None:
            snap.stop()

        def _pct(vals: list) -> dict:
            vals = sorted(vals)
            return {"served": len(vals),
                    "qps": round(len(vals) / duration_s, 1)
                    if duration_s > 0 else 0.0,
                    "p50_us": int(vals[len(vals) // 2]) if vals else 0,
                    "p99_us": int(vals[int(len(vals) * 0.99)])
                    if vals else 0}

        flat = [x for xs in served for x in xs]
        hybrid_lat = [dt for h, dt in flat if h]
        graph_lat = [dt for h, dt in flat if not h]
        out = {"qps": round(len(flat) / duration_s, 1)
               if duration_s > 0 else 0.0,
               "served": len(flat), "errors": sum(errors),
               "clients": clients, "duration_s": duration_s,
               "zipf_a": zipf_a, "hybrid_frac": hybrid_frac,
               "anchors": len(anchors),
               "hybrid": _pct(hybrid_lat), "graph": _pct(graph_lat)}
        log_info(f"graphrag: {out['qps']:,.0f} q/s mixed "
                 f"(hybrid {out['hybrid']['qps']:,.0f} q/s "
                 f"p99 {out['hybrid']['p99_us']:,}us, graph "
                 f"{out['graph']['qps']:,.0f} q/s, "
                 f"{sum(errors)} errors)")
        return out

    # ------------------------------------------------------------------
    # hot-spot heat scenario (ROADMAP item 3 acceptance fixture)
    # ------------------------------------------------------------------
    def run_hotspot(self, n_ops: int = 1500, zipf_a: float = 1.6,
                    seed: int = 0, sstore=None) -> dict:
        """Skewed-workload heat scenario: drive ``n_ops`` host-side shard
        fetches whose shard choice follows a Zipf(``zipf_a``) law rotated
        onto a seeded hot shard, through the sharded store's normal
        resilience fetch path (so every access charges the heat plane the
        way live stagings do). Proves the telemetry the elastic-migration
        tentpole consumes: the heat report must rank the hot shard first,
        and the per-shard load-rate CDFs must separate hot from cold.
        The scenario then runs the observe-only PlacementAdvisor over the
        tsdb trend window it just produced (ROADMAP item 3's acceptance
        fixture): the emitted MigrationPlan must name the seeded hot
        shard as top donor, and the store must be bit-untouched (verified
        by per-shard store-version equality). Returns {hot, ranked,
        separation, report, plan, plan_donor_is_hot, store_untouched} —
        ``separation`` is the hot shard's p50 access rate over the
        hottest cold shard's.
        """
        from wukong_tpu.obs.heat import get_heat
        from wukong_tpu.obs.placement import get_advisor
        from wukong_tpu.obs.tsdb import get_tsdb

        sstore = sstore if sstore is not None else getattr(
            self.proxy.dist, "sstore", None)
        if sstore is None:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "the hot-spot scenario needs a sharded store "
                              "(--dist)")
        heat = get_heat()
        heat.reset()  # the scenario's ranking starts from a clean slate
        tsdb = get_tsdb()
        tsdb.reset()  # the advisor's trend window starts clean too
        tsdb.sample_once()  # trend-window start marker
        rng = np.random.default_rng(seed)
        hot = int(rng.integers(0, sstore.D))
        _zipf_drive(sstore, hot, n_ops, zipf_a, rng, "hotspot")
        tsdb.sample_once()  # trend-window end marker
        D = sstore.D
        report = self.monitor.heat_report(k=D)
        ranked = [r["shard"] for r in report["ranked"]]
        hot_rate = report["shards"][hot]["load_rate_cdf"].get(0.5, 0.0)
        cold_rates = [d["load_rate_cdf"].get(0.5, 0.0)
                      for s, d in report["shards"].items() if s != hot]
        separation = (hot_rate / max(cold_rates)
                      if cold_rates and max(cold_rates) > 0 else float("inf"))
        # the observe-only proof: identity + version + content CRC per
        # shard, before vs after advising. Version alone is vacuous on a
        # freshly built world (0 until the first dynamic insert), and
        # identity alone misses in-place array writes — the digest walks
        # every persisted array, so neither a swapped stores[] entry nor
        # a raw write can leave the tuple unchanged
        from wukong_tpu.store.persist import gstore_digest

        def _fingerprint():
            return [(id(g), int(getattr(g, "version", 0)), gstore_digest(g))
                    for g in sstore.stores]

        fp_before = _fingerprint()
        advisor = get_advisor()  # the singleton: /plan + Monitor surface it
        advisor.attach_store(sstore)
        plan = advisor.advise_once()
        store_untouched = _fingerprint() == fp_before
        donor_is_hot = plan is not None and plan.donor_shard == hot
        log_info(f"hotspot: shard {hot} drew "
                 f"{report['shards'][hot]['share']:.0%} of {n_ops} fetches; "
                 f"ranked={ranked[:4]}..., load-rate separation "
                 f"{separation:.1f}x; advisor "
                 + (f"plan donor={plan.donor_shard} (hot={donor_is_hot}, "
                    f"~{plan.predicted_move_bytes / 2**20:.1f} MiB, "
                    f"store untouched={store_untouched})"
                    if plan is not None else "emitted no plan"))
        return {"hot": hot, "ranked": ranked,
                "separation": separation, "report": report,
                "plan": plan.to_dict() if plan is not None else None,
                "plan_donor_is_hot": donor_is_hot,
                "store_untouched": bool(store_untouched)}

    def run_rebalance(self, n_ops: int = 1500, zipf_a: float = 1.6,
                      seed: int = 0, sstore=None) -> dict:
        """The hot-spot drill flipped from observe-only to EXECUTED
        (``bench.py --rebalance``; ROADMAP item 3's elastic acceptance):
        run :meth:`run_hotspot` to produce the Zipfian skew and the
        advisor's ``MigrationPlan``, then drive the plan through the live
        shard-migration actuator (``runtime/migration.py`` —
        ``migration_enable`` must be on or the executor refuses, the
        observe-only posture). After every completed phase the migrating
        shard is probed through the normal resilience fetch path and the
        payload compared byte-for-byte against a pre-migration oracle —
        a migration that serves one torn byte fails the drill. Then the
        SAME skewed workload replays against the post-move placement and
        the advisor re-scores host imbalance: the drill passes when the
        post-move max/mean host load-rate ratio drops below
        ``placement_imbalance_x``. Returns the hotspot report plus
        {executed, job, probes, queries_identical, imbalance_before,
        imbalance_after, rebalanced, decision_after, rebalance_gain}.
        """
        from wukong_tpu.obs.heat import get_heat
        from wukong_tpu.obs.placement import MigrationPlan, get_advisor
        from wukong_tpu.obs.tsdb import get_tsdb
        from wukong_tpu.runtime.migration import get_migrator

        sstore = sstore if sstore is not None else getattr(
            self.proxy.dist, "sstore", None)
        rep = self.run_hotspot(n_ops=n_ops, zipf_a=zipf_a, seed=seed,
                               sstore=sstore)
        if rep["plan"] is None:
            raise WukongError(
                ErrorCode.UNSUPPORTED_SHAPE,
                "the rebalance drill needs a MigrationPlan but the "
                "advisor emitted none — raise the skew or lower "
                "placement_imbalance_x")
        plan = MigrationPlan(**rep["plan"])
        donor = plan.donor_shard
        # the byte-identical oracle: the probe payload BEFORE any phase
        # runs (the migration only ever reads the donor, so this stays
        # the ground truth for every copy that serves the shard)
        oracle, ok = sstore._fetch_shard(donor, _probe_read, "rebalance")
        if not ok:
            raise WukongError(ErrorCode.SHARD_UNAVAILABLE,
                              f"donor shard {donor} unreadable before "
                              "the drill even started")
        probes: dict[str, bool] = {}

        def probe(tag: str) -> None:
            out, complete = sstore._fetch_shard(donor, _probe_read,
                                                "rebalance")
            probes[tag] = bool(complete) and bool(
                np.array_equal(np.asarray(out), np.asarray(oracle)))

        mig = get_migrator()
        mig.attach(sstore=sstore, owner=self.proxy)
        job = mig.run_plan(plan, phase_hook=lambda ph, _job: probe(ph))
        probe("post")  # one more after the state machine fully settles
        # replay the SAME skew against the post-move placement and let
        # the advisor re-score host imbalance over a fresh trend window
        heat = get_heat()
        heat.reset()
        tsdb = get_tsdb()
        tsdb.reset()
        tsdb.sample_once()
        _zipf_drive(sstore, rep["hot"], n_ops, zipf_a,
                    np.random.default_rng(seed), "rebalance")
        tsdb.sample_once()
        advisor = get_advisor()
        advisor.attach_store(sstore)
        advisor.advise_once()
        st = advisor.status()
        imb_after = float(st["imbalance"])
        threshold = max(float(Global.placement_imbalance_x), 1.0)
        identical = bool(probes) and all(probes.values())
        gain = (plan.imbalance_before / imb_after
                if imb_after > 0 else float("inf"))
        log_info(
            f"rebalance: shard {donor} -> host {plan.recipient_host} "
            f"({job.bytes_moved / 2**20:.1f} MiB, cutover pause "
            f"{job.cutover_pause_us}us); imbalance "
            f"{plan.imbalance_before:.2f} -> {imb_after:.2f} "
            f"(threshold {threshold:g}, decision {st['decision']}); "
            f"probes identical={identical} {probes}")
        # store_untouched was run_hotspot's pre-execution observe-only
        # proof; the whole point of THIS drill is that the store moved
        return {**rep, "store_untouched": False,
                "executed": True, "job": job.to_dict(),
                "probes": dict(probes), "queries_identical": identical,
                "imbalance_before": float(plan.imbalance_before),
                "imbalance_after": imb_after,
                "rebalanced": imb_after < threshold,
                "decision_after": st["decision"],
                "rebalance_gain": gain}

    # ------------------------------------------------------------------
    # read-mostly serving-cache scenario (ROADMAP item 7 acceptance
    # fixture — obs/reuse.py's decision substrate)
    # ------------------------------------------------------------------
    def run_readmostly(self, texts: list, reads: int = 600,
                       warmup_reads: int = 200,
                       write_rates=(0.0, 0.02, 0.08),
                       zipf_a: float = 1.1, seed: int = 0,
                       write_batch=None, batch_rows: int = 48,
                       tenants: list | None = None,
                       cached: bool = False, views: bool = False) -> dict:
        """The Zipfian read-mostly closed loop: template+const reads drawn
        Zipf(``zipf_a``) over ``texts`` through the REAL serving entry
        (``serve_query``), replayed once per ``write_rates`` phase with
        that many writes interleaved per read (0.02 = one dynamic insert
        batch per 50 reads). Every reply charges the serving-cache
        observatory, so each phase's shadow-cache hit rate is what a
        version-keyed result cache (key = plan signature + consts + store
        version) would have achieved under that write pressure — item 7's
        acceptance numbers, measured before the cache exists.

        Three proofs ride along (the ``run_hotspot`` posture):

        - the zero-write phase's hit rate is ``predicted_hit_rate`` (the
          headline; the skewed mix must clear the cache's economic bar),
        - the store content digest is bit-identical across that phase —
          the ledger + shadow simulation read everything and touch
          nothing,
        - hit rate degrades monotonically as the write rate rises (every
          insert bumps the version the keys carry; ``degrades`` is the
          ordered-phase check), with the write-side ``cache.invalidate``
          events on the same timeline as the reads.

        ``write_batch`` is an [N,3] triple pool writes sample from
        (``batch_rows`` rows per insert, appended non-dedup so every
        batch is a real version edge); phases with a positive write rate
        require it. ``tenants`` rotates reply attribution across the
        given tenant names (default single-tenant).

        ``cached=True`` flips the drill from observe-only to the
        ACTUATOR (wukong_tpu/serve/): the real result cache fronts every
        serve, and every reply is compared byte-for-byte against an
        uncached oracle execution of the same text (status, rows,
        columns, table bytes, projection map) — one mismatch fails the
        ``identical`` verdict. Write phases verify inline, each reply
        against the store state it saw; pure-read phases verify in a
        sweep AFTER the timed window (one oracle per distinct text
        served — re-serving returns the same resident entry, so the
        comparison witnesses exactly the measured bytes without the
        oracle's executions polluting the throughput number).
        ``views=True`` additionally arms rung ii, so hot templates
        promote to materialized views and their hit rates survive the
        write phases. Cached q/s is measured over the cached serves
        alone; ``uncached_qps`` reports the oracle's rate for the
        in-run speedup.
        """
        from wukong_tpu.obs.reuse import get_reuse, reuse_trend
        from wukong_tpu.obs.tsdb import get_tsdb
        from wukong_tpu.store.dynamic import insert_batch_into
        from wukong_tpu.store.persist import gstore_digest

        if any(w > 0 for w in write_rates) and write_batch is None:
            raise WukongError(ErrorCode.SYNTAX_ERROR,
                              "write_rates > 0 need a write_batch pool")
        obs = get_reuse()
        obs.reset()
        tsdb = get_tsdb()
        tsdb.reset()
        tsdb.sample_once()  # trend-window start marker
        rng = np.random.default_rng(seed)
        n = len(texts)
        w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), zipf_a)
        w /= w.sum()
        tens = tenants or ["default"]
        g = self.proxy.g

        rc = vr = None
        knobs0 = (Global.enable_result_cache, Global.enable_views)
        if cached:
            from wukong_tpu.serve import get_serve

            plane = get_serve()
            plane.reset()
            plane.attach(g, self.proxy.str_server)
            Global.enable_result_cache = True
            Global.enable_views = bool(views)
            rc = plane.cache
            vr = plane.views
        cached_us = [0]
        oracle_us = [0]
        oracle_n = [0]
        mismatches = [0]
        deferred: list = []  # zero-write phases: texts to verify after

        def serve_one(k: int, measured: bool = True,
                      verify_inline: bool = True) -> bool:
            text = texts[int(rng.choice(n, p=w))]
            try:
                t0 = get_usec()
                q = self.proxy.serve_query(text, blind=True,
                                           tenant=tens[k % len(tens)])
                cached_us[0] += get_usec() - t0
                ok = q.result.status_code == ErrorCode.SUCCESS
            except Exception:
                return False
            if cached and measured:
                if verify_inline:
                    t1 = get_usec()
                    oq = self._readmostly_oracle(text)
                    oracle_us[0] += get_usec() - t1
                    oracle_n[0] += 1
                    if not _replies_identical(q, oq):
                        mismatches[0] += 1
                else:
                    deferred.append(text)
            return ok

        def verify_deferred() -> None:
            """Zero-write phases: verify AFTER the timed window, once
            per distinct (text, version) served — re-serving returns the
            same resident entry the measured pass handed out, so the
            oracle comparison witnesses exactly the measured bytes
            without polluting the throughput measurement."""
            for text in dict.fromkeys(deferred):
                try:
                    q = self.proxy.serve_query(text, blind=True,
                                               tenant=tens[0])
                    t1 = get_usec()
                    oq = self._readmostly_oracle(text)
                    oracle_us[0] += get_usec() - t1
                    oracle_n[0] += 1
                    if not _replies_identical(q, oq):
                        mismatches[0] += 1
                except Exception:
                    mismatches[0] += 1
            deferred.clear()

        try:
            phases = []
            store_untouched = None
            for write_rate in write_rates:
                every = (int(round(1.0 / write_rate))
                         if write_rate > 0 else 0)
                if write_rate == 0 and store_untouched is None:
                    # the observe-only proof brackets THIS phase (warmup
                    # + measurement are both pure reads), wherever it
                    # sits in the write_rates ordering
                    digest0 = gstore_digest(g)
                    version0 = int(getattr(g, "version", 0))
                # warm the shadow population for THIS phase's steady
                # state (uncounted — the hit rate models a long-running
                # cache, not its cold start)
                for k in range(warmup_reads):
                    serve_one(k, measured=False)
                s0 = obs.shadow.stats()
                r0 = rc.stats() if rc is not None else None
                c0, o0 = cached_us[0], oracle_us[0]
                on0 = oracle_n[0]
                served = errors = writes = 0
                t0 = get_usec()
                for k in range(reads):
                    # write phases verify inline (each reply against the
                    # store state IT saw); pure-read phases defer the
                    # sweep past the timed window — the oracle's own
                    # executions must not pollute the throughput number
                    if serve_one(k, verify_inline=every > 0):
                        served += 1
                    else:
                        errors += 1
                    if every and (k + 1) % every == 0:
                        rows = write_batch[rng.integers(
                            0, len(write_batch), batch_rows)]
                        insert_batch_into(self.proxy._insert_targets(),
                                          rows, dedup=False)
                        writes += 1
                dur_s = max((get_usec() - t0) / 1e6, 1e-9)
                s1 = obs.shadow.stats()
                probes = (s1["hits"] + s1["misses"]
                          - s0["hits"] - s0["misses"])
                hits = s1["hits"] - s0["hits"]
                phase = {
                    "write_rate": float(write_rate),
                    "reads": reads, "served": served, "errors": errors,
                    "writes": writes,
                    "qps": round(reads / dur_s, 1),
                    "probes": probes, "hits": hits,
                    "hit_rate": (round(hits / probes, 4)
                                 if probes else None),
                    "keys_killed": s1["killed"] - s0["killed"],
                }
                if rc is not None:
                    r1 = rc.stats()
                    rp = (r1["hits"] + r1["misses"]
                          - r0["hits"] - r0["misses"])
                    rh = r1["hits"] - r0["hits"]
                    cs = max((cached_us[0] - c0) / 1e6, 1e-9)
                    phase.update({
                        "real_probes": rp, "real_hits": rh,
                        "real_hit_rate": (round(rh / rp, 4)
                                          if rp else None),
                        "real_killed": r1["killed"] - r0["killed"],
                        "cached_qps": round(reads / cs, 1),
                    })
                    verify_deferred()  # outside the throughput window
                    on = oracle_n[0] - on0
                    os_ = max((oracle_us[0] - o0) / 1e6, 1e-9)
                    phase["uncached_qps"] = (round(on / os_, 1)
                                             if on else None)
                phases.append(phase)
                if write_rate == 0 and store_untouched is None:
                    # the observe-only proof: a full read phase (ledger +
                    # shadow probes — and, cached, real fills — on every
                    # reply) left the store bit-identical
                    store_untouched = (
                        gstore_digest(g) == digest0
                        and int(getattr(g, "version", 0)) == version0)
        finally:
            Global.enable_result_cache, Global.enable_views = knobs0
        tsdb.sample_once()  # trend-window end marker
        # monotone degradation within a small jitter tolerance: compared
        # in WRITE-RATE order (not tuple order — a caller may interleave
        # phases), more write pressure must never serve a better hit rate
        rates = [p["hit_rate"]
                 for p in sorted(phases, key=lambda p: p["write_rate"])
                 if p["hit_rate"] is not None]
        degrades = all(b <= a + 0.05 for a, b in zip(rates, rates[1:]))
        predicted = next((p["hit_rate"] for p in phases
                          if p["write_rate"] == 0), None)
        rep = obs.report(k=8)
        out = {
            "predicted_hit_rate": predicted,
            "phases": phases,
            "degrades": bool(degrades),
            "store_untouched": bool(store_untouched)
            if store_untouched is not None else None,
            "zipf_alpha": rep["popularity"]["zipf_alpha"],
            "bytes_saved": rep["shadow"]["bytes_saved"],
            "uncacheable_by_reason": rep["uncacheable_by_reason"],
            "trend": reuse_trend(),
            "report": rep,
        }
        if rc is not None:
            # the actuator verdicts: real-vs-shadow parity on the
            # zero-write phase, byte-identity against the oracle on
            # EVERY measured reply, the in-run speedup, and (views) the
            # flat-curve check — rung ii's whole point
            zero = next((p for p in phases if p["write_rate"] == 0), None)
            real_zero = zero.get("real_hit_rate") if zero else None
            by_rate = sorted((p for p in phases
                              if p.get("real_hit_rate") is not None),
                             key=lambda p: p["write_rate"])
            flat_pts = None
            if (real_zero is not None and by_rate
                    and by_rate[-1]["write_rate"] > 0):
                flat_pts = round(
                    (real_zero - by_rate[-1]["real_hit_rate"]) * 100, 1)
            from wukong_tpu.serve.result_cache import divergence_total

            out["real"] = {
                "identical": mismatches[0] == 0,
                "mismatches": mismatches[0],
                "hit_rate": real_zero,
                "shadow_predicted": predicted,
                "beats_shadow": (real_zero is not None
                                 and predicted is not None
                                 and real_zero >= predicted - 1e-9),
                "readmostly_qps": zero.get("cached_qps") if zero else None,
                "uncached_qps": zero.get("uncached_qps") if zero else None,
                "speedup_vs_uncached": (
                    round(zero["cached_qps"] / zero["uncached_qps"], 2)
                    if zero and zero.get("uncached_qps") else None),
                "hit_rate_drop_pts": flat_pts,
                "views_enabled": bool(views),
                "divergence": divergence_total(),
                "cache": rc.stats(),
                "views": vr.stats() if vr is not None else None,
            }
        log_info(
            "readmostly: predicted hit rate "
            + ("-" if predicted is None else f"{predicted:.1%}")
            + f" on Zipf({zipf_a}) x{n} templates; phases "
            + " ".join(f"w={p['write_rate']:g}:"
                       + ("-" if p["hit_rate"] is None
                          else f"{p['hit_rate']:.0%}")
                       + ("" if p.get("real_hit_rate") is None
                          else f"/real:{p['real_hit_rate']:.0%}")
                       for p in phases)
            + f"; degrades={degrades}, store untouched={store_untouched}"
            + (f"; cached identical={out['real']['identical']} "
               f"qps={out['real']['readmostly_qps']} "
               f"(x{out['real']['speedup_vs_uncached']}), "
               f"drop={out['real']['hit_rate_drop_pts']}pts"
               if rc is not None else ""))
        return out

    def _readmostly_oracle(self, text: str):
        """Uncached oracle execution for the cached drill's byte-identity
        proof: the same parse/plan/execute path ``serve_query`` takes,
        minus the admission/SLO/reuse reply hooks (they would double-
        charge the observatory) and minus the result cache."""
        q = self.proxy._parse_text(text)
        self.proxy._plan_prepared(q, True, None, tenant="oracle")
        eng = self.proxy._engine_for(q, None)
        eng.execute(q)
        return q

    # ------------------------------------------------------------------
    # multi-tenant SLO scenario (ROADMAP item 4 acceptance fixture)
    # ------------------------------------------------------------------
    def run_tenants(self, texts: list, duration_s: float = 3.0,
                    warmup_s: float = 0.3, tenants: list | None = None,
                    chaos: bool = False, chaos_p: float = 0.25,
                    overload_x: float = 1.0, seed: int = 0) -> dict:
        """N tenant classes with conflicting SLOs drive closed-loop
        clients through the REAL serving entry (``serve_query`` with a
        tenant identity), so per-tenant compliance, remaining error
        budget, and multi-window burn rates land in the SLO tracker,
        ``/slo.json``, and the rolling report — item 4's acceptance
        fixture, the way ``run_hotspot`` is item 3's.

        The default cast is three conflicting classes: ``gold`` (tight
        latency target, three nines — almost no error budget), ``silver``
        (moderate), and ``bulk`` (twice the clients, one nine — it floods
        the engines the others contend with). ``chaos=True`` injects
        transient failures at the ``proxy.serve`` boundary with the SAME
        probability for every tenant: only tenants whose availability
        budget cannot absorb the fault rate trip the burn sentinel, and
        each trip dumps exactly one attributable trace per cooldown
        window (tracing is forced on for the run so dumps have traces).
        A tenant entry may carry its own ``texts`` list; otherwise all
        classes share ``texts``.

        ``overload_x > 1`` multiplies every class's client count — the
        admission plane's 2x-capacity overload drill: with
        ``enable_admission`` armed the per-tenant ``partial`` /
        ``rejected`` counts and the ``admission`` report in the output
        show the degrade ladder shedding lowest-weight-first while the
        protected class stays compliant.
        """
        import threading

        from wukong_tpu.obs.slo import (
            SLOSpec,
            get_overload,
            get_slo,
            render_slo,
            reset_labels,
        )
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.faults import FaultPlan, FaultSpec
        from wukong_tpu.utils.logger import log_warn

        classes = tenants if tenants is not None else [
            {"tenant": "gold", "clients": 2,
             "slo": SLOSpec("gold", 0.95, 50.0, 0.999)},
            {"tenant": "silver", "clients": 2,
             "slo": SLOSpec("silver", 0.95, 500.0, 0.99)},
            {"tenant": "bulk", "clients": 4,
             "slo": SLOSpec("bulk", 0.95, 0.0, 0.9)},
        ]
        tracker, signals = get_slo(), get_overload()
        tracker.reset()  # the scenario's report starts from a clean slate
        signals.reset()
        reset_labels()
        get_recorder().clear()
        for c in classes:
            if c.get("slo") is not None:
                tracker.register(c["slo"])

        prev_plan = faults.active()
        prev_tracing = (Global.enable_tracing, Global.trace_sample_every)
        if chaos:
            # the burn dump must carry an attributable trace
            Global.enable_tracing = True
            Global.trace_sample_every = 1
            faults.install(FaultPlan(
                [FaultSpec("proxy.serve", "transient", p=chaos_p)],
                seed=seed))

        stop = threading.Event()
        t_measure = [time.monotonic() + warmup_s]
        stats = [{"served": 0, "errors": 0, "partial": 0, "rejected": 0,
                  "lat": []} for _ in classes]

        def client(ti: int, k: int) -> None:
            c = classes[ti]
            pool = c.get("texts") or texts
            name = c["tenant"]
            rng = np.random.default_rng(seed * 1009 + ti * 31 + k)
            while not stop.is_set():
                text = pool[int(rng.integers(0, len(pool)))]
                t0 = get_usec()
                partial = rejected = False
                try:
                    q = self.proxy.serve_query(text, blind=True,
                                               tenant=name)
                    ok = q.result.status_code == ErrorCode.SUCCESS
                    # the degrade ladder's rung 2: a truncated reply
                    # (mark_partial) counts as neither served nor error
                    partial = not q.result.complete
                except WukongError as e:
                    ok = False
                    rejected = e.code == ErrorCode.CAPACITY_EXCEEDED
                except Exception:
                    ok = False
                dt = get_usec() - t0
                if time.monotonic() >= t_measure[0]:
                    st = stats[ti]
                    if rejected:
                        st["rejected"] += 1
                    elif partial:
                        st["partial"] += 1
                    elif ok:
                        st["served"] += 1
                        st["lat"].append(dt)
                    else:
                        st["errors"] += 1
                    self.monitor.add_latency(dt, qtype=ti)

        nclients = {c["tenant"]: max(int(round(
            int(c.get("clients", 1)) * max(float(overload_x), 0.1))), 1)
            for c in classes}
        threads = [threading.Thread(target=client, args=(ti, k),
                                    daemon=True,
                                    name=f"tenant-{c['tenant']}-{k}")
                   for ti, c in enumerate(classes)
                   for k in range(nclients[c["tenant"]])]
        try:
            for t in threads:
                t.start()
            t_end = time.monotonic() + warmup_s + duration_s
            started = False
            while time.monotonic() < t_end:
                if not started and time.monotonic() >= t_measure[0]:
                    self.monitor.start_thpt()
                    started = True
                self.monitor.maybe_print_thpt()
                time.sleep(0.05)
            stop.set()
            for t in threads:
                t.join(timeout=10)
        finally:
            stop.set()
            faults.install(prev_plan)
            Global.enable_tracing, Global.trace_sample_every = prev_tracing

        out_tenants: dict = {}
        total = 0
        for ti, c in enumerate(classes):
            name = c["tenant"]
            st = stats[ti]
            lat = sorted(st["lat"])
            total += st["served"]
            out_tenants[name] = {
                "clients": nclients[name],
                "served": st["served"],
                "errors": st["errors"],
                "partial": st["partial"],
                "rejected": st["rejected"],
                "qps": round(st["served"] / duration_s, 1),
                "p50_us": int(lat[len(lat) // 2]) if lat else 0,
                "p99_us": int(lat[int(len(lat) * 0.99)]) if lat else 0,
                "slo": tracker.compliance(name),
            }
        burn_dumps = [(r, tr) for (r, tr) in list(get_recorder().dumps)
                      if r == "SLO_BURN"]
        out = {
            "duration_s": duration_s,
            "chaos": bool(chaos),
            "chaos_p": chaos_p if chaos else 0.0,
            "overload_x": float(overload_x),
            "qps": round(total / duration_s, 1),
            "tenant_qps": round(total / duration_s, 1),
            "tenants": out_tenants,
            "alerts": {n: (d["slo"] or {}).get("alerts", 0)
                       for n, d in out_tenants.items()},
            "burn_dumps": [{"tenant": tr.tenant, "trace": tr.trace_id}
                           for (_r, tr) in burn_dumps],
            "slo_report": tracker.report(),
            "signals": signals.report(),
        }
        if Global.enable_admission:
            from wukong_tpu.runtime.admission import get_admission

            out["admission"] = get_admission().report()
        for line in self.monitor.slo_lines(k=len(classes)):
            log_info(line)
        log_info(f"run_tenants: {out['qps']:,.0f} q/s over {duration_s}s"
                 f" ({len(classes)} classes, chaos={chaos}); alerts "
                 + " ".join(f"{n}:{a}" for n, a in out["alerts"].items()))
        if chaos and not burn_dumps:
            log_warn("run_tenants: chaos ran but no burn dump landed "
                     "(thresholds/budgets absorb the fault rate?)")
        _text, js = render_slo()
        out["slo_json"] = js
        return out

    # ------------------------------------------------------------------
    # kill-and-recover drill (fault-tolerance fire drill)
    # ------------------------------------------------------------------
    def run_drill(self, shard: int = 1, texts: list | None = None,
                  rounds: int = 3) -> dict:
        """Force one primary shard down mid-run and prove the recovery
        story end to end: with replication, distributed results stay
        ``complete=True`` via replica failover during the outage; after
        the "host is replaced" (fault cleared) the recovery manager
        rebuilds + promotes the primary and the verify round must match
        the baseline. Returns the drill report (console ``recover -d``).
        """
        from wukong_tpu.obs.metrics import get_registry
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.faults import FaultPlan, FaultSpec

        proxy = self.proxy
        if proxy.dist is None:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "the kill-and-recover drill needs the "
                              "distributed engine (--dist)")
        sstore = proxy.dist.sstore
        m_failover = get_registry().counter(
            "wukong_failover_total",
            "Shard fetches served by a replica after a primary failure",
            labels=("shard",))

        def run_round() -> dict:
            complete = True
            nrows = []
            for t in (texts or [None]):
                q = self._drill_query(t)
                proxy._serve_execute(q, proxy.dist, pinned=True)
                complete &= bool(q.result.complete)
                nrows.append(int(q.result.nrows))
            return {"complete": complete, "nrows": nrows}

        report = {"shard": int(shard),
                  "replication_factor": sstore.replication_factor}
        report["baseline"] = run_round()
        f0 = m_failover.value(shard=str(shard))
        # save any operator-installed chaos plan: the drill must not end a
        # soak run's fault schedule as a side effect
        prev_plan = faults.active()
        faults.install(FaultPlan([FaultSpec("dist.shard_fetch",
                                            "shard_down", shard=shard)]))
        # the dead host's staged device data dies with it — force restaging
        # so the outage actually exercises the fetch/failover path
        sstore.invalidate_stagings()
        try:
            outage = [run_round() for _ in range(max(rounds, 1))]
        finally:
            faults.install(prev_plan)  # the dead host is replaced
        report["outage"] = {
            "rounds": len(outage),
            "complete": all(r["complete"] for r in outage),
            "nrows_match": all(r["nrows"] == report["baseline"]["nrows"]
                               for r in outage),
            "failovers": int(m_failover.value(shard=str(shard)) - f0),
        }
        # the recovery watcher may have healed in the background already
        # (it races this explicit sweep by design); "healthy" is the
        # invariant, the healed list just says who did the work
        report["healed"] = proxy.recovery().heal_once(force=True)
        report["healthy"] = not proxy.recovery().sick_shards()
        verify = run_round()
        report["recovered"] = {
            "complete": verify["complete"],
            "nrows_match": verify["nrows"] == report["baseline"]["nrows"],
        }
        log_info(f"drill shard={shard}: outage complete="
                 f"{report['outage']['complete']} "
                 f"(failovers={report['outage']['failovers']}), healthy="
                 f"{report['healthy']}, recovered match="
                 f"{report['recovered']['nrows_match']}")
        return report

    def run_proc_drill(self, ckpt_dir: str, texts: list | None = None,
                       kill_group: int = 0, rounds: int = 3) -> dict:
        """Process-granularity chaos drill: spawn the worker pool, prove
        the socket path is byte-identical to loopback, SIGKILL one worker
        mid-query-stream (replies must stay ``complete=True`` and
        byte-identical via replica failover while any replica lives),
        grow the WAL past the boot checkpoint, then restart the worker
        and assert it rejoined digest-identical after checkpoint +
        WAL-tail replay. Returns the drill report."""
        from wukong_tpu.obs.metrics import get_registry
        from wukong_tpu.runtime.procs import ProcSupervisor
        from wukong_tpu.store.dynamic import insert_batch_into
        from wukong_tpu.store.persist import gstore_digest

        proxy = self.proxy
        if proxy.dist is None:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "the kill-a-process drill needs the "
                              "distributed engine (--dist)")
        sstore = proxy.dist.sstore
        reg = get_registry()
        m_failover = reg.counter(
            "wukong_failover_total",
            "Shard fetches served by a replica after a primary failure",
            labels=("shard",))
        m_restarts = reg.counter(
            "wukong_proc_restarts_total",
            "Worker processes restarted by the supervisor",
            labels=("group",))
        probes = list(texts) if texts else [None]

        def ask(t):
            q = self._drill_query(t)
            q.result.blind = False  # byte-identity needs the real table
            proxy._serve_execute(q, proxy.dist, pinned=True)
            return q

        def probe_round() -> list:
            # restage every round so the fetch path (and therefore the
            # transport) is actually on the serving path, not a warm cache
            sstore.invalidate_stagings()
            return [ask(t) for t in probes]

        def identical(qs: list) -> bool:
            return all(_replies_identical(o, q) for o, q in zip(oracle, qs))

        oracle = probe_round()  # loopback ground truth
        report = {"replication_factor": sstore.replication_factor,
                  "probes": len(probes)}
        sup = ProcSupervisor(sstore, ckpt_dir)
        sup.start()
        try:
            gid = int(kill_group)
            killed_shards = list(sup.groups[gid].shard_ids)
            report["groups"] = {g: sorted(grp.shard_ids)
                                for g, grp in sup.groups.items()}
            report["worker_jax_loaded"] = sup.worker_jax_loaded
            base = probe_round()
            report["proc_identical"] = identical(base)
            # -- SIGKILL mid-query-stream --------------------------------
            f0 = sum(m_failover.value(shard=str(s)) for s in killed_shards)
            r0 = m_restarts.value(group=str(gid))
            outage: list = []
            killed = False
            for r in range(max(rounds, 1)):
                sstore.invalidate_stagings()
                for j, t in enumerate(probes):
                    outage.append(ask(t))
                    if not killed and r == 0 and j == 0:
                        sup.kill(gid)
                        # the dead worker's staged segments die with the
                        # fetch cache: restage so the very next fetch hits
                        # the corpse and has to fail over
                        sstore.invalidate_stagings()
                        killed = True
            report["outage"] = {
                "rounds": max(rounds, 1),
                "complete": all(q.result.complete for q in outage),
                "identical": all(_replies_identical(
                    oracle[k % len(probes)], q)
                    for k, q in enumerate(outage)),
                "failovers": int(sum(m_failover.value(shard=str(s))
                                     for s in killed_shards) - f0),
            }
            # -- grow the WAL past the boot checkpoint -------------------
            # a fresh predicate id: the insert must be replayed by the
            # restarting worker (digest proof) without perturbing the
            # probe queries' reply bytes. Without an active WAL the
            # mutation could never reach the worker — skip it, the rejoin
            # then proves the checkpoint path alone.
            from wukong_tpu.store.wal import active_wal

            wal_on = active_wal() is not None
            if wal_on:
                g0 = proxy.g
                pid_new = max((p for (p, _d) in g0.index), default=0) + 9
                batch = np.array([[900001 + i, pid_new, 900101 + i]
                                  for i in range(4)], dtype=np.int64)
                insert_batch_into(proxy._insert_targets(), batch,
                                  dedup=False)
            # -- restart through checkpoint + WAL-tail replay ------------
            ok = sup.restart(gid)
            parent = {sid: int(gstore_digest(sstore.stores[sid]))
                      for sid in killed_shards}
            report["rejoin"] = {
                "ok": bool(ok),
                "wal_replayed": wal_on,
                "digests_match": sup.worker_digests(gid) == parent,
                "repeered": all(sup.transport.peer_for(s) is not None
                                for s in killed_shards),
                "restarts": int(m_restarts.value(group=str(gid)) - r0),
            }
            verify = probe_round()
            report["recovered"] = {
                "complete": all(q.result.complete for q in verify),
                "identical": identical(verify),
            }
        finally:
            sup.stop()
        post = probe_round()  # loopback restored: zero-touch both ways
        report["loopback_restored"] = {
            "mode": sstore.transport.mode,
            "identical": identical(post),
        }
        log_info(f"proc drill group={kill_group}: outage complete="
                 f"{report['outage']['complete']} identical="
                 f"{report['outage']['identical']} "
                 f"(failovers={report['outage']['failovers']}), rejoin "
                 f"digests_match={report['rejoin']['digests_match']}, "
                 f"loopback identical={report['loopback_restored']['identical']}")
        return report

    def _drill_query(self, text: str | None):
        """A drill probe: the given SPARQL text, or a synthesized one-hop
        scan over the most populous predicate index (works on any dataset
        without a query file)."""
        if text is not None:
            q = Parser(self.proxy.str_server).parse(text)
        else:
            from wukong_tpu.sparql.ir import (
                Pattern,
                PatternGroup,
                SPARQLQuery,
            )
            from wukong_tpu.types import IN, OUT

            g = self.proxy.g
            pid = max(
                (k[0] for k, v in g.index.items()
                 if k[1] == IN and k[0] not in g.type_ids and len(v)),
                key=lambda p: len(g.index[(p, IN)]), default=None)
            if pid is None:
                raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                                  "no predicate index to drill against")
            q = SPARQLQuery()
            q.pattern_group = PatternGroup(
                patterns=[Pattern(subject=-1, predicate=int(pid),
                                  direction=OUT, object=-2)])
            q.result.nvars = 2
            q.result.required_vars = [-1, -2]
        q.result.blind = True
        q.deadline = Deadline.from_config()
        self._plan(q)
        return q

    # ------------------------------------------------------------------
    @staticmethod
    def _batchable(tmpl, q_planned) -> bool:
        """One %placeholder, and the plan's start constant IS that placeholder
        (otherwise batching would substitute candidates into the wrong slot)."""
        if tmpl is None or len(tmpl.pos) != 1:
            return False
        pats = q_planned.pattern_group.patterns
        return (bool(pats) and pats[0].subject > 0 and pats[0].predicate > 0
                and pats[0].subject == getattr(q_planned, "_inst_const", None))

    @staticmethod
    def _draw_consts(tmpl, rng, B: int) -> np.ndarray:
        cand = tmpl.candidates[0]
        return np.asarray(cand[rng.integers(0, len(cand), B)], dtype=np.int64)
