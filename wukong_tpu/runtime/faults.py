"""Deterministic fault injection for the chaos suite.

The execution stack exposes named fault sites (``faults.site("dist.shard_fetch",
shard=3)``) at its transient failure points; a :class:`FaultPlan` installed for
the process decides — from a seeded RNG — whether each site call is delayed,
fails transiently, or hits a persistently-down shard. The same seed + specs
replay the exact failure schedule, so chaos tests are ordinary deterministic
tests rather than flaky probabilistic ones.

A plan can be installed programmatically (tests) or via the
``WUKONG_FAULT_PLAN`` env var (chaos runs of the real binaries):

    WUKONG_FAULT_PLAN="seed=42;dist.shard_fetch:transient,p=0.3,count=2;hdfs.read:delay,delay=0.05"
    WUKONG_FAULT_PLAN="dist.shard_fetch:shard_down,shard=1"

Sites instrumented today:
- ``dist.shard_fetch``  — per-shard host CSR fetch in parallel/sharded_store.py
- ``dist.chain_dispatch`` — compiled-chain dispatch in parallel/dist_engine.py
- ``hdfs.read``         — HDFS CLI invocations in loader/hdfs.py
- ``pool.execute``      — per-query execution in runtime/scheduler.py
- ``dynamic.insert``    — online batch insert in store/dynamic.py
  (``shard`` = partition sid; fires before any mutation, so retries are safe)
- ``stream.ingest``     — per-epoch commit in stream/ingest.py (retried with
  backoff when dedup makes the batch idempotent)
- ``wal.append``        — write-ahead-log append in store/wal.py (fires
  before any bytes land: an injected failure fails the commit with both
  the log and the store untouched — the batch was never acknowledged)
- ``replica.fetch``     — failover fetch from a shard replica in
  parallel/sharded_store.py (``shard`` = the replica HOST id)
- ``checkpoint.write``  — checkpoint bundle write in runtime/recovery.py
- ``join.materialize``  — WCOJ sorted-edge-table materialization in
  join/wcoj.py (fires before any result state is touched, so the proxy
  degrades the query to the walk instead of erroring)
- ``join.slice``        — one hash-partition slice of a distributed join
  in join/dist.py (``shard`` = slice index; fires before the slice runs,
  so an injected failure costs one inline retry on the gather thread —
  per-slice fallback, never a failed query)
- ``proxy.serve``       — serving-boundary dispatch in runtime/proxy.py
  (fires before any engine dispatch: an injected failure surfaces as a
  client-visible error reply — the SLO-plane chaos scenario's way of
  burning per-tenant error budgets through the real serving path)
- ``migration.clone`` / ``migration.catchup`` / ``migration.cutover`` —
  the shard-migration actuator's phase entries (runtime/migration.py).
  Each fires BEFORE its phase touches any shared state, so an injected
  failure aborts the migration cleanly back to the donor (content digest
  unchanged) or leaves a resumable crash for ``resume()`` to roll forward.

When no plan is installed every hook is a cheap no-op.
"""

from __future__ import annotations

import hashlib
import os
import random
import time
from dataclasses import dataclass, field


# THE central fault-site registry: every ``faults.site("X")`` hook in the
# package must use a name declared here, every name here must have a live
# hook, and every name must be exercised by at least one test — all three
# directions are enforced mechanically by the ``fault-sites`` analysis
# gate (wukong_tpu/analysis/drift.py). Adding a site = add the hook, add
# the name here, add a deterministic chaos test.
KNOWN_FAULT_SITES = frozenset({
    "dist.shard_fetch",    # per-shard host CSR fetch (sharded_store)
    "dist.chain_dispatch",  # compiled-chain dispatch (dist_engine)
    "hdfs.read",           # HDFS CLI invocations (loader/hdfs.py)
    "pool.execute",        # per-query execution (runtime/scheduler.py)
    "dynamic.insert",      # online batch insert (store/dynamic.py)
    "stream.ingest",       # per-epoch commit (stream/ingest.py)
    "wal.append",          # write-ahead-log append (store/wal.py)
    "replica.fetch",       # failover replica fetch (sharded_store)
    "checkpoint.write",    # checkpoint bundle write (runtime/recovery.py)
    "batch.heavy.dispatch",  # fused heavy-lane dispatch (runtime/batcher.py)
    "join.materialize",    # WCOJ sorted-table materialization (join/wcoj.py)
    "join.slice",          # distributed-join partition slice (join/dist.py;
                           # fires before the slice touches any state, so an
                           # injected failure degrades per-slice — one
                           # inline retry on the gather thread — never
                           # per-query)
    "proxy.serve",         # serving-boundary dispatch (runtime/proxy.py;
                           # the SLO-plane chaos scenario's injection point)
    "vector.upsert",       # embedding upsert batch (vector/vstore.py;
                           # fires BEFORE the WAL append, so an injected
                           # failure leaves WAL and vstore both untouched)
    "transport.connect",   # socket-transport peer connect (runtime/transport.py)
    "transport.send",      # socket-transport frame send (fires before the
                           # syscall, so an injected failure exercises the
                           # drop-connection + reconnect + breaker path a
                           # dead worker process does)
    "transport.recv",      # socket-transport frame recv (same contract)
    "migration.clone",     # shard-migration snapshot (runtime/migration.py)
    "migration.catchup",   # shard-migration WAL-tail replay + dual-write
    "migration.cutover",   # shard-migration read-path swap
    "template.compile",    # whole-plan program staging/trace
                           # (engine/template_compile.py; fires before any
                           # query state is touched, so an injected failure
                           # degrades to the host walk byte-identically and
                           # latches the per-template demotion)
    "template.dispatch",   # whole-plan fused XLA dispatch (same contract:
                           # the result commits only after a clean fetch,
                           # so mid-flight chaos degrades, never corrupts)
})


class TransientFault(Exception):
    """An injected transient infrastructure failure (retryable)."""


class ShardDown(Exception):
    """An injected persistent shard failure (not retryable)."""

    def __init__(self, site: str, shard: int | None):
        self.site = site
        self.shard = shard
        super().__init__(f"injected shard-down at {site} (shard={shard})")


@dataclass
class FaultSpec:
    """One injection rule. kind: 'delay' | 'transient' | 'shard_down'."""

    site: str
    kind: str
    p: float = 1.0  # per-call firing probability (seeded RNG)
    count: int | None = None  # max times this spec fires (None = unlimited)
    after: int = 0  # skip the first N matching calls
    delay_s: float = 0.0  # 'delay' kind: how long to sleep
    shard: int | None = None  # restrict to one shard (None = any)
    fired: int = field(default=0, compare=False)
    seen: int = field(default=0, compare=False)


class FaultPlan:
    """Seeded, replayable schedule of injected faults.

    Each spec draws from its own RNG stream (derived from the plan seed, the
    site name, and the spec index), so whether one site fires never perturbs
    another site's schedule — the property that makes `same seed => same
    failure schedule` hold under reordered inter-site call interleavings.
    """

    def __init__(self, specs: list[FaultSpec] | None = None, seed: int = 0,
                 sleep=time.sleep):
        self.seed = int(seed)
        self.specs = list(specs or [])
        self.sleep = sleep
        self.history: list[tuple[str, int | None, str]] = []
        self._rngs: dict[int, random.Random] = {}

    def _rng(self, idx: int) -> random.Random:
        if idx not in self._rngs:
            h = hashlib.sha256(
                f"{self.seed}:{self.specs[idx].site}:{idx}".encode()).digest()
            self._rngs[idx] = random.Random(int.from_bytes(h[:8], "big"))
        return self._rngs[idx]

    def fire(self, site: str, shard: int | None = None) -> None:
        """Apply every matching spec to one site call. Raises TransientFault /
        ShardDown or sleeps, per the seeded schedule."""
        for idx, sp in enumerate(self.specs):
            if sp.site != site:
                continue
            if sp.shard is not None and shard is not None and sp.shard != shard:
                continue
            sp.seen += 1
            if sp.seen <= sp.after:
                continue
            if sp.count is not None and sp.fired >= sp.count:
                continue
            # draw even when p == 1 so trimming p later replays the same
            # underlying stream
            if self._rng(idx).random() >= sp.p:
                continue
            sp.fired += 1
            self.history.append((site, shard, sp.kind))
            # observability: injected faults land on the ambient trace and
            # the metrics registry, so a chaos run's trace explains itself
            from wukong_tpu.obs.metrics import get_registry
            from wukong_tpu.obs.trace import trace_event

            trace_event("fault.injected", site=site, kind=sp.kind,
                        shard=shard)
            get_registry().counter(
                "wukong_faults_injected_total", "Injected fault firings",
                labels=("site", "kind")).labels(site=site,
                                                kind=sp.kind).inc()
            if sp.kind == "delay":
                self.sleep(sp.delay_s)
            elif sp.kind == "transient":
                raise TransientFault(f"injected transient at {site}"
                                     f" (shard={shard})")
            elif sp.kind == "shard_down":
                raise ShardDown(site, shard)
            else:
                raise ValueError(f"unknown fault kind: {sp.kind}")


def parse_plan(text: str, sleep=time.sleep) -> FaultPlan:
    """Parse the compact ``WUKONG_FAULT_PLAN`` form: ';'-separated entries,
    optionally starting with ``seed=N``; each entry is
    ``<site>:<kind>[,k=v...]`` with keys p/count/after/delay/shard."""
    seed = 0
    specs: list[FaultSpec] = []
    for ent in text.split(";"):
        ent = ent.strip()
        if not ent:
            continue
        if ent.startswith("seed="):
            seed = int(ent[5:])
            continue
        site, _, rest = ent.partition(":")
        parts = rest.split(",")
        kind = parts[0].strip()
        if kind not in ("delay", "transient", "shard_down"):
            # validate at parse time — a bad kind must be a config error at
            # startup, not a ValueError mid-query from FaultPlan.fire
            raise ValueError(f"unknown fault kind: {kind!r} in {ent!r} "
                             "(expected delay|transient|shard_down)")
        kw: dict = {}
        for p in parts[1:]:
            k, _, v = p.partition("=")
            k = k.strip()
            if k == "p":
                kw["p"] = float(v)
            elif k == "count":
                kw["count"] = int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "delay":
                kw["delay_s"] = float(v)
            elif k == "shard":
                kw["shard"] = int(v)
            else:
                raise ValueError(f"unknown fault-plan key: {k}")
        specs.append(FaultSpec(site=site.strip(), kind=kind, **kw))
    return FaultPlan(specs, seed=seed, sleep=sleep)


# ---------------------------------------------------------------------------
# process-wide installation
# ---------------------------------------------------------------------------

_state: dict = {"plan": None, "env_checked": False}


def install(plan: FaultPlan | None) -> None:
    _state["plan"] = plan
    _state["env_checked"] = True  # explicit install overrides the env var


def clear() -> None:
    _state["plan"] = None
    _state["env_checked"] = True


def active() -> FaultPlan | None:
    if not _state["env_checked"]:
        _state["env_checked"] = True
        text = os.environ.get("WUKONG_FAULT_PLAN")
        if text:
            _state["plan"] = parse_plan(text)
    return _state["plan"]


def site(name: str, shard: int | None = None) -> None:
    """Fault hook: no-op unless a plan is installed."""
    plan = active()
    if plan is not None:
        plan.fire(name, shard)
