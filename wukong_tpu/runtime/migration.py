"""Live shard-migration actuator: crash-safe clone / catch-up / cutover / retire.

PR 11's placement observatory ended at a literal ``MigrationPlan`` artifact
(obs/placement.py) — the advisor could *say* "move shard 2 to host 3" but
placement stayed static hash, so a hot shard stayed hot until restart.
:class:`MigrationExecutor` is the pure ACTUATOR of those plans (ROADMAP
item 3, Pragh ATC'19 — live repartitioning without downtime): it consumes
a ``MigrationPlan`` and drives a four-phase state machine, each phase
journaled (``shard.migrate.*``) and each of clone / catch-up / cutover an
injectable fault site:

1. **clone** — snapshot the donor shard's primary onto the recipient host
   through the TRANSPORT seam (``Transport.snapshot``): the loopback
   transport is the crash-consistent ``persist.clone_gstore`` structural
   copy, the socket transport moves the shard through the checkpoint wire
   codec (from its worker process when one serves it). The snapshot is
   taken under the WAL *mutation lock*, so it is exact at a recorded WAL
   high-water mark (``seq_clone``); the (long, in a real cluster) transfer
   then runs with writes flowing normally to the donor.
2. **catch-up** — replay the WAL tail ``(seq_clone, now]`` onto the
   recipient under the mutation lock (writes pause only for this bounded
   window, not the clone), with re-logging suppressed, then enroll the
   recipient as a **dual-write sink** (store/dynamic.py) inside the same
   critical section — from this instant every committed batch/epoch
   reaches the recipient too, so no mutation can fall between replay and
   dual-write. With the WAL off, the dual-write starts at the snapshot
   instant instead and catch-up is a no-op.
3. **cutover** — atomically swap the read path to the recipient
   (``ShardedDeviceStore.cutover_shard``: primary install + placement
   update + breaker close + staging invalidation — the failover/rebuild
   promotion machinery), deroll the dual sink, and rebind long-lived
   mutation fan-out lists (the stream ingestor's), all in ONE
   mutation-locked section. The pause is measured (``cutover_pause_us``).
   With ``migration_rotate_reads`` (default on) the donor copy is demoted
   to a read-rotation replica on its old host — reads split
   donor+recipient, which is exactly the plan's predicted-balance model
   (replica-read rotation, ROADMAP follow-up j); off drops it outright.
4. **retire** — release the donor copy (unless rotated), re-arm the
   shard's breaker, journal completion, observe the duration histogram.

Crash safety: every phase is resumable and abortable. ``abort()`` rolls
cleanly back to the donor — dual sink derolled, a completed cutover
swapped back — with the donor's ``persist.gstore_digest`` untouched (the
migration only ever *reads* the donor). ``resume()`` rolls forward from
the recorded state: a crash in clone or catch-up restarts from a fresh
snapshot (a partially-replayed recipient must never double-apply), a
crash at cutover redoes the idempotent swap, a crash at retire re-retires.
Writes issued during any phase survive: pre-catch-up writes are in the
WAL tail the (re-)clone covers, post-catch-up writes dual-apply.

Known bound (shared with the heal/rebuild promotion path): a writer that
snapshotted its fan-out target list *before* a cutover and commits *after*
it applies to the retired donor object. The window is one in-flight
``_insert_targets()`` call; stream epochs are immune (their bound list is
rebound inside the cutover's critical section).

Wired behind the ``migration_enable`` knob (default OFF: the advisor
stays observe-only, the PR 11 posture — ``run_plan`` refuses). With it on
and ``placement_interval_s > 0``, the actuator loop sweeps the advisor
continuously against ``PLACEMENT_INPUTS`` and executes each emitted plan.
Surfaces: the ``migrate`` / ``migrate -abort`` console verbs, in-flight
state on ``/plan`` and ``/healthz`` (a mid-cutover shard reports
degraded-not-dead), a Monitor ``Migration[...]`` line, and the
``wukong_migration_*`` metrics.
"""

from __future__ import annotations

import threading
import weakref
from collections import deque
from dataclasses import dataclass, field

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.events import emit_event
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.placement import MigrationPlan, get_advisor, get_lineage
from wukong_tpu.store.wal import active_wal, mutation_lock
from wukong_tpu.utils.errors import ErrorCode, WukongError
from wukong_tpu.utils.logger import log_info, log_warn
from wukong_tpu.utils.timer import get_usec

#: the actuator's phase order — a literal registry (the migration-safety
#: analysis gate pins it and requires every phase transition to journal)
MIGRATION_PHASES = ("clone", "catchup", "cutover", "retire")

# the executor state lock guards job-field/history updates only (plain
# scalar/deque writes) — innermost by construction; events/metrics are
# always emitted OUTSIDE it, and the phase bodies take the WAL mutation
# lock BEFORE ever touching it
declare_leaf("migration.state")

_M_MIGRATIONS = get_registry().counter(
    "wukong_migrations_total", "Shard migrations by outcome",
    labels=("outcome",))
_M_BYTES = get_registry().counter(
    "wukong_migration_bytes_total", "Bytes moved by shard migrations")
_M_DURATION = get_registry().histogram(
    "wukong_migration_duration_us",
    "End-to-end shard-migration duration (usec)")
_M_ABORTS = get_registry().counter(
    "wukong_migration_aborts_total", "Migration aborts by cause",
    labels=("cause",))


@dataclass
class MigrationJob:
    """One migration's live state — the resumable record ``resume()``
    rolls forward from and ``abort()`` rolls back from."""

    plan: MigrationPlan
    t_start_us: int = 0
    phase: str = "pending"  # pending|clone|catchup|cutover|retire|done|aborted
    next_i: int = 0  # index of the next phase to run (resume cursor)
    attempts: int = 0  # execute/resume entries (journaled on re-runs)
    seq_clone: int = -1  # WAL high-water mark at the snapshot instant
    replayed: int = 0  # WAL records replayed by catch-up
    bytes_moved: int = 0
    cutover_pause_us: int = 0
    donor_host: int | None = None
    abort_cause: str = ""
    rotated: bool = False  # donor demoted to a read-rotation replica
    event_ids: list = field(default_factory=list)
    recipient: object = None  # the in-flight clone (GStore)
    donor_store: object = None  # rollback anchor until retire
    dirty_catchup: bool = False  # a partial replay may have landed

    def to_dict(self) -> dict:
        return {"plan_id": self.plan.plan_id,
                "donor_shard": self.plan.donor_shard,
                "recipient_host": self.plan.recipient_host,
                "phase": self.phase, "attempts": self.attempts,
                "seq_clone": self.seq_clone, "replayed": self.replayed,
                "bytes_moved": self.bytes_moved,
                "cutover_pause_us": self.cutover_pause_us,
                "rotated": self.rotated,
                "abort_cause": self.abort_cause,
                "event_ids": list(self.event_ids)}


def _sink_key(donor: int) -> tuple:
    return ("migrate", int(donor))


class MigrationExecutor:
    """Drives MigrationPlans through the four-phase state machine; one
    migration in flight at a time (the cluster moves one shard, proves
    balance, then moves the next — the advisory loop's cadence)."""

    def __init__(self, sstore=None, owner=None):
        # weakref posture (the advisor's): the executor is process-global,
        # and a strong capture would pin a retired world's partitions (and
        # the proxy that owns them) in memory
        self._sstore_ref = None  # lock-free: rebound atomically; phases deref once
        self._owner_ref = None  # lock-free: rebound atomically (the proxy, for fan-out rebinds)
        self.attach(sstore=sstore, owner=owner)
        self._lock = make_lock("migration.state")
        # reference swaps + job-field updates; phases run on one driver
        # thread, readers are /plan + Monitor + healthz threads
        self._job: MigrationJob | None = None  # guarded by: _lock
        self._history: deque = deque(maxlen=32)  # guarded by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None  # lock-free: start/stop are operator-thread only

    # ------------------------------------------------------------------
    def attach(self, sstore=None, owner=None) -> None:
        """Bind the sharded store (weakly) and the owning proxy (weakly;
        duck-typed: ``_insert_targets()`` and optionally ``_stream`` /
        ``_on_store_change`` are used for post-cutover fan-out rebinds)."""
        if sstore is not None:
            self._sstore_ref = weakref.ref(sstore)
        if owner is not None:
            self._owner_ref = weakref.ref(owner)

    def _store(self):
        ref = self._sstore_ref
        return ref() if ref is not None else None

    def _owner(self):
        ref = self._owner_ref
        return ref() if ref is not None else None

    def _require_store(self):
        ss = self._store()
        if ss is None:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "no live sharded store attached — nothing "
                              "to migrate (--dist worlds only)")
        return ss

    # ------------------------------------------------------------------
    # the state machine
    # ------------------------------------------------------------------
    def run_plan(self, plan: MigrationPlan, phase_hook=None,
                rollback: bool = True) -> MigrationJob:
        """Run one plan end to end. ``phase_hook(phase, job)`` fires after
        each completed phase (drills interleave probes/writes there). Any
        phase failure rolls back via :meth:`abort` and re-raises;
        ``rollback=False`` leaves the crashed state in place instead (the
        kill drill's posture — :meth:`resume` picks it up)."""
        if not Global.migration_enable:
            raise WukongError(
                ErrorCode.UNSUPPORTED_SHAPE,
                "migration_enable is off — the actuator refuses to move "
                "shards (observe-only posture; flip the knob to arm it)")
        ss = self._require_store()
        donor = int(plan.donor_shard)
        recipient_host = int(plan.recipient_host)
        if not 0 <= donor < ss.D:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              f"plan names donor shard {donor} but the "
                              f"store has {ss.D} shards")
        if not 0 <= recipient_host < ss.D:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              f"plan names recipient host {recipient_host} "
                              f"but the cluster has {ss.D} hosts")
        with self._lock:
            if self._job is not None and self._job.phase not in ("done",
                                                                 "aborted"):
                cur = self._job.plan.plan_id
            else:
                cur = None
                self._job = MigrationJob(plan=plan, t_start_us=get_usec())
            job = self._job
        if cur is not None:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              f"migration {cur} is already in flight — "
                              "abort it or let it finish")
        try:
            self._run(job, phase_hook)
        except BaseException as e:
            if rollback:
                self.abort(cause=self._cause(e))
            raise
        return job

    def resume(self, phase_hook=None) -> MigrationJob:
        """Roll the crashed in-flight migration forward from its recorded
        state. A crash in clone or catch-up restarts from a fresh snapshot
        (a partially-replayed recipient must never double-apply a
        non-dedup record); a crash at cutover/retire redoes the idempotent
        phase."""
        with self._lock:
            job = self._job
        if job is None or job.phase in ("done", "aborted"):
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "no crashed migration to resume (aborted "
                              "plans re-execute from scratch)")
        if job.next_i <= 1 or job.dirty_catchup:
            # clone or catch-up did not complete: discard the copy and
            # re-snapshot — the WAL tail from the NEW seq_clone covers
            # every write the discarded copy might have missed
            self._drop_copy(job)
            with self._lock:
                job.next_i = 0
                job.replayed = 0
                job.dirty_catchup = False
        try:
            self._run(job, phase_hook)
        except BaseException as e:
            self.abort(cause=self._cause(e))
            raise
        return job

    def _run(self, job: MigrationJob, phase_hook) -> None:
        phases = (self._phase_clone, self._phase_catchup,
                  self._phase_cutover, self._phase_retire)
        with self._lock:
            job.attempts += 1
        while job.next_i < len(phases):
            i = job.next_i
            # a concurrent abort() (the operator's `migrate -abort`
            # against the actuator loop's driver thread) wins: the state
            # machine must never roll forward past an abort, and a phase
            # that raced the abort gets its side effects re-rolled-back
            with self._lock:
                aborted = job.phase == "aborted"
                if not aborted:
                    job.phase = MIGRATION_PHASES[i]
            if aborted:
                self._abort_raced(job)
            phases[i](job)
            with self._lock:
                aborted = job.phase == "aborted"
                if not aborted:
                    job.next_i = i + 1
            if aborted:
                self._abort_raced(job)
            if phase_hook is not None:
                phase_hook(MIGRATION_PHASES[i], job)
        with self._lock:
            if job.phase == "aborted":  # abort raced the final hook
                aborted = True
            else:
                aborted = False
                job.phase = "done"
                job.donor_store = None  # rollback anchor released
                self._history.append(job)
        if aborted:
            self._abort_raced(job)
        _M_MIGRATIONS.labels(outcome="completed").inc()
        _M_DURATION.observe(get_usec() - job.t_start_us)
        log_info(
            f"migration {job.plan.plan_id} complete: shard "
            f"{job.plan.donor_shard} -> host {job.plan.recipient_host} "
            f"({job.bytes_moved / 2**20:.1f} MiB, {job.replayed} WAL "
            f"records caught up, cutover pause {job.cutover_pause_us}us"
            f"{', donor rotated' if job.rotated else ''})")

    def _abort_raced(self, job: MigrationJob) -> None:
        """A concurrent :meth:`abort` landed while a phase was running:
        its rollback may predate the racing phase's side effects (a sink
        enrolled, a cutover published), so re-roll them back, then stop
        the driver."""
        self._rollback(job)
        with self._lock:
            job.recipient = None
        raise WukongError(
            ErrorCode.UNSUPPORTED_SHAPE,
            f"migration {job.plan.plan_id} aborted "
            f"({job.abort_cause or 'operator'}) — the state machine "
            "stops here")

    @staticmethod
    def _cause(e: BaseException) -> str:
        from wukong_tpu.runtime.faults import ShardDown, TransientFault

        if isinstance(e, (TransientFault, ShardDown)):
            return "injected_fault"
        if isinstance(e, WukongError):
            return e.code.name.lower()
        return type(e).__name__.lower()

    # ------------------------------------------------------------------
    def _phase_clone(self, job: MigrationJob) -> None:
        """Snapshot the donor under the mutation lock: exact at
        ``seq_clone``, writes pause only for the copy. The copy itself is
        a TRANSPORT transfer (runtime/transport.py ``snapshot``): loopback
        is the in-memory structural clone (byte-for-byte PR 12 behavior);
        the socket transport moves the shard through the checkpoint wire
        codec — from its worker process when one serves it."""
        from wukong_tpu.runtime import faults
        from wukong_tpu.store.dynamic import enroll_migration_sink

        ss = self._require_store()
        donor = job.plan.donor_shard
        ev = emit_event("shard.migrate.start", shard=donor,
                        plan=job.plan.plan_id,
                        recipient_host=job.plan.recipient_host,
                        predicted_bytes=job.plan.predicted_move_bytes,
                        attempt=job.attempts)
        if ev:
            job.event_ids.append(ev)
        faults.site("migration.clone", shard=donor)
        wal = active_wal()
        with mutation_lock():
            job.seq_clone = (wal.next_seq - 1) if wal is not None else -1
            job.donor_store = ss.stores[donor]
            job.donor_host = ss.host_of(donor)
            job.recipient = ss.transport.snapshot(donor, job.donor_store)
            if wal is None:
                # no WAL tail to catch up from: dual-write must start at
                # the snapshot instant, inside this same critical section
                enroll_migration_sink(_sink_key(donor), job.recipient)
        mb = getattr(job.recipient, "memory_bytes", None)
        job.bytes_moved = int(mb()) if callable(mb) else int(
            job.plan.predicted_move_bytes)
        _M_BYTES.inc(job.bytes_moved)

    def _phase_catchup(self, job: MigrationJob) -> None:
        """Replay the WAL tail ``(seq_clone, now]`` onto the recipient and
        enroll the dual-write sink, one mutation-locked section: every
        committed batch is either replayed here or dual-applied after —
        never both, never neither."""
        from wukong_tpu.runtime import faults
        from wukong_tpu.store.dynamic import (
            enroll_migration_sink,
            insert_triples,
        )

        donor = job.plan.donor_shard
        faults.site("migration.catchup", shard=donor)
        wal = active_wal()
        replayed = 0
        if wal is not None:
            with mutation_lock():
                job.dirty_catchup = True
                # suppression is safe here: the mutation lock excludes
                # live commits for the replay window, so only the replay
                # itself is suppressed (direct per-partition inserts fire
                # no WAL hook anyway — the _rebuild_shard contract)
                with wal.suppress():
                    from wukong_tpu.vector.vstore import apply_vector_record

                    for rec in wal.replay(after_seq=job.seq_clone):
                        if rec.kind == "vector":
                            # embedding mutations ride the same tail: the
                            # recipient's vstore must match the donor's at
                            # sink-enroll time or knn answers tear on cutover
                            apply_vector_record(job.recipient, rec.payload)
                        else:
                            insert_triples(
                                job.recipient, rec.payload["triples"],
                                dedup=bool(rec.payload.get("dedup", True)),
                                check_ids=False)
                        replayed += 1
                enroll_migration_sink(_sink_key(donor), job.recipient)
                job.dirty_catchup = False
        job.replayed = replayed
        ev = emit_event("shard.migrate.catchup", shard=donor,
                        plan=job.plan.plan_id, replayed=replayed,
                        since_seq=job.seq_clone)
        if ev:
            job.event_ids.append(ev)

    def _phase_cutover(self, job: MigrationJob) -> None:
        """Swap the read path to the recipient and retire the dual sink in
        one mutation-locked section; the measured pause is the only write
        stall the cutover costs."""
        from wukong_tpu.runtime import faults
        from wukong_tpu.store.dynamic import deroll_migration_sink

        ss = self._require_store()
        donor = job.plan.donor_shard
        faults.site("migration.cutover", shard=donor)
        rotate = bool(Global.migration_rotate_reads)
        t0 = get_usec()
        # the swap itself is guarded by: the store's _migration_lock
        # (taken inside cutover_shard); this frame additionally holds the
        # WAL mutation lock so no batch commit straddles the publication
        with mutation_lock():
            if ss.stores[donor] is not job.recipient:  # resume idempotence
                ss.cutover_shard(donor, job.recipient,
                                 job.plan.recipient_host, rotate=rotate)
            job.rotated = bool(ss.rotation.get(donor))
            deroll_migration_sink(_sink_key(donor))
            # long-lived bound fan-out lists (the stream ingestor's) must
            # learn the new primary inside the SAME critical section, or
            # the next epoch would insert into the retired donor
            self._rebind_targets()
            # the serving plane's actuator edge (wukong_tpu/serve/): the
            # read-path swap purges the real result cache inside the
            # same critical section — the clone is byte-identical, but a
            # rotation-split read after the publication must never race
            # a stale entry. One knob check when the cache is off.
            from wukong_tpu.serve import notify_mutation

            notify_mutation("cutover", shard=donor)
        job.cutover_pause_us = get_usec() - t0
        get_lineage().observe_store(ss)  # post-move lineage, immediately
        # cache-coherence telemetry (obs/reuse.py): a read-path swap is a
        # conservative full purge for a version-keyed result cache (the
        # shard's version counter travels with the byte-identical clone,
        # so a version-diff kill would see no edge — the swap itself is
        # the invalidation). Outside the mutation lock, after the pause
        # measurement: pure observability
        from wukong_tpu.obs.reuse import maybe_note_invalidation

        maybe_note_invalidation("cutover", version=None, shard=donor,
                                plan=job.plan.plan_id)
        ev = emit_event("shard.migrate.cutover", shard=donor,
                        plan=job.plan.plan_id,
                        recipient_host=job.plan.recipient_host,
                        pause_us=job.cutover_pause_us,
                        rotated=job.rotated)
        if ev:
            job.event_ids.append(ev)
        own = self._owner()
        if own is not None and hasattr(own, "_on_store_change"):
            own._on_store_change()  # plan caches / compiled chains re-derive

    def _phase_retire(self, job: MigrationJob) -> None:
        """Release the donor copy (unless demoted to a rotation replica at
        cutover) and re-arm the shard's breaker."""
        ss = self._require_store()
        donor = job.plan.donor_shard
        if not job.rotated:
            job.donor_store = None  # the last strong ref: the copy dies
        ss.breaker.record_success(donor)  # migrations end with a closed breaker
        ev = emit_event("shard.migrate.retire", shard=donor,
                        plan=job.plan.plan_id, rotated=job.rotated,
                        bytes=job.bytes_moved)
        if ev:
            job.event_ids.append(ev)

    def _rebind_targets(self) -> None:  # caller holds: wal.mutation_lock
        own = self._owner()
        if own is None:
            return
        stream = getattr(own, "_stream", None)
        if stream is not None and hasattr(own, "_insert_targets"):
            stream.ingestor.stores = own._insert_targets()

    def _drop_copy(self, job: MigrationJob) -> None:
        """Discard the in-flight recipient copy + its dual sink (rollback
        or re-snapshot); the donor is untouched by construction."""
        from wukong_tpu.store.dynamic import deroll_migration_sink

        with mutation_lock():
            deroll_migration_sink(_sink_key(job.plan.donor_shard))
        job.recipient = None

    def _rollback(self, job: MigrationJob) -> bool:
        """Deroll the dual sink and, when a cutover already published the
        recipient, swap the read path back to the donor. Idempotent (also
        re-run after a phase raced a concurrent abort). Returns whether a
        published cutover was swapped back."""
        from wukong_tpu.store.dynamic import deroll_migration_sink

        ss = self._store()
        donor = job.plan.donor_shard
        swapped = False
        with mutation_lock():
            deroll_migration_sink(_sink_key(donor))
            if (ss is not None and job.recipient is not None
                    and ss.stores[donor] is job.recipient
                    and job.donor_store is not None):
                # cutover already published: swap the read path back. A
                # retire that already RELEASED the donor leaves nothing
                # to swap back to — the recipient stays primary (the
                # migration is committed in all but name)
                ss.rollback_cutover(donor, job.donor_store, job.donor_host)
                swapped = True
                self._rebind_targets()
                # the swap-back is a read-path publication like the
                # cutover itself: the real result cache purges inside
                # the same critical section (serve plane actuator edge)
                from wukong_tpu.serve import notify_mutation

                notify_mutation("cutover", shard=donor)
        return swapped

    # ------------------------------------------------------------------
    def abort(self, cause: str = "operator") -> MigrationJob | None:
        """Roll the in-flight migration back to the donor: dual sink
        derolled, a completed cutover swapped back, recipient discarded.
        The donor's content digest is untouched — the migration only ever
        read it. Safe against a concurrently running driver thread: the
        state machine re-checks for the abort at every phase boundary and
        re-rolls-back anything a racing phase published. Returns the
        aborted job, or None when nothing is in flight."""
        with self._lock:
            job = self._job
            if job is None or job.phase in ("done", "aborted"):
                return None
            at_phase = job.phase
            job.phase = "aborted"  # published FIRST: the driver stops here
            job.abort_cause = str(cause)
        swapped = self._rollback(job)
        donor = job.plan.donor_shard
        with self._lock:
            job.recipient = None
            self._history.append(job)
        ev = emit_event("shard.migrate.abort", shard=donor,
                        plan=job.plan.plan_id, cause=str(cause),
                        at_phase=at_phase, swapped_back=swapped)
        if ev:
            job.event_ids.append(ev)
        _M_ABORTS.labels(cause=str(cause)).inc()
        _M_MIGRATIONS.labels(outcome="aborted").inc()
        own = self._owner()
        if swapped and own is not None and hasattr(own, "_on_store_change"):
            own._on_store_change()
        log_warn(f"migration {job.plan.plan_id} aborted at {at_phase} "
                 f"({cause}); donor shard {donor} untouched"
                 + (" (cutover rolled back)" if swapped else ""))
        return job

    # ------------------------------------------------------------------
    def status(self) -> dict:
        """The actuator's surface body (/plan, /healthz probe, Monitor)."""
        with self._lock:
            job = self._job
            last = self._history[-1] if self._history else None
        in_flight = job is not None and job.phase not in ("done", "aborted")
        return {"enabled": bool(Global.migration_enable),
                "in_flight": in_flight,
                "job": job.to_dict() if job is not None else None,
                "last": last.to_dict() if last is not None else None}

    def job(self) -> MigrationJob | None:
        with self._lock:
            return self._job

    def reset(self) -> None:
        """Tests: stop the loop, drop job/history/attachments, deroll any
        leaked dual sink."""
        self.stop()
        with self._lock:
            job = self._job
        if job is not None and job.phase not in ("done", "aborted"):
            self.abort(cause="reset")
        with self._lock:
            self._job = None
            self._history.clear()
        self._sstore_ref = None
        self._owner_ref = None

    # ------------------------------------------------------------------
    # the actuator loop (the advisory loop, armed)
    # ------------------------------------------------------------------
    def start(self) -> "MigrationExecutor":
        """Launch the background actuator loop: every
        ``placement_interval_s`` seconds, sweep the advisor and execute
        the plan it emits. Idempotent; the thread is a daemon."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run_loop, daemon=True,
                                        name="migration-actuator")
        self._thread.start()
        return self

    def _run_loop(self) -> None:
        me = threading.current_thread()
        while not self._stop.wait(max(float(Global.placement_interval_s
                                            or 1), 1.0)):
            if self._thread is not me:
                return  # superseded: an execute overran stop()'s join
            if (not Global.migration_enable
                    or Global.placement_interval_s <= 0):
                continue  # knobs flipped off at runtime: idle
            try:
                with self._lock:
                    busy = (self._job is not None
                            and self._job.phase not in ("done", "aborted"))
                if busy or self._store() is None:
                    continue
                plan = get_advisor().advise_once()
                if plan is not None:
                    self.run_plan(plan)
            except Exception as e:  # the actuator must never die silently
                log_warn(f"migration actuator sweep failed: {e!r}")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        # clear BEFORE the fresh Event below (the advisor's straggler-safe
        # stop pattern): _run_loop self-retires once superseded
        self._thread = None
        if t is not None:
            t.join(timeout=2)
        self._stop = threading.Event()


# process-wide actuator (console verb, /plan, Monitor, healthz share it)
_migrator = MigrationExecutor()


def get_migrator() -> MigrationExecutor:
    return _migrator


def maybe_start_migration(sstore=None, owner=None
                          ) -> "MigrationExecutor | None":
    """Attach the sharded store/owner and start the actuator loop when
    ``migration_enable`` + ``placement_interval_s`` ask for one. The
    attach happens either way so the ``migrate`` verb works on demand.
    Returns the executor when its loop runs (the caller then skips the
    observe-only advisor loop — one sweeper, not two), else None."""
    _migrator.attach(sstore=sstore, owner=owner)
    if sstore is not None:
        get_advisor().attach_store(sstore)
    if not Global.migration_enable or Global.placement_interval_s <= 0:
        return None
    if _migrator._store() is None:
        return None
    # the actuator loop sweeps the advisor itself: the observe-only loop
    # would double every decision counter if both ran
    get_advisor().stop()
    return _migrator.start()


def _phase_gauge() -> float:
    """Pull gauge: the in-flight phase as an index into MIGRATION_PHASES
    (1-based; 0 = idle/done/aborted)."""
    job = _migrator.job()
    if job is None or job.phase not in MIGRATION_PHASES:
        return 0.0
    return float(MIGRATION_PHASES.index(job.phase) + 1)


get_registry().gauge(
    "wukong_migration_phase",
    "In-flight migration phase (1=clone..4=retire, 0=idle)"
).set_function(_phase_gauge)


def _health_probe():
    """/healthz readiness source: a shard mid-migration serves (live),
    but the process reports degraded-not-dead until retire."""
    st = _migrator.status()
    if not st["in_flight"]:
        return None
    j = st["job"]
    return {"shard": j["donor_shard"], "phase": j["phase"],
            "recipient_host": j["recipient_host"]}


from wukong_tpu.obs.httpd import register_health_source  # noqa: E402

register_health_source("migration", _health_probe)
