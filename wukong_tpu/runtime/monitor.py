"""Latency/throughput monitor (reference: core/monitor.hpp:36-233).

Per-query latency records keyed by query id, rolling throughput reporting, and
per-query-type latency vectors aggregated into a CDF — the same measurements the
reference's proxy prints during `sparql -n N` and `sparql-emu` runs.

Beyond the reference: streaming metrics (per-epoch ingest/eval latency and
commit-to-results lag, fed by stream/ingest.py) and per-shard circuit-breaker
state (attached CircuitBreakers from the resilience layer), both folded into
the rolling throughput report.

Observability (PR 3): latencies ALSO publish into the process-wide
MetricsRegistry (``wukong_query_latency_us`` histogram) and attached
breakers export a pull gauge (``wukong_breaker_open``) — the Monitor's
private vectors keep feeding the CDF prints, the registry feeds the
Prometheus/JSON exporters.

Heat telemetry (PR 7): the Monitor also aggregates the sharded store's
per-shard heat charges (obs/heat.py) into per-shard load CDFs and a top-K
hot-shard report — ``heat_report()`` / ``shard_load_cdfs()`` are the
placement inputs ROADMAP item 3's migration planner consumes, and the
rolling throughput report prints the hot-shard line.
"""

from __future__ import annotations

from collections import defaultdict, deque

import numpy as np

from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.utils.logger import log_info
from wukong_tpu.utils.timer import get_usec

_M_LATENCY = get_registry().histogram(
    "wukong_query_latency_us", "Per-query latency by class (usec)",
    labels=("qtype",))

# every live Monitor with attached breakers feeds ONE registry-level pull
# gauge (weakly referenced: dropped monitors vanish from the export instead
# of lingering as stale series or being pinned in memory). With several
# monitors exporting the same breaker name, the last-iterated value wins —
# they share the breaker object via share_observability, so values agree.
import weakref  # noqa: E402

_BREAKER_MONITORS: "weakref.WeakSet" = weakref.WeakSet()


def _breaker_open_series() -> dict:
    out: dict = {}
    for m in list(_BREAKER_MONITORS):
        for nm, br in m._breakers.items():
            out[(nm,)] = sum(1 for st in br.snapshot().values()
                             if st["state"] != "closed")
    return out


get_registry().gauge(
    "wukong_breaker_open", "Breaker keys not in the closed state",
    labels=("name",)).set_function(_breaker_open_series)

# per-epoch latency samples kept for the stream CDF (bounds memory on
# long-running ingest loops; the totals keep counting past it)
STREAM_WINDOW = 4096


def _cdf(vals, points=(0.5, 0.9, 0.95, 0.99, 1.0)) -> dict[float, float]:
    """Percentile dict over a sample list/deque (monitor.hpp print_cdf
    indexing — shared by the query and stream CDFs)."""
    if not vals:
        return {}
    arr = np.sort(np.asarray(vals, dtype=np.float64))
    return {p: float(arr[min(int(p * len(arr)), len(arr) - 1)])
            for p in points}


class StreamStats:
    """Streaming counters + latency windows, shareable between monitors
    (the emulator's per-run Monitor adopts the proxy monitor's instance so
    its rolling report sees epochs committed on the proxy side)."""

    __slots__ = ("epochs", "triples", "lag_us", "eval_us", "ingest_us")

    def __init__(self):
        self.epochs = 0
        self.triples = 0
        self.lag_us: deque = deque(maxlen=STREAM_WINDOW)
        self.eval_us: deque = deque(maxlen=STREAM_WINDOW)
        self.ingest_us: deque = deque(maxlen=STREAM_WINDOW)


class Monitor:
    def __init__(self):
        self._start: dict[int, int] = {}
        self.latencies: dict[int, list[int]] = defaultdict(list)  # type -> usecs
        self.cnt = 0
        self._t0 = None
        self._last_print = None
        self._last_cnt = 0
        # -- streaming (stream/ingest.py feeds record_stream_epoch) --------
        self.stream = StreamStats()
        self._last_stream_epochs = 0
        self._last_stream_triples = 0
        # -- circuit breakers (name -> CircuitBreaker) ---------------------
        self._breakers: dict[str, object] = {}

    def share_observability(self, other: "Monitor") -> None:
        """Adopt ``other``'s stream stats and breaker registry by reference,
        keeping per-query counters (and the rolling-print cursor) private.
        The emulator's per-run Monitor does this against the proxy monitor
        so breaker/stream lines reach the only rolling-report printer."""
        self.stream = other.stream
        self._breakers = other._breakers
        # start the print cursors at the adopted totals — epochs committed
        # before this monitor existed must not read as rate in its first
        # report window
        self._last_stream_epochs = other.stream.epochs
        self._last_stream_triples = other.stream.triples

    # -- per-query records (monitor.hpp start_record/end_record) ----------
    def start_record(self, qid: int, qtype: int = 0) -> None:
        self._start[qid] = get_usec()

    def end_record(self, qid: int, qtype: int = 0) -> None:
        t = get_usec()
        if qid in self._start:
            dt = t - self._start.pop(qid)
            self.latencies[qtype].append(dt)
            self.cnt += 1
            _M_LATENCY.labels(qtype=qtype).observe(dt)

    def add_latency(self, usec: float, qtype: int = 0, count: int = 1) -> None:
        """Record an aggregate measurement (batched execution)."""
        self.latencies[qtype].extend([usec] * count)
        self.cnt += count
        _M_LATENCY.labels(qtype=qtype).observe(usec, count=count)

    # -- open-loop throughput (monitor.hpp timely print) -------------------
    def start_thpt(self) -> None:
        self._t0 = self._last_print = get_usec()
        self._last_cnt = self.cnt = 0
        self.latencies.clear()

    def maybe_print_thpt(self, interval_usec: int = 500_000) -> None:
        now = get_usec()
        if self._last_print is not None and now - self._last_print > interval_usec:
            d = now - self._last_print
            log_info(f"Throughput: {(self.cnt - self._last_cnt) / (d / 1e6):,.0f} q/s")
            if self.stream.epochs > self._last_stream_epochs:
                de = self.stream.epochs - self._last_stream_epochs
                dt = self.stream.triples - self._last_stream_triples
                lag = self.stream_lag_cdf()
                lag_str = (f", lag p50={lag[0.5]:,.0f}us "
                           f"p99={lag[0.99]:,.0f}us" if lag else "")
                log_info(f"Stream: {de / (d / 1e6):,.1f} epochs/s, "
                         f"{dt / (d / 1e6):,.0f} triples/s{lag_str}")
            self._last_stream_epochs = self.stream.epochs
            self._last_stream_triples = self.stream.triples
            for line in self.breaker_report():
                log_info(line)
            for line in self.heat_lines(k=3):
                log_info(line)
            for line in self.lane_lines():
                log_info(line)
            for line in self.slo_lines(k=3):
                log_info(line)
            for line in self.admission_lines(k=3):
                log_info(line)
            for line in self.events_lines(k=4):
                log_info(line)
            for line in self.placement_lines():
                log_info(line)
            for line in self.migration_lines():
                log_info(line)
            for line in self.cache_lines():
                log_info(line)
            for line in self.device_lines():
                log_info(line)
            self._last_print = now
            self._last_cnt = self.cnt

    def thpt(self) -> float:
        if self._t0 is None:
            return 0.0
        dt = get_usec() - self._t0
        return self.cnt / (dt / 1e6) if dt else 0.0

    # -- streaming metrics (no reference analogue; Wukong+S-style lag) -----
    def record_stream_epoch(self, n_triples: int, ingest_us: int,
                            eval_us: int, lag_us: int) -> None:
        """One committed epoch: batch size, insert time, standing-query
        evaluation time, and commit-to-results lag."""
        self.stream.epochs += 1
        self.stream.triples += int(n_triples)
        self.stream.ingest_us.append(int(ingest_us))
        self.stream.eval_us.append(int(eval_us))
        self.stream.lag_us.append(int(lag_us))

    def stream_lag_cdf(self, points=(0.5, 0.9, 0.95, 0.99, 1.0)):
        return _cdf(self.stream.lag_us, points)

    def stream_stats(self) -> dict:
        """Aggregate streaming view (bench_stream.py's artifact source)."""
        return {
            "epochs": self.stream.epochs,
            "triples": self.stream.triples,
            "ingest_us_cdf": _cdf(self.stream.ingest_us),
            "eval_us_cdf": _cdf(self.stream.eval_us),
            "lag_us_cdf": self.stream_lag_cdf(),
        }

    # -- circuit breakers (resilience satellite: PR 1 follow-up) -----------
    def attach_breaker(self, name: str, breaker) -> None:
        """Register a CircuitBreaker for state surfacing (e.g. the sharded
        store's per-shard breaker). Idempotent by name. Also exports a
        pull gauge into the metrics registry: keys not in the closed state,
        read from the breaker snapshot at export time."""
        self._breakers[name] = breaker
        _BREAKER_MONITORS.add(self)  # feeds the wukong_breaker_open gauge

    def breaker_summary(self) -> dict[str, dict]:
        """name -> {counts by state, last_trip_age_s (most recent across
        keys, None = never)}."""
        out = {}
        for name, br in self._breakers.items():
            snap = br.snapshot()
            counts = {"closed": 0, "open": 0, "half_open": 0}
            last_trip = None
            for st in snap.values():
                counts[st["state"]] += 1
                age = st["last_trip_age_s"]
                if age is not None and (last_trip is None or age < last_trip):
                    last_trip = age
            out[name] = {**counts, "last_trip_age_s": last_trip}
        return out

    def breaker_report(self) -> list[str]:
        """Rolling-report lines — only breakers with any tracked key, and
        trip info only when something actually tripped."""
        lines = []
        for name, s in self.breaker_summary().items():
            total = s["closed"] + s["open"] + s["half_open"]
            if total == 0:
                continue
            line = (f"Breaker[{name}]: {s['closed']} closed, "
                    f"{s['open']} open, {s['half_open']} half-open")
            if s["last_trip_age_s"] is not None:
                line += f" (last trip {s['last_trip_age_s']:.1f}s ago)"
            lines.append(line)
        return lines

    # -- per-shard heat (obs/heat.py; PR 7 telemetry plane) ----------------
    def heat_report(self, k: int | None = None) -> dict:
        """The aggregated per-shard heat view: load CDFs, latency CDFs,
        and the top-K hot-shard ranking — the placement inputs ROADMAP
        item 3's migration planner consumes. Aggregation lives on the
        process-wide accountant (every sharded store charges into it);
        the Monitor is its reporting surface."""
        from wukong_tpu.obs.heat import get_heat

        return get_heat().report(k)

    def shard_load_cdfs(self) -> dict[int, dict]:
        """shard -> load-rate CDF (instantaneous fetches/s percentiles)."""
        rep = self.heat_report(k=None)
        return {s: d["load_rate_cdf"] for s, d in rep["shards"].items()}

    def lane_lines(self) -> list[str]:
        """Rolling-report line for the heavy lane: queue depth, fused
        dispatches, and mean group occupancy — only once the lane has seen
        traffic (quiet on light-only runs)."""
        from wukong_tpu.obs.metrics import (
            snapshot_histogram_mean,
            snapshot_labeled_value,
        )

        snap = get_registry().snapshot()
        heavy_sub = int(snapshot_labeled_value(
            snap, "wukong_pool_submitted_total", lane="heavy"))
        disp = sum(int(s.get("value", 0)) for s in (
            snap.get("wukong_batch_heavy_dispatch_total") or {}).get(
            "series", []))
        if not heavy_sub and not disp:
            return []
        depth = int(snapshot_labeled_value(
            snap, "wukong_pool_lane_depth", lane="heavy"))
        mean = snapshot_histogram_mean(
            snap, "wukong_batch_heavy_occupancy") or 0.0
        return [f"HeavyLane: depth {depth}, {disp} fused dispatches "
                f"({heavy_sub} lane submits), mean group {mean:.1f}"]

    def slo_lines(self, k: int = 3) -> list[str]:
        """Rolling-report lines for the tenant SLO plane (obs/slo.py):
        the k worst-burning spec'd tenants' compliance / remaining error
        budget / burn rates — quiet when no tenant replies were observed
        (single-tenant runs stay clean)."""
        from wukong_tpu.obs.slo import get_slo

        rows = [r for r in get_slo().report()["tenants"]
                if r["spec"] is not None]
        if not rows:
            return []
        parts = []
        for r in rows[:k]:
            burn = r.get("burn") or {}
            parts.append(
                f"{r['tenant']}: compl "
                + ("-" if r["compliance"] is None
                   else f"{r['compliance']:.1%}")
                + f" budget {r.get('error_budget_remaining', 0):.0%}"
                + f" burn {burn.get('fast', 0):.1f}/{burn.get('slow', 0):.1f}"
                + (f" alerts {r['alerts']}" if r["alerts"] else ""))
        return ["SLO[" + "  ".join(parts) + "]"]

    def admission_lines(self, k: int = 3) -> list[str]:
        """Rolling-report line for the admission control plane
        (runtime/admission.py): overload level + the k busiest tenants'
        non-admit decision counts — quiet while the plane is off or has
        decided nothing (off-knob runs print nothing)."""
        from wukong_tpu.config import Global

        if not Global.enable_admission:
            return []
        from wukong_tpu.runtime.admission import get_admission

        adm = get_admission()
        rep = adm.report()
        decisions = rep["decisions"]
        if not decisions:
            return []
        shed = {kt: n for kt, n in decisions.items()
                if not kt.startswith("admit/")}
        top = sorted(shed.items(), key=lambda kv: -kv[1])[:k]
        parts = [f"{kt}:{n}" for kt, n in top]
        total = sum(decisions.values())
        return ["Admission[level " + str(rep["level"])
                + f" {total:,} decisions"
                + ("  " + "  ".join(parts) if parts else "") + "]"]

    def events_lines(self, k: int = 4) -> list[str]:
        """Rolling-report line for the cluster event journal
        (obs/events.py): total journaled events + the k most frequent
        kinds and the newest event — quiet while nothing happened."""
        from wukong_tpu.obs.events import get_journal

        j = get_journal()
        counts = j.counts()
        if not counts:
            return []
        top = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
        newest = j.last(1)
        tail = ""
        if newest:
            e = newest[0]
            tail = (f"; last {e.event_id} {e.kind}"
                    + (f" shard={e.shard}" if e.shard is not None else ""))
        return ["Events[" + "  ".join(f"{kd}:{n}" for kd, n in top)
                + f"] ({sum(counts.values())} total{tail})"]

    def placement_lines(self) -> list[str]:
        """Rolling-report line for the observe-only placement advisor
        (obs/placement.py): the last MigrationPlan, or nothing while no
        plan has been emitted (balanced clusters stay quiet)."""
        from wukong_tpu.obs.placement import get_advisor

        st = get_advisor().status()
        p = st["plan"]
        if p is None:
            return []
        return [f"Placement[plan {p['plan_id']}: donor shard "
                f"{p['donor_shard']} -> host {p['recipient_host']}, "
                f"{p['predicted_move_bytes'] / 2**20:.1f} MiB "
                f"({p['bytes_source']}), imbalance "
                f"{p['imbalance_before']:.2f} -> "
                f"{p['imbalance_after']:.2f}]"]

    def migration_lines(self) -> list[str]:
        """Rolling-report line for the shard-migration actuator
        (runtime/migration.py): the in-flight migration's phase and
        progress — quiet while nothing is moving."""
        from wukong_tpu.runtime.migration import get_migrator

        st = get_migrator().status()
        if not st["in_flight"]:
            return []
        j = st["job"]
        return [f"Migration[{j['plan_id']}: shard {j['donor_shard']} -> "
                f"host {j['recipient_host']}, {j['phase']}, "
                f"{j['bytes_moved'] / 2**20:.1f} MiB moved, "
                f"{j['replayed']} WAL records caught up]"]

    def cache_lines(self) -> list[str]:
        """Rolling-report lines for the serving cache: the REAL result
        cache + view registry (wukong_tpu/serve/) when the actuator is
        on and probed, then the observatory's shadow line (obs/reuse.py)
        — quiet until any reply has been observed (reuse off or no
        serving traffic)."""
        from wukong_tpu.config import Global
        from wukong_tpu.obs.reuse import get_reuse

        lines = []
        if Global.enable_result_cache:
            from wukong_tpu.serve import get_serve
            from wukong_tpu.serve.result_cache import divergence_total

            rc = get_serve().cache.stats()
            if rc["hits"] + rc["misses"]:
                hr = rc["hit_rate"]
                lines.append(
                    "Cache[real "
                    + ("-" if hr is None else f"{hr:.1%}")
                    + f" over {rc['hits'] + rc['misses']:,} probes, "
                    f"{rc['entries']} entries, "
                    f"{rc['bytes_held'] / 2**20:.1f} MiB held, "
                    f"{get_serve().views.count()} views, "
                    f"{rc['collapsed']:,} collapsed, "
                    f"diverged {divergence_total():,}]")
        obs = get_reuse()
        sh = obs.shadow.stats()
        if sh["hits"] + sh["misses"] == 0:
            return lines
        pop = obs.ledger.report(k=1)
        hot = ""
        if pop["ranked"]:
            r = pop["ranked"][0]
            hot = (f", top {r['template']} {r['share']:.0%} "
                   f"@{r['rate_qps']:,.0f}q/s")
        hr = sh["hit_rate"]
        lines.append(f"Cache[shadow "
                     + ("-" if hr is None else f"{hr:.1%}")
                     + f" over {sh['hits'] + sh['misses']:,} probes, "
                     f"{sh['keys']} keys, {sh['killed']:,} killed, "
                     f"saved {sh['bytes_saved'] / 2**20:.1f} MiB"
                     f"{hot}]")
        return lines

    def device_lines(self) -> list[str]:
        """Rolling-report line for the device observatory: dispatch count
        + cold/warm split + padding efficiency + resident bytes vs the
        budget — quiet until any dispatch or residency fill has been
        charged (host-only runs stay silent)."""
        from wukong_tpu.obs.device import get_device_obs

        obs = get_device_obs()
        d = obs.dispatch_ledger.dispatch_counts()
        res = obs.residency.stats()
        if d["count"] == 0 and res["total_bytes"] == 0:
            return []
        eff = obs.dispatch_ledger.padding_efficiency()
        return [f"Device[{d['count']:,} dispatches "
                f"({d['cold']:,} cold / {d['warm']:,} warm), pad_eff "
                + ("-" if eff is None else f"{eff:.1%}")
                + f", resident {res['total_bytes'] / 2**20:.1f}"
                f"/{res['budget_bytes'] / 2**20:.0f} MiB"
                f" (hw {res['high_water_bytes'] / 2**20:.1f})"
                + (", OVER BUDGET" if res["over_budget"] else "") + "]"]

    def heat_lines(self, k: int = 3) -> list[str]:
        """Rolling-report lines: the top-k hot shards, only when any fetch
        has been charged (quiet on single-host runs)."""
        rep = self.heat_report(k)
        if not rep["ranked"]:
            return []
        parts = []
        for r in rep["ranked"]:
            parts.append(f"{r['shard']}:{r['fetches']} ({r['share']:.0%}"
                         f", ewma {r['ewma_us']:,.0f}us)")
        return [f"Heat[top{k}]: " + "  ".join(parts)]

    # -- CDF (monitor.hpp print_cdf) ---------------------------------------
    def cdf(self, qtype: int | None = None,
            points=(0.5, 0.9, 0.95, 0.99, 1.0)) -> dict[float, float]:
        vals: list = []
        if qtype is None:
            for v in self.latencies.values():
                vals.extend(v)
        else:
            vals = list(self.latencies.get(qtype, []))
        return _cdf(vals, points)

    def print_cdf(self, labels: dict[int, str] | None = None) -> None:
        """Per-class latency CDF. `labels` marks how a class was measured —
        device-batch classes report batch_time/B, a different quantity from
        a pool round-trip, and must not read as the same thing."""
        for qtype in sorted(self.latencies):
            c = self.cdf(qtype)
            line = "  ".join(f"p{int(p * 100)}={v:,.0f}us" for p, v in c.items())
            tag = f" [{labels[qtype]}]" if labels and qtype in labels else ""
            log_info(f"Q{qtype + 1}{tag} latency CDF "
                     f"({len(self.latencies[qtype])} samples): {line}")
