"""Latency/throughput monitor (reference: core/monitor.hpp:36-233).

Per-query latency records keyed by query id, rolling throughput reporting, and
per-query-type latency vectors aggregated into a CDF — the same measurements the
reference's proxy prints during `sparql -n N` and `sparql-emu` runs.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from wukong_tpu.utils.logger import log_info
from wukong_tpu.utils.timer import get_usec


class Monitor:
    def __init__(self):
        self._start: dict[int, int] = {}
        self.latencies: dict[int, list[int]] = defaultdict(list)  # type -> usecs
        self.cnt = 0
        self._t0 = None
        self._last_print = None
        self._last_cnt = 0

    # -- per-query records (monitor.hpp start_record/end_record) ----------
    def start_record(self, qid: int, qtype: int = 0) -> None:
        self._start[qid] = get_usec()

    def end_record(self, qid: int, qtype: int = 0) -> None:
        t = get_usec()
        if qid in self._start:
            self.latencies[qtype].append(t - self._start.pop(qid))
            self.cnt += 1

    def add_latency(self, usec: float, qtype: int = 0, count: int = 1) -> None:
        """Record an aggregate measurement (batched execution)."""
        self.latencies[qtype].extend([usec] * count)
        self.cnt += count

    # -- open-loop throughput (monitor.hpp timely print) -------------------
    def start_thpt(self) -> None:
        self._t0 = self._last_print = get_usec()
        self._last_cnt = self.cnt = 0
        self.latencies.clear()

    def maybe_print_thpt(self, interval_usec: int = 500_000) -> None:
        now = get_usec()
        if self._last_print is not None and now - self._last_print > interval_usec:
            d = now - self._last_print
            log_info(f"Throughput: {(self.cnt - self._last_cnt) / (d / 1e6):,.0f} q/s")
            self._last_print = now
            self._last_cnt = self.cnt

    def thpt(self) -> float:
        if self._t0 is None:
            return 0.0
        dt = get_usec() - self._t0
        return self.cnt / (dt / 1e6) if dt else 0.0

    # -- CDF (monitor.hpp print_cdf) ---------------------------------------
    def cdf(self, qtype: int | None = None,
            points=(0.5, 0.9, 0.95, 0.99, 1.0)) -> dict[float, float]:
        vals: list = []
        if qtype is None:
            for v in self.latencies.values():
                vals.extend(v)
        else:
            vals = list(self.latencies.get(qtype, []))
        if not vals:
            return {}
        arr = np.sort(np.asarray(vals, dtype=np.float64))
        return {p: float(arr[min(int(p * len(arr)), len(arr) - 1)]) for p in points}

    def print_cdf(self, labels: dict[int, str] | None = None) -> None:
        """Per-class latency CDF. `labels` marks how a class was measured —
        device-batch classes report batch_time/B, a different quantity from
        a pool round-trip, and must not read as the same thing."""
        for qtype in sorted(self.latencies):
            c = self.cdf(qtype)
            line = "  ".join(f"p{int(p * 100)}={v:,.0f}us" for p, v in c.items())
            tag = f" [{labels[qtype]}]" if labels and qtype in labels else ""
            log_info(f"Q{qtype + 1}{tag} latency CDF "
                     f"({len(self.latencies[qtype])} samples): {line}")
