"""Process supervision: per-shard-group worker pools behind the proxy.

The reference runs one process per server under MPI with one-sided RDMA
reads (PAPER.md §L2/L3); until PR 20 every "distributed" guarantee here
was really a threading guarantee inside one interpreter. This module puts
real process boundaries under the transport seam (runtime/transport.py):

- **Spawn.** :class:`ProcSupervisor` splits the sharded store's D
  partitions into ``proc_workers`` contiguous groups and spawns one
  worker process per group (``multiprocessing`` *spawn* context — no
  forked JAX runtime state; workers are numpy-only by construction and
  report whether jax leaked into them). A worker boots exactly like a
  crashed server recovering: it loads its partitions from the NEWEST
  checkpoint bundle and replays the WAL tail through the normal PR 5
  mutation paths (``insert_triples`` / ``apply_vector_record``) before
  serving a byte, then proves itself with a per-shard content digest the
  parent checks against its own stores.
- **Serve.** Each worker listens on a loopback TCP socket and answers the
  framed transport ops (segment/versatile/index fetches, digest probes,
  WAL-tail syncs, migration snapshots). The parent's SocketTransport gets
  one peer registration per shard; shards whose worker is down (or whose
  digest did not match) stay parent-served.
- **Supervise.** A heartbeat thread pings every group at
  ``proc_heartbeat_ms``; ``proc_heartbeat_misses`` consecutive misses
  declare the worker dead (counted in
  ``wukong_proc_heartbeat_misses_total``) and trigger a restart with
  capped-exponential backoff (``proc_restart_backoff_ms`` doubling up to
  ``proc_restart_backoff_max_ms``), counted in
  ``wukong_proc_restarts_total`` and journaled as ``proc.restart``. While
  the worker is down its shards' fetches flow through the existing
  resilience ladder: peers deregister → retries → breaker → replica
  failover (``wukong_failover_total``) — results stay ``complete=True``
  and byte-identical while any replica lives, which is exactly what the
  kill-a-process drill (runtime/emulator.py ``run_proc_drill``) asserts.

The WAL is the mutation transport: workers share the parent's WAL
*directory* read-only (store/wal.py ``replay_dir`` — they must never
construct a ``WriteAheadLog`` on it, whose constructor repairs torn tails
in place) and catch up via the ``sync`` op, which heartbeats piggyback.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import signal
import socket
import sys
import threading
import time

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.utils.logger import log_info, log_warn

# supervisor group-state lock: guards the group table and per-group
# restart bookkeeping (plain dict/int writes); innermost by construction —
# spawning, transport calls, and events all happen OUTSIDE it
declare_leaf("procs.state")
# worker-side serve-state lock: guards applied_seq during WAL syncs
declare_leaf("procs.worker.state")

#: knobs a spawn-context worker inherits from the parent (spawn starts a
#: fresh interpreter, so Global resets to defaults there)
_INHERITED_KNOBS = ("transport_max_frame_mb", "wal_dir")


# ---------------------------------------------------------------------------
# worker side (runs in the child process — keep this numpy-only: no jax,
# no engine/parallel imports beyond device_store's numpy helpers)
# ---------------------------------------------------------------------------

def _newest_bundle(ckpt_dir: str):
    """(path, manifest) of the newest valid checkpoint bundle, or None.
    Mirrors RecoveryManager._checkpoints without importing the recovery
    manager (that would drag proxy-side modules into the worker)."""
    try:
        names = sorted((n for n in os.listdir(ckpt_dir)
                        if n.startswith("ckpt-")), reverse=True)
    except FileNotFoundError:
        return None
    for name in names:
        path = os.path.join(ckpt_dir, name)
        mpath = os.path.join(path, "MANIFEST.json")
        try:
            with open(mpath, encoding="utf-8") as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        return path, manifest
    return None


class _WorkerState:
    """One worker process's serving state: its owned partitions and the
    WAL replay high-water mark."""

    def __init__(self, stores: dict, applied_seq: int, wal_dir: str):
        self.stores = stores  # sid -> GStore (owned partitions)
        self.applied_seq = applied_seq
        self.wal_dir = wal_dir
        self.lock = make_lock("procs.worker.state")

    def sync(self, upto_seq: int) -> int:
        """Replay the parent WAL tail (read-only) through the normal
        mutation paths; returns the new high-water mark. Cheap no-op when
        the parent has committed nothing new."""
        from wukong_tpu.store.dynamic import insert_triples
        from wukong_tpu.store.wal import replay_dir
        from wukong_tpu.vector.vstore import apply_vector_record

        with self.lock:
            if not self.wal_dir or upto_seq <= self.applied_seq:
                return self.applied_seq
            for rec in replay_dir(self.wal_dir,
                                  after_seq=self.applied_seq):
                if rec.kind == "vector":
                    for g in self.stores.values():
                        apply_vector_record(g, rec.payload)
                else:
                    # plain insert — or an epoch without stream context
                    # (recovery.py's no-stream branch): the data must not
                    # be lost; insert_triples filters to each partition
                    for g in self.stores.values():
                        insert_triples(g, rec.payload["triples"],
                                       dedup=rec.payload.get("dedup", True),
                                       check_ids=False)
                self.applied_seq = rec.seq
            return self.applied_seq


def _serve_connection(sock, state: _WorkerState) -> None:
    from wukong_tpu.runtime.transport import (
        FrameDecoder,
        encode_frame,
        pack_error,
        pack_reply,
        run_op,
        unpack_message,
    )
    from wukong_tpu.utils.errors import ErrorCode, WukongError

    dec = FrameDecoder()
    try:
        while True:
            chunk = sock.recv(1 << 20)
            if not chunk:
                return
            for payload in dec.feed(chunk):
                try:
                    op, sid, args = unpack_message(payload)
                    if op == "sync":
                        result = state.sync(args[0])
                    elif op == "ping":
                        # piggyback the parent's committed seq: a worker
                        # answering a heartbeat is also caught up
                        state.sync(args[0])
                        g = state.stores.get(sid)
                        if g is None:
                            g = state.stores[min(state.stores)]
                        result = run_op(op, g, *args)
                    else:
                        g = state.stores.get(sid)
                        if g is None:
                            raise WukongError(
                                ErrorCode.SHARD_UNAVAILABLE,
                                f"worker does not own shard {sid}")
                        result = run_op(op, g, *args)
                    reply = encode_frame(pack_reply(result))
                except WukongError as e:
                    reply = encode_frame(pack_error(int(e.code), e.detail))
                except Exception as e:  # noqa: BLE001 — a handler crash
                    # must answer (the parent fails over); it must not
                    # kill the serve thread
                    reply = encode_frame(pack_error(
                        int(ErrorCode.SHARD_UNAVAILABLE),
                        f"worker op failed: {e!r:.200}"))
                sock.sendall(reply)
    except OSError:
        return  # peer went away; the parent reconnects
    finally:
        try:
            sock.close()
        except OSError:
            pass


def worker_main(conn, group_id: int, shard_ids: list, num_shards: int,
                ckpt_dir: str, wal_dir: str, knobs: dict) -> None:
    """Entry point of one worker process (spawn context): recover the
    owned partitions (newest checkpoint + WAL tail — the normal PR 5
    paths), then serve transport ops on a loopback socket forever."""
    from wukong_tpu.store.dynamic import insert_triples
    from wukong_tpu.store.persist import (
        checkpoint_part_path,
        gstore_digest,
        load_gstore,
    )
    from wukong_tpu.store.wal import replay_dir
    from wukong_tpu.utils.errors import CheckpointCorrupt
    from wukong_tpu.vector.vstore import apply_vector_record

    try:
        for k, v in knobs.items():
            try:
                Global.set(k, v)
            except Exception:  # noqa: BLE001 — immutable/renamed knob
                pass
        found = _newest_bundle(ckpt_dir)
        if found is None:
            conn.send(("error", f"no checkpoint bundle in {ckpt_dir}"))
            return
        path, manifest = found
        wal_seq = int(manifest.get("wal_seq", -1))
        stores: dict = {}
        for sid in shard_ids:
            idx = next((j for j, p in enumerate(manifest.get("parts", []))
                        if int(p.get("sid", -1)) == int(sid)
                        and int(p.get("num_workers", 0)) == num_shards),
                       None)
            if idx is None:
                conn.send(("error",
                           f"bundle {path} has no part for shard {sid}"))
                return
            stores[int(sid)] = load_gstore(checkpoint_part_path(path, idx))
        # WAL tail replay with recovery.py's contiguity rule: a gap means
        # acknowledged records were truncated away behind some OTHER
        # checkpoint — applying the rest would silently skip mutations
        prev_seq = wal_seq
        if wal_dir:
            for rec in replay_dir(wal_dir, after_seq=wal_seq):
                if rec.seq != prev_seq + 1:
                    raise CheckpointCorrupt(
                        f"WAL gap: record {rec.seq} follows {prev_seq}",
                        path=wal_dir)
                prev_seq = rec.seq
                if rec.kind == "vector":
                    for g in stores.values():
                        apply_vector_record(g, rec.payload)
                else:
                    for g in stores.values():
                        insert_triples(g, rec.payload["triples"],
                                       dedup=rec.payload.get("dedup", True),
                                       check_ids=False)
        state = _WorkerState(stores, prev_seq, wal_dir)
        server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        server.bind(("127.0.0.1", 0))
        server.listen(16)
        digests = {sid: int(gstore_digest(g)) for sid, g in stores.items()}
        conn.send(("ready", server.getsockname()[1], digests,
                   int(prev_seq), "jax" in sys.modules))
    except Exception as e:  # noqa: BLE001 — boot failure must reach the
        # supervisor as a message, not a silent exit code
        try:
            conn.send(("error", f"{type(e).__name__}: {e}"))
        except OSError:
            pass
        return
    while True:
        try:
            cli, _addr = server.accept()
        except OSError:
            return
        cli.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        t = threading.Thread(target=_serve_connection, args=(cli, state),
                             daemon=True)
        t.start()


# ---------------------------------------------------------------------------
# parent side
# ---------------------------------------------------------------------------

class _Group:
    """Supervisor bookkeeping for one worker process."""

    def __init__(self, gid: int, shard_ids: list):
        self.gid = gid
        self.shard_ids = list(shard_ids)
        self.proc = None
        self.addr = None
        self.misses = 0
        self.restarts = 0  # consecutive failed/backed-off restarts
        self.serving: set = set()  # shards whose digest matched (peered)


def _metrics():
    from wukong_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.counter("wukong_proc_restarts_total",
                    "Worker processes restarted by the supervisor",
                    labels=("group",)),
        reg.counter("wukong_proc_heartbeat_misses_total",
                    "Supervisor heartbeats a worker failed to answer",
                    labels=("group",)),
    )


class ProcSupervisor:
    """Own the worker pool for one sharded store: spawn, heartbeat,
    restart-with-recovery, and the SocketTransport peer registry.

    Lifecycle: ``start()`` checkpoints the current stores (workers boot
    from it), spawns the pool, installs a SocketTransport on the sstore;
    ``stop()`` tears the pool down and restores the previous transport.
    ``kill()`` SIGKILLs one worker — the chaos drill's hammer."""

    def __init__(self, sstore, ckpt_dir: str, wal_dir: str | None = None,
                 recovery=None):
        from wukong_tpu.runtime.transport import SocketTransport
        from wukong_tpu.store.wal import active_wal

        self.sstore = sstore
        self.ckpt_dir = ckpt_dir
        wal = active_wal()
        self.wal_dir = (wal_dir if wal_dir is not None
                        else (wal.dir if wal is not None else ""))
        self._recovery = recovery  # optional RecoveryManager for checkpoints
        self.transport = SocketTransport()
        self._prev_transport = None
        self._lock = make_lock("procs.state")
        # table shape changes (start/stop) hold _lock; readers iterate a
        # live dict (CPython-atomic) and _Group fields are single-writer
        self.groups: dict[int, _Group] = {}  # lock-free: single-writer table; per-group fields owned by heartbeat thread
        self._ctx = multiprocessing.get_context("spawn")
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self._m_restarts, self._m_misses = _metrics()
        self.worker_jax_loaded: bool | None = None  # drill/test probe

    # -- lifecycle -------------------------------------------------------
    def _checkpoint(self) -> None:
        if self._recovery is not None:
            self._recovery.checkpoint()
            return
        from wukong_tpu.runtime.recovery import RecoveryManager

        rm = RecoveryManager(lambda: list(self.sstore.stores),
                             sstore=self.sstore, ckpt_dir=self.ckpt_dir)
        rm.checkpoint()

    def start(self, checkpoint: bool = True) -> None:
        from wukong_tpu.obs.events import emit_event

        if checkpoint:
            self._checkpoint()
        D = self.sstore.D
        W = max(1, min(int(Global.proc_workers), D))
        # contiguous split: shard i -> group i * W // D
        with self._lock:
            for gid in range(W):
                shard_ids = [i for i in range(D) if i * W // D == gid]
                self.groups[gid] = _Group(gid, shard_ids)
        for grp in self.groups.values():
            self._spawn(grp)
        self._prev_transport = self.sstore.transport
        self.sstore.transport = self.transport
        self._hb_stop.clear()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop,
                                           name="proc-heartbeat",
                                           daemon=True)
        self._hb_thread.start()
        emit_event("proc.pool.start", workers=W,
                   shards=D, ckpt_dir=self.ckpt_dir)

    def stop(self) -> None:
        from wukong_tpu.obs.events import emit_event

        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None
        if self._prev_transport is not None:
            self.sstore.transport = self._prev_transport
            self._prev_transport = None
        self.transport.close()
        with self._lock:
            groups, self.groups = dict(self.groups), {}
        for grp in groups.values():
            if grp.proc is not None and grp.proc.is_alive():
                grp.proc.terminate()
                grp.proc.join(timeout=5)
        emit_event("proc.pool.stop", workers=len(groups))

    # -- spawn / restart -------------------------------------------------
    def _spawn(self, grp: _Group, timeout_s: float = 60.0) -> bool:
        """Spawn (or respawn) one group's worker and wait for its
        recovery to finish: checkpoint load + WAL-tail replay, proven by
        a per-shard digest match against the parent's live stores. Only
        matching shards get peered; a mismatch stays parent-served."""
        from wukong_tpu.obs.events import emit_event
        from wukong_tpu.store.persist import gstore_digest

        knobs = {k: getattr(Global, k) for k in _INHERITED_KNOBS}
        knobs["wal_dir"] = ""  # workers never append; replay is read-only
        parent_conn, child_conn = self._ctx.Pipe()
        proc = self._ctx.Process(
            target=worker_main,
            args=(child_conn, grp.gid, grp.shard_ids, self.sstore.D,
                  self.ckpt_dir, self.wal_dir, knobs),
            daemon=True, name=f"wukong-worker-{grp.gid}")
        proc.start()
        child_conn.close()
        if not parent_conn.poll(timeout_s):
            log_warn(f"proc group {grp.gid}: worker did not report within "
                     f"{timeout_s}s; leaving shards parent-served")
            proc.terminate()
            return False
        try:
            msg = parent_conn.recv()
        except (EOFError, OSError):
            log_warn(f"proc group {grp.gid}: worker died before reporting; "
                     "leaving shards parent-served")
            proc.join(timeout=5)
            return False
        if msg[0] != "ready":
            log_warn(f"proc group {grp.gid}: worker boot failed: {msg[1]}")
            proc.join(timeout=5)
            return False
        _tag, port, digests, applied_seq, jax_loaded = msg
        self.worker_jax_loaded = bool(jax_loaded)
        grp.proc = proc
        grp.addr = ("127.0.0.1", int(port))
        grp.misses = 0
        grp.serving = set()
        for sid in grp.shard_ids:
            want = int(gstore_digest(self.sstore.stores[sid]))
            got = int(digests.get(sid, -1))
            if got != want:
                log_warn(f"proc group {grp.gid}: shard {sid} digest "
                         f"mismatch after recovery (worker {got:#x}, "
                         f"parent {want:#x}); keeping it parent-served")
                continue
            grp.serving.add(sid)
            self.transport.register_peer(sid, grp.addr)
            # the outage is over for this shard: close its breaker so the
            # next fetch goes straight back to the (new) primary path
            self.sstore.breaker.record_success(sid)
        log_info(f"proc group {grp.gid}: worker pid={proc.pid} serving "
                 f"{sorted(grp.serving)} on port {port} "
                 f"(wal seq {applied_seq})")
        emit_event("proc.worker.ready", group=grp.gid, pid=proc.pid,
                   shards=sorted(grp.serving), wal_seq=int(applied_seq))
        return bool(grp.serving)

    def _deregister(self, grp: _Group) -> None:
        for sid in list(grp.serving):
            self.transport.deregister_peer(sid)
        grp.serving = set()

    def kill(self, gid: int) -> int:
        """SIGKILL one worker (the drill's mid-stream hammer); returns the
        dead pid. Peers stay registered on purpose: in-flight and
        subsequent fetches must discover the death the hard way (connect
        refused → retries → breaker → replica failover) exactly like a
        real crash, until the heartbeat notices and restarts."""
        grp = self.groups[gid]
        pid = grp.proc.pid
        os.kill(pid, signal.SIGKILL)
        grp.proc.join(timeout=10)
        return pid

    def restart(self, gid: int) -> bool:
        """Restart one group's worker through the full recovery path,
        with capped-exponential backoff between consecutive attempts."""
        from wukong_tpu.obs.events import emit_event

        grp = self.groups[gid]
        self._deregister(grp)
        if grp.proc is not None and grp.proc.is_alive():
            grp.proc.terminate()
        if grp.proc is not None:
            grp.proc.join(timeout=10)
        backoff_ms = min(
            int(Global.proc_restart_backoff_ms) * (2 ** grp.restarts),
            int(Global.proc_restart_backoff_max_ms))
        if grp.restarts > 0 or backoff_ms > 0:
            time.sleep(backoff_ms / 1000.0)
        ok = self._spawn(grp)
        if ok:
            grp.restarts = 0
        else:
            grp.restarts += 1
        self._m_restarts.labels(group=str(gid)).inc()
        emit_event("proc.restart", group=gid, ok=ok,
                   backoff_ms=int(backoff_ms))
        return ok

    # -- heartbeat -------------------------------------------------------
    def _committed_seq(self) -> int:
        from wukong_tpu.store.wal import active_wal

        wal = active_wal()
        return (wal.next_seq - 1) if wal is not None else -1

    def _ping(self, grp: _Group) -> bool:
        if grp.addr is None or not grp.serving:
            return False
        sid = min(grp.serving)
        try:
            out = self.transport.call(grp.addr, "ping", sid,
                                      (self._committed_seq(),))
        except Exception:  # noqa: BLE001 — any failure shape is a miss;
            # classification is the restart's job
            return False
        return int(out.get("sid", -1)) == sid

    def _heartbeat_loop(self) -> None:
        period = max(int(Global.proc_heartbeat_ms), 10) / 1000.0
        misses_allowed = max(int(Global.proc_heartbeat_misses), 1)
        while not self._hb_stop.wait(period):
            with self._lock:
                groups = list(self.groups.values())
            for grp in groups:
                if self._hb_stop.is_set():
                    return
                if grp.proc is None:
                    continue
                if self._ping(grp):
                    grp.misses = 0
                    continue
                grp.misses += 1
                self._m_misses.labels(group=str(grp.gid)).inc()
                if grp.misses >= misses_allowed:
                    log_warn(f"proc group {grp.gid}: "
                             f"{grp.misses} consecutive heartbeat misses; "
                             "restarting the worker")
                    grp.misses = 0
                    self.restart(grp.gid)

    # -- drill / test helpers -------------------------------------------
    def sync(self) -> None:
        """Push the WAL tail to every live worker (the heartbeat does this
        continuously; drills call it for a deterministic barrier)."""
        seq = self._committed_seq()
        for grp in self.groups.values():
            if grp.serving:
                self.transport._retry_call(min(grp.serving), "sync", (seq,))

    def worker_digests(self, gid: int) -> dict:
        """Per-shard content digests served by one live worker."""
        grp = self.groups[gid]
        return {sid: int(self.transport._retry_call(sid, "digest", ()))
                for sid in sorted(grp.serving)}

    def group_of(self, sid: int) -> int:
        for gid, grp in self.groups.items():
            if sid in grp.shard_ids:
                return gid
        raise KeyError(sid)
