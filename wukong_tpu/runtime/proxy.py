"""Proxy: the client-facing frontend (reference: core/proxy.hpp).

Glues parser -> planner -> engine and implements the reference's query modes:
- run_single_query: parse, optimize (or apply a user plan), execute with
  repeats, record latency, print/dump results (proxy.hpp:298-385)
- run_query_emu: open-loop throughput emulator over template mixes with
  candidate filling (proxy.hpp:69-129, 391-545) — see emulator.py
- dynamic_load_data / gstore_check passthroughs (proxy.hpp:548-597)
- streaming verbs (no reference analogue — Wukong+S): stream_register /
  stream_unregister / stream_poll for standing queries, stream_feed for
  epoch commits (see wukong_tpu/stream/)
"""

from __future__ import annotations

import threading
import time

import numpy as np

from wukong_tpu.analysis.lockdep import make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs import (
    activate,
    get_recorder,
    get_registry,
    maybe_device_trace,
    maybe_start_metrics_http,
    maybe_start_trace,
)
from wukong_tpu.obs.device import note_feedback
from wukong_tpu.obs.reuse import maybe_observe_reuse
from wukong_tpu.obs.slo import get_overload, get_slo, tenant_label
from wukong_tpu.runtime.admission import maybe_admission
from wukong_tpu.planner.heuristic import heuristic_plan
from wukong_tpu.planner.plan_file import set_plan
from wukong_tpu.runtime.batcher import (
    _M_PARSE_CACHE,
    _M_PLAN_CACHE,
    PlanCache,
    QueryBatcher,
    snapshot_patterns,
    template_signature,
)
from wukong_tpu.runtime.monitor import Monitor
from wukong_tpu.runtime.resilience import Deadline
from wukong_tpu.sparql.ir import SPARQLQuery, SPARQLTemplate
from wukong_tpu.sparql.parser import Parser
from wukong_tpu.types import IN, OUT, is_tpid
from wukong_tpu.utils.errors import ErrorCode, WukongError
from wukong_tpu.utils.logger import log_error, log_info
from wukong_tpu.utils.lru import LRUCache
from wukong_tpu.utils.timer import get_usec


# ceiling on how long a serving thread waits for a coalesced dispatch to
# settle (the stream lane's STREAM_WAIT_TIMEOUT_S analogue) — a wedged
# batcher surfaces as an error, never as a hung client
BATCH_WAIT_TIMEOUT_S = 600.0


def _batch_wait_timeout(q) -> float:
    dl = getattr(q, "deadline", None)
    if dl is not None:
        rem = dl.remaining_s()
        if rem is not None:
            return min(rem + 60.0, BATCH_WAIT_TIMEOUT_S)
    return BATCH_WAIT_TIMEOUT_S


class Proxy:
    def __init__(self, gstore, str_server, cpu_engine=None, tpu_engine=None,
                 dist_engine=None, planner=None):
        self.g = gstore
        self.str_server = str_server
        self.cpu = cpu_engine
        self.tpu = tpu_engine
        self.dist = dist_engine
        self.planner = planner  # cost-based optimizer (optional)
        self.monitor = Monitor()
        # observability: flight recorder ring + process metrics registry
        # (console verbs `trace` / `metrics` read these back)
        self.recorder = get_recorder()
        self.metrics = get_registry()
        self._m_queries = self.metrics.counter(
            "wukong_queries_total", "Proxy queries by reply status and tenant",
            labels=("status", "tenant"))
        self._m_lane = self.metrics.counter(
            "wukong_lane_routed_total",
            "Plan-time light/heavy lane routing decisions", labels=("lane",))
        # tensor-join strategy routing (wukong_tpu/join/): per-query
        # strategy decisions and wcoj-to-walk degradations
        self._m_join = self.metrics.counter(
            "wukong_join_queries_total",
            "Plan-time execution-strategy decisions", labels=("strategy",))
        self._m_join_fallback = self.metrics.counter(
            "wukong_join_fallback_total",
            "WCOJ executions degraded to the walk", labels=("reason",))
        self._m_join_demoted = self.metrics.counter(
            "wukong_join_demotions_total",
            "Templates demoted wcoj->walk by measured-blowup feedback")
        # device-route plumbing (join_device knob): plan-time host/device
        # decisions and the measured-candidate demotions back to host
        self._m_join_route = self.metrics.counter(
            "wukong_join_route_total",
            "Plan-time wcoj level-route decisions", labels=("route",))
        self._m_route_demoted = self.metrics.counter(
            "wukong_join_route_demotions_total",
            "Templates demoted device->host by measured-candidate feedback")
        # compiled-template routing (engine/template_compile.py): plan-
        # time route decisions and compiled executions degraded to the
        # host walk (the demotion latch itself counts inside the engine)
        self._m_template_route = self.metrics.counter(
            "wukong_template_route_total",
            "Plan-time compiled-template route decisions",
            labels=("route",))
        self._m_template_fallback = self.metrics.counter(
            "wukong_template_fallback_total",
            "Compiled-template executions degraded to the host walk",
            labels=("reason",))
        # hybrid graph+vector serving (wukong_tpu/vector/): per-mode knn
        # query counts, plan-time scan-route decisions, and the measured
        # demotions back to the host kernels (the JOIN_ROUTES posture)
        self._m_vec_queries = self.metrics.counter(
            "wukong_vector_queries_total",
            "knn() queries by composition mode", labels=("mode",))
        self._m_vec_route = self.metrics.counter(
            "wukong_vector_route_total",
            "Plan-time knn scan route decisions", labels=("route",))
        self._m_vec_demoted = self.metrics.counter(
            "wukong_vector_route_demotions_total",
            "knn templates demoted device->host by measured feedback")
        self._wcoj = None  # guarded by: _batcher_init_lock
        self._wcoj_dist = None  # guarded by: _batcher_init_lock
        self._template = None  # guarded by: _batcher_init_lock
        self._pool = None
        self._stream = None
        # serving fast path: parse cache (query text -> parsed query) and
        # plan cache (template signature + store version -> plan recipe);
        # the batcher itself starts lazily on the first batched dispatch
        self._parse_cache = LRUCache(Global.parse_cache_size)
        self._plan_cache = PlanCache(Global.plan_cache_size)
        self._batcher: QueryBatcher | None = None  # guarded by: _batcher_init_lock
        self._batcher_init_lock = make_lock("proxy.batcher_init")
        # fault tolerance: the recovery manager (checkpoint/restore + shard
        # healing) starts lazily; its background threads launch here only
        # when the knobs ask for them (zero-cost when off)
        self._recovery = None  # guarded by: _recovery_init_lock
        self._recovery_init_lock = make_lock("proxy.recovery_init")
        if (Global.checkpoint_interval_s > 0 and Global.checkpoint_dir) or (
                dist_engine is not None and Global.replication_factor > 1):
            self.recovery().start()
        # metrics scrape endpoint (metrics_port knob; no-op when 0/off)
        maybe_start_metrics_http()
        # the placement observatory: the metrics time-series sampler
        # (enable_tsdb; trend windows for /history and the advisor) and —
        # with a sharded store — the observe-only placement advisor
        # (placement_interval_s > 0 runs its loop; 0 = on-demand /plan)
        from wukong_tpu.obs.placement import maybe_start_advisor
        from wukong_tpu.obs.tsdb import maybe_start_tsdb

        maybe_start_tsdb()
        sstore = getattr(dist_engine, "sstore", None)
        if sstore is not None:
            # the migration actuator (runtime/migration.py) attaches
            # either way (the `migrate` verb works on demand); when its
            # loop runs (migration_enable + placement_interval_s) it
            # sweeps the advisor itself, so the observe-only loop is
            # skipped — one sweeper, not two
            from wukong_tpu.runtime.migration import maybe_start_migration

            if maybe_start_migration(sstore, owner=self) is None:
                maybe_start_advisor(sstore)
            # /healthz readiness probe: degraded or failover shards mean
            # the process serves, but not at full strength. The probe
            # holds the store through a weakref: the registry is
            # process-global, so a strong capture would keep a retired
            # world's degraded set driving readiness (503 under
            # health_ready_503) long after the store that owned it died
            import weakref

            from wukong_tpu.obs.httpd import register_health_source

            ss_ref = weakref.ref(sstore)

            def _shard_probe():
                ss = ss_ref()
                if ss is None or not (ss.degraded_shards
                                      or ss.failover_shards):
                    return None
                return {"degraded": sorted(ss.degraded_shards),
                        "failover": sorted(ss.failover_shards)}

            register_health_source("shards", _shard_probe)
        # surface the sharded store's per-shard breaker in the rolling
        # throughput report (resilience observability, PR 1 follow-up)
        breaker = getattr(getattr(dist_engine, "sstore", None), "breaker", None)
        if breaker is not None:
            self.monitor.attach_breaker("dist.shard", breaker)
        # the materialized-view serving plane (wukong_tpu/serve/): bind
        # the result cache + view registry to THIS proxy's host
        # partition — a re-attach (new world in-process) purges entries
        # and drops old-world view registrations wholesale
        from wukong_tpu.serve import get_serve

        get_serve().attach(self.g, self.str_server)

    def engine_pool(self):
        """Lazily-started host engine pool (N CPU engines with stealing and
        adaptive snooze — wukong.cpp:202-225 spawns these at boot; here the
        first concurrent workload starts them)."""
        if self._pool is None:
            from wukong_tpu.engine.cpu import CPUEngine
            from wukong_tpu.runtime.scheduler import EnginePool

            self._pool = EnginePool(
                make_engine=lambda tid: CPUEngine(self.g, self.str_server))
            self._pool.start()
        return self._pool

    # ------------------------------------------------------------------
    def _parse_text(self, text: str) -> SPARQLQuery:
        """Parse with the bounded-LRU parse cache: repeated query texts
        skip the parser entirely. Entries are pickled blobs — loads() is
        several times cheaper than deepcopy on the serving fast path, and
        every hit gets a pristine query (no execution-state leaks)."""
        import pickle

        blob = self._parse_cache.get(text)
        if blob is not None:
            _M_PARSE_CACHE.labels(result="hit").inc()
            q = pickle.loads(blob)
            q._qtext = text  # view promotion re-registers from the text
            return q
        _M_PARSE_CACHE.labels(result="miss").inc()
        q = Parser(self.str_server).parse(text)
        q._qtext = text
        try:
            self._parse_cache.put(
                text, pickle.dumps(q, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception:  # unpicklable artifact: skip caching, stay correct
            _M_PARSE_CACHE.labels(result="uncacheable").inc()
        return q

    def _plan_version(self):
        """The plan-cache version key: the store version (dynamic inserts /
        stream commits bump it) + whether the cost planner is active."""
        return (getattr(self.g, "version", 0),
                self.planner is not None and Global.enable_planner)

    def _plan(self, q: SPARQLQuery, plan_text: str | None = None) -> None:
        if plan_text is not None:
            if Global.enable_planner:
                log_info("user plan ignored: planner is enabled (config)")
            elif not set_plan(q.pattern_group, plan_text):
                raise WukongError(ErrorCode.UNKNOWN_PLAN, "bad plan file")
            else:
                return
        if getattr(getattr(q, "knn", None), "mode", "") == "rank_then_pattern":
            # a seeded chain executes in TEXTUAL order outward from the
            # knn seeds: a planner reorder would re-root the chain away
            # from the seeded variable and flip the query's semantics
            q._tsig = template_signature(q)
            q._rver = self._plan_version()[0]
            return
        # plan cache: same template signature + same store version replays
        # the recorded plan recipe (dynamic inserts / stream commits bump
        # the version, so stale plans never apply)
        sig = template_signature(q)
        # stashed for the reply-side reuse observatory: classify() reuses
        # the plan-time signature instead of re-walking the patterns
        # (the largest single component of the per-reply hook cost), and
        # the shadow key must carry the version the read EXECUTES under —
        # a write committing between plan and reply would otherwise file
        # the key under the new version and credit hits a real cache
        # could not have served
        q._tsig = sig
        version = self._plan_version()
        q._rver = version[0]
        if sig is None:
            # unions/optionals/empty groups plan recursively — shapes the
            # recipe cache (and the item-7 result cache) cannot key
            _M_PLAN_CACHE.labels(result="uncacheable").inc()
        elif self._plan_cache.lookup(q, sig, version):
            return
        parsed = snapshot_patterns(q) if sig is not None else None
        if self.planner is not None and Global.enable_planner:
            if self.planner.generate_plan(q):
                if sig is not None:
                    self._plan_cache.record(parsed, q, sig, version)
                return
        heuristic_plan(q)
        if sig is not None:
            self._plan_cache.record(parsed, q, sig, version)

    def _engine_for(self, q: SPARQLQuery, device: str | None):
        if device == "tpu" or (device is None and Global.enable_tpu and self.tpu):
            return self.tpu or self.cpu
        if device == "dist" and self.dist is not None:
            return self.dist
        return self.cpu

    # ------------------------------------------------------------------
    def run_single_query(self, text: str, repeats: int = 1,
                         plan_text: str | None = None, mt_factor: int = 1,
                         device: str | None = None, blind: bool | None = None,
                         print_results: int = 0,
                         tenant: str = "default") -> SPARQLQuery:
        """sparql -f <file> [-n repeats] [-p plan] [-m mt] [-N] [-v N] [-t tenant] (console.hpp:141-153)."""
        if mt_factor > 1:
            # the reference fans an index scan out to mt_factor threads and
            # merges replies (sparql.hpp:1064-1088). The single-driver engines
            # here scan the whole index vectorized in one kernel, and the
            # distributed engine shards scans per partition — so -m is a no-op
            # rather than a partial-result slice.
            log_info("-m (mt_factor) is vectorized away on this engine; "
                     "running the full index scan")

        if repeats < 1:
            # validate BEFORE admission: a raise past _admit would leak
            # the tenant's in-flight slot (note_done never runs)
            raise WukongError(ErrorCode.SYNTAX_ERROR, "repeats must be >= 1")
        # per-query trace context, created at receipt (sampled; None when
        # tracing is off — every downstream hook then degrades to a getattr)
        trace = maybe_start_trace(kind="query", text=text)
        t0_us = get_usec()
        # tenant admission: bounded label + overload-bus in-flight/arrival
        # note (obs/slo.py; one knob check when accounting is off)
        ten = self._admit(tenant)
        if trace is not None:
            trace.tenant = ten

        adm_d = None

        def prepare():
            if trace is None:
                qq = self._parse_text(text)
                self._plan_prepared(qq, blind, plan_text, tenant=ten)
                if adm_d is not None:
                    adm_d.apply(qq)
                return qq
            with trace.span("proxy.parse"):
                qq = self._parse_text(text)
            qq.trace = trace
            qq.qid = trace.qid
            with trace.span("proxy.plan"):
                self._plan_prepared(qq, blind, plan_text, tenant=ten)
            if adm_d is not None:
                adm_d.apply(qq)
            return qq

        q = None
        total_us = 0
        # activate the trace on the proxy thread too (parse/plan/fallback
        # decisions), and scope the JAX device profiler around the traced
        # execution when WUKONG_XPROF_DIR asks for an XProf capture
        try:
            adm_d = self._consult_admission(ten)
            with activate(trace), maybe_device_trace():
                q, total_us = self._run_repeats(prepare, repeats, device,
                                                trace)
        except Exception as e:
            # a parse/plan failure raises before any reply exists — it must
            # still reach the reply-side observability (a syntax-error storm
            # is an operational signal, not a silent gap)
            code = e.code if isinstance(e, WukongError) else "ERROR"
            self._m_queries.labels(
                status=code.name if isinstance(code, ErrorCode)
                else str(code), tenant=ten).inc()
            if trace is not None:
                self.recorder.on_complete(trace, code)
            self._observe_slo(ten, get_usec() - t0_us, ok=False,
                              status=code, trace=trace)
            raise
        # reply-side observability: the finished trace enters the flight
        # recorder (auto-dumping on timeout/budget/shard failures), and the
        # reply status lands on the metrics registry
        status = q.result.status_code
        self._m_queries.labels(status=status.name, tenant=ten).inc()
        if trace is not None:
            self.recorder.on_complete(trace, status)
            self._attribute(trace, q, text)
            log_info(f"trace {trace.trace_id} (qid {trace.qid}) recorded: "
                     f"{len(trace.spans)} spans, {trace.dur_us:,}us")
        # SLO accounting after the trace is finished/recorded: a burn
        # dump must serialize a completed trace, not a RUNNING one
        self._observe_slo(ten, get_usec() - t0_us,
                          ok=status == ErrorCode.SUCCESS, status=status,
                          trace=trace)
        self._note_admission_reply(ten, q)
        # serving-cache observatory (obs/reuse.py): template popularity +
        # the observe-only shadow-cache probe, charged at the reply point
        # against the store version the read executed under
        self._observe_reuse(q, ten, text)
        if q.result.status_code != ErrorCode.SUCCESS:
            if not q.result.complete:
                # structured partial reply, not a crash: the rows produced
                # before the deadline/budget expiry are still in the table
                log_error(
                    f"query degraded: {q.result.status_code.name} — partial "
                    f"result ({q.result.nrows} rows, "
                    f"{len(q.result.dropped_patterns)} pattern(s) dropped)")
            else:
                log_error(f"query failed: {q.result.status_code.name}")
            return q
        log_info(f"(last) result rows: {q.result.nrows}, "
                 f"avg latency: {total_us / repeats:,.0f} usec ({repeats} runs)")
        if print_results and not q.result.blind:
            self.print_result(q, min(print_results, q.result.nrows))
        return q

    def _run_repeats(self, prepare, repeats: int, device, trace):
        """The repeat/fallback execution loop (shape + capacity
        degradation); returns (last query, total execution usec)."""
        q = None
        total_us = 0
        for i in range(repeats):
            q = prepare()
            eng = self._engine_for(q, device)
            t0 = get_usec()
            self._serve_execute(q, eng, pinned=device is not None)
            total_us += get_usec() - t0
            if (q.result.status_code == ErrorCode.UNSUPPORTED_SHAPE
                    and eng is self.dist):
                # the distributed engine rejects some shapes up front
                # (UNION/OPTIONAL/versatile) — fall back to the
                # configured host engine. Capacity-exhaustion failures
                # keep their error status (falling back would
                # materialize the oversized table on one host).
                log_info("distributed engine rejected the plan shape; "
                         "falling back to the host engine")
                host = self._engine_for(q, None) or self.cpu
                if host is None or host is self.dist:
                    break  # no host engine: keep the error status
                if trace is not None:
                    trace.event("proxy.fallback", reason="shape",
                                to="host")
                q = prepare()
                t0 = get_usec()
                host.execute(q)
                total_us += get_usec() - t0
            elif (q.result.status_code == ErrorCode.CAPACITY_EXCEEDED
                  and eng is self.tpu and self.cpu is not None):
                # graceful degradation: the device capacity ceiling is a
                # TPU constraint, not a query property — the CPU engine
                # has no capacity classes, so re-run host-side (the
                # resilience analogue of the GPU->CPU spill in
                # WCOJ-on-GPU engines)
                log_info("device capacity exceeded; degrading to the "
                         "CPU engine")
                if trace is not None:
                    trace.event("proxy.fallback", reason="capacity",
                                to="cpu")
                q = prepare()
                t0 = get_usec()
                self.cpu.execute(q)
                total_us += get_usec() - t0
            if q.result.status_code in (ErrorCode.QUERY_TIMEOUT,
                                        ErrorCode.BUDGET_EXCEEDED):
                break  # deadline/budget spent: repeats are pointless
        return q, total_us

    def _admit(self, tenant) -> str:
        """Tenant admission: the bounded metric-label form of the tenant
        id, plus the overload bus's in-flight/arrival note. With
        accounting off this is one knob check and the raw id."""
        if not Global.enable_tenant_accounting:
            return str(tenant) if tenant else "default"
        ten = tenant_label(tenant)
        get_overload().note_admit(ten)
        return ten

    def _consult_admission(self, ten: str, cached: bool = False):
        """The admission control plane's consult point, AFTER ``_admit``
        (so the in-flight signal includes the query under decision) and
        inside the caller's reply-accounting try (a rejection releases
        the in-flight slot through ``_observe_slo``). One knob check
        when the plane is off. Rung-1 defers sleep HERE on the serving
        thread (past the batch window, draining congestion); rung-3
        raises the structured CAPACITY_EXCEEDED rejection; the returned
        Decision stamps a rung-2 partial budget onto the prepared
        query."""
        adm = maybe_admission()
        if adm is None:
            return None
        d = adm.admit(ten, cached=cached)
        if d.action == "reject":
            raise WukongError(
                ErrorCode.CAPACITY_EXCEEDED,
                f"admission shed: tenant {ten!r} ({d.reason or 'overload'})"
                f" — retry after {d.retry_after_s:.1f}s")
        if d.action == "defer" and d.wait_s > 0:
            time.sleep(min(d.wait_s, 5.0))
        return d

    def _note_admission_reply(self, ten: str, q) -> None:
        """Reply-side aggregate-row accounting for the row-budget quota
        (one knob check when the plane is off)."""
        adm = maybe_admission()
        if adm is not None:
            adm.note_reply(ten, int(getattr(q.result, "nrows", 0)))

    def _observe_slo(self, tenant: str, dur_us: int, ok: bool, status,
                     trace) -> None:
        """Reply-side SLO accounting (the LatencyAttributor observation
        point): release the in-flight slot, count reply-side sheds, and
        fold the reply into the tenant's SLO window — the burn-rate
        sentinel fires from here. One knob check when accounting is off."""
        if not Global.enable_tenant_accounting:
            return
        sig = get_overload()
        sig.note_done(tenant)
        if status == ErrorCode.QUERY_TIMEOUT:
            sig.note_shed("reply_timeout", tenant)
        elif status == ErrorCode.BUDGET_EXCEEDED:
            sig.note_shed("reply_budget", tenant)
        get_slo().observe(tenant, int(dur_us), ok, trace=trace)

    def _observe_reuse(self, q, tenant: str, text: str) -> None:
        """Reply-side reuse-observatory hook: the shadow key carries the
        PLAN-time store version (``_rver``, stashed where the plan cache
        read it), so a write landing between plan and reply cannot file
        the key under a version the read never saw. Queries that skipped
        the plan path (user plan files) fall back to the current
        version. With the real cache on, the shadow's verdict for this
        reply is compared against the real probe's (stamped on the query
        in ``_serve_execute``) — a disagreement on the same key counts
        toward ``wukong_cache_divergence_total``."""
        shadow_hit = maybe_observe_reuse(
            q, tenant,
            q.__dict__.get("_rver", getattr(self.g, "version", 0)),
            text=text)
        if Global.enable_result_cache:
            from wukong_tpu.serve.result_cache import note_shadow_outcome

            note_shadow_outcome(q, shadow_hit)

    def _plan_prepared(self, qq: SPARQLQuery, blind, plan_text,
                       tenant: str = "default") -> None:
        """Shared prepare tail: tenant stamp, blind mode, resilience
        knobs, planning, plan-time lane routing."""
        qq.tenant = tenant
        qq.mt_factor = 1
        qq.result.blind = Global.silent if blind is None else blind
        # per-query deadline + work budget from the resilience knobs
        # (query_deadline_ms / query_budget_rows; None when both off)
        qq.deadline = Deadline.from_config()
        self._plan(qq, plan_text)
        if getattr(qq, "knn", None) is not None:
            self._prepare_knn(qq)
        qq.lane = self.classify_lane(qq)
        self._m_lane.labels(lane=qq.lane).inc()
        qq.join_strategy = self.classify_join_strategy(qq)
        self._m_join.labels(strategy=qq.join_strategy).inc()
        if qq.join_strategy == "wcoj":
            qq.join_route = self.classify_join_route(qq)
            self._m_join_route.labels(route=qq.join_route).inc()
        elif getattr(qq, "knn", None) is None:
            # walk-strategy shapes may compile the WHOLE plan into one
            # fused device program (engine/template_compile.py)
            qq.template_route = self.classify_template_route(qq)
            self._m_template_route.labels(route=qq.template_route).inc()

    # ------------------------------------------------------------------
    # hybrid graph+vector routing (wukong_tpu/vector/)
    # ------------------------------------------------------------------
    def _prepare_knn(self, q: SPARQLQuery) -> None:
        """Plan-time knn stamps: refuse when the subsystem is off (the
        actuator posture — never silently degrade a vector query to a
        graph query), classify the composition mode and scan route, and
        flag wide scans so lane routing sends them down the heavy lane."""
        from wukong_tpu.vector import knn as vknn

        if not Global.enable_vectors:
            raise WukongError(ErrorCode.ATTR_DISABLE,
                              "knn() requires enable_vectors")
        q.knn_mode = vknn.classify_knn_mode(q)
        self._m_vec_queries.labels(mode=q.knn_mode).inc()
        vs = getattr(self.g, "vstore", None)
        n = int(vs.live_count()) if vs is not None else 0
        # EXPLAIN inputs (obs/profile.py): scan size = every live
        # embedding, scan bytes = the float32 block the kernel reads
        q._knn_live = n
        q._knn_dim = int(vs.dim) if vs is not None else 0
        # a wide scan-side composition (pure scan / rank-then-pattern)
        # is heavy-lane work: slice-range split across the engine pool
        q._knn_wide = (q.knn_mode != "pattern_then_rank"
                       and n >= max(int(Global.knn_split_threshold), 1))
        q.knn_route = self.classify_knn_route(q, n)
        self._m_vec_route.labels(route=q.knn_route).inc()

    def classify_knn_route(self, q: SPARQLQuery, live: int) -> str:
        """Plan-time host/device route for the knn scan, memoized per
        template signature + store version under ``knn_device auto``
        (vector upserts bump the store version, so the volume-driven
        decision re-arms on every embedding mutation). Overwritten by
        ``_record_knn_feedback`` when the device path failed."""
        knob = str(Global.knn_device).strip().lower()
        if knob in ("host", "device"):
            return knob
        thr = max(int(Global.knn_split_threshold), 1)

        def compute() -> str:
            # device when the scan volume amortizes the dispatch: the
            # split threshold doubles as the auto-device floor (both mark
            # "wide enough that per-dispatch overhead stops mattering")
            return "device" if live >= thr else "host"

        sig = template_signature(q)
        if sig is None:
            return compute()  # pure scans: unmemoized, computed per query
        return self._plan_cache.aux("knn_route", sig,
                                    self._knn_route_memo_key(), compute)

    def _knn_route_memo_key(self):
        return (*self._plan_version(), "auto",
                int(Global.knn_split_threshold))

    def _record_knn_feedback(self, q: SPARQLQuery) -> None:
        """Measured-feedback demotion for the knn device route: the
        engine/slice fallback latched a device failure onto the query
        (``knn_demoted``) — under ``knn_device auto``, demote the
        template's memoized route to host so same-template queries stop
        re-paying the failed device attempt. A store mutation or knob
        flip re-arms the volume-driven decision."""
        if getattr(q, "knn", None) is None:
            return
        demoted = getattr(q, "knn_demoted", None)
        if demoted is None:
            return
        if str(Global.knn_device).strip().lower() == "auto":
            sig = template_signature(q)
            if sig is not None:
                self._plan_cache.put_aux("knn_route", sig,
                                         self._knn_route_memo_key(), "host")
        self._m_vec_demoted.inc()
        note_feedback("knn", "demote_host")
        log_info(f"knn device route: demoted to host ({demoted})")

    def _maybe_presolve_knn(self, q: SPARQLQuery) -> None:
        """Wide scan-side knn: run the slice-range split across the
        engine pool's heavy lane HERE (the proxy owns the pool), stamping
        the ranked seeds onto the query so the engine's ``_knn_pre``
        consumes them instead of scanning inline. Any fan-out failure
        falls back to the engine's single-threaded scan — degraded, never
        broken."""
        if (getattr(q, "knn", None) is None
                or not getattr(q, "_knn_wide", False)
                or getattr(q, "knn_seeds", None) is not None):
            return
        vs = getattr(self.g, "vstore", None)
        if vs is None:
            return  # the engine raises the structured error
        from wukong_tpu.vector import knn as vknn

        try:
            anchor = vknn.resolve_anchor(vs, q.knn)
        except WukongError:
            return  # the engine surfaces it with proper status plumbing
        metric = q.knn.metric or Global.knn_metric
        thr = max(int(Global.knn_split_threshold), 1)
        n = int(vs.live_count())
        parts = max(min(n // thr + 1, 8), 1)
        if parts <= 1:
            return
        # the heavy-split decision: this scan fans out across the pool
        note_feedback("knn", "heavy_split")
        try:
            seeds, _scores, demoted = vknn.sliced_topk(
                self.engine_pool(), vs, anchor, q.knn.k, metric,
                getattr(q, "knn_route", "host"), parts)
        except Exception as e:
            log_info(f"knn sliced scan failed ({type(e).__name__}); "
                     "the engine scans inline")
            return
        q.knn_seeds = seeds
        if demoted:
            q.knn_demoted = demoted

    # ------------------------------------------------------------------
    # tensor-join strategy routing (wukong_tpu/join/)
    # ------------------------------------------------------------------
    def classify_join_strategy(self, q: SPARQLQuery) -> str:
        """Plan-time walk/wcoj strategy for a PLANNED query, memoized per
        template signature + store version through the plan cache (the
        ``lane`` pattern). The mutable knobs join the memo key so a
        runtime ``join_strategy``/``wcoj_ratio`` change applies
        immediately instead of serving stale decisions."""
        pg = q.pattern_group
        if (pg.unions or pg.optional or q.planner_empty
                or not pg.patterns
                or getattr(q, "knn", None) is not None):
            # knn composition lives in the walk engine's pre/post hooks;
            # the tensor-join executors have no vector seam
            return "walk"
        knob = str(Global.join_strategy).strip().lower()
        if knob == "walk":
            return "walk"
        if self.planner is None or not Global.enable_planner:
            # no cost model: only the forced knob may route wcoj
            if knob != "wcoj":
                return "walk"
            from wukong_tpu.join.qgraph import analyze

            return "wcoj" if analyze(pg.patterns).supported else "walk"
        sig = template_signature(q)
        pats = list(pg.patterns)
        key_extra = (knob, int(Global.wcoj_ratio),
                     int(Global.wcoj_min_rows))
        return self._plan_cache.aux(
            "strategy", sig, (*self._plan_version(), *key_extra),
            lambda: self.planner.choose_strategy(pats))

    def classify_join_route(self, q: SPARQLQuery) -> str:
        """Plan-time host/device level route for a wcoj-routed query,
        memoized per template signature + store version like the strategy
        decision (the knobs join the key so a runtime flip applies
        immediately). Overwritten by ``_record_route_feedback`` when the
        measured candidate volume says the estimate over-predicted."""
        knob = str(Global.join_device).strip().lower()
        if knob in ("host", "device"):
            return "device" if knob == "device" else "host"
        if self.planner is None or not Global.enable_planner:
            return "host"  # no cost model to amortize the dispatch against
        sig = template_signature(q)
        pats = list(q.pattern_group.patterns)
        key_extra = (knob, int(Global.join_device_min_candidates))
        return self._plan_cache.aux(
            "route", sig, (*self._plan_version(), *key_extra),
            lambda: self.planner.choose_join_route(pats))

    def _route_memo_key(self):
        return (*self._plan_version(), "auto",
                int(Global.join_device_min_candidates))

    def _record_route_feedback(self, q: SPARQLQuery) -> None:
        """Device-route feedback (the PR 10 measured-blowup pattern, one
        layer down): after a successful wcoj execution that ROUTED device
        under ``join_device auto``, compare the MEASURED candidate volume
        (summed per-level candidates from ``q.join_stats``) against the
        dispatch-amortization threshold and demote the memoized route to
        host when the estimate over-predicted — the padded dispatches
        were pure overhead on a chain this small. The memo key mirrors
        ``classify_join_route``'s exactly, so the demotion takes effect
        on the very next same-template query, and a knob flip or store
        mutation re-arms the estimate-driven decision."""
        stats = getattr(q, "join_stats", None)
        if (not stats or q.result.status_code != ErrorCode.SUCCESS
                or getattr(q, "join_route", "host") != "device"
                or str(Global.join_device).strip().lower() != "auto"
                or self.planner is None or not Global.enable_planner):
            return
        sig = template_signature(q)
        if sig is None:
            return
        if getattr(q, "_join_device_broken", False):
            # the executor latched host mid-query (DeviceRangeError, a
            # kernel bug, ...): a deterministic failure would re-pay the
            # failed device attempt on every same-template query — demote
            # the memo; a store mutation or knob flip re-arms the attempt
            self._plan_cache.put_aux("route", sig, self._route_memo_key(),
                                     "host")
            self._m_route_demoted.inc()
            note_feedback("join_route", "latched_host")
            log_info("wcoj device route: template demoted to host "
                     "(device path failed and latched host)")
            return
        measured = sum(int(lv.get("candidates", 0)) for lv in stats)
        if measured < max(int(Global.join_device_min_candidates), 1):
            self._plan_cache.put_aux("route", sig, self._route_memo_key(),
                                     "host")
            self._m_route_demoted.inc()
            note_feedback("join_route", "demote_host")
            log_info(f"wcoj device route: template demoted to host "
                     f"(measured candidates {measured:,} < "
                     f"join_device_min_candidates "
                     f"{Global.join_device_min_candidates:,})")

    # ------------------------------------------------------------------
    # whole-plan compiled-template routing (engine/template_compile.py)
    # ------------------------------------------------------------------
    def classify_template_route(self, q: SPARQLQuery) -> str:
        """Plan-time host/device route for a walk-strategy query through
        the whole-plan compiled engine. Only the planner's peak-rows
        ESTIMATE is memoized (per template signature + store version,
        the ``lane`` pattern) — the route itself is chosen live by
        ``choose_template_route`` so the per-template demotion latch and
        the measured padding-efficiency feedback apply on the very next
        query, not at the next memo invalidation."""
        from wukong_tpu.engine.template_compile import \
            choose_template_route

        # the PRE-PLAN signature (stamped in _plan): the demotion latch
        # keys on q._tsig at failure time, and the planner has reordered
        # the patterns by now — recomputing here would never match it
        sig = getattr(q, "_tsig", None)
        if sig is None:
            sig = template_signature(q)
        if sig is None:
            return "host"  # recursive shapes: no template to compile
        est = None
        if self.planner is not None and Global.enable_planner:
            pats = list(q.pattern_group.patterns)

            def compute():
                try:
                    return self.planner.estimate_peak_rows(pats)
                except Exception:
                    return None

            est = self._plan_cache.aux("template_est", sig,
                                       self._plan_version(), compute)
        q._template_est_rows = est
        return choose_template_route(sig, est,
                                     getattr(self.g, "version", 0))

    def template_engine(self):
        """Lazily-built whole-plan compiled engine over the host
        partition (its staged device operands are cached per store
        version through the shared JoinTableCache discipline, so
        dynamic inserts and stream commits self-invalidate)."""
        if self._template is None:  # unguarded: double-checked fast path, as wcoj()
            with self._batcher_init_lock:
                if self._template is None:
                    from wukong_tpu.engine.template_compile import \
                        TemplateCompiledEngine

                    self._template = TemplateCompiledEngine(
                        self.g, self.str_server)
        return self._template  # unguarded: write-once reference, non-None past init

    def _record_template_feedback(self, q: SPARQLQuery) -> None:
        """Measured feedback for the compiled-template route: after a
        successful compiled execution under ``template_device auto``, a
        measured live-row count below ``template_min_rows`` means the
        estimate over-predicted and the fused dispatch was overhead on a
        plan this small — latch the template back to the host walk (a
        store mutation re-arms the estimate-driven decision)."""
        if str(Global.template_device).strip().lower() != "auto":
            return
        recs = [r for r in (getattr(q, "device_steps", None) or [])
                if r.get("site") == "template.plan"]
        if not recs:
            return
        live = int(recs[-1].get("live", 0))
        if live < max(int(Global.template_min_rows), 1):
            from wukong_tpu.engine.template_compile import latch_demotion

            latch_demotion(getattr(q, "_tsig", None), "small_measured",
                           getattr(self.g, "version", 0))
            log_info(f"compiled template demoted to the host walk "
                     f"(measured live rows {live:,} < template_min_rows "
                     f"{Global.template_min_rows:,})")

    def _record_wcoj_feedback(self, q: SPARQLQuery) -> None:
        """WCOJ auto-routing feedback (PR 9 headroom): after a successful
        wcoj execution, record the MEASURED materialized-prefix blowup
        (peak per-level ``rows_out`` over the final fragment) from
        ``q.join_stats`` into the plan cache, and demote the template's
        memoized ``auto`` strategy to the walk when wcoj did NOT deliver
        its premise — intermediates bounded near the fragment. ``auto``
        routes wcoj on the ESTIMATED walk blowup, which over-predicts on
        the small WatDiv cyclic shapes (BENCH_CYCLIC.json
        ``auto_strategies`` lose 2-3x to the walk there): when the join's
        own materialized rows still blow past ``wcoj_ratio`` x final, it
        is doing walk-like materialization PLUS per-level intersection
        overhead, and the walk's simpler kernels win. Measured on the
        cyclic suite: winners keep the prefix at ~1.0x final (triangle
        1.0 / diamond 1.0) while the losers materialize 18-55x (clique4
        18.5 / w_tri_likes 27 / w_tri_follows 55). The closing-level
        CANDIDATE count is deliberately excluded — bounding candidates
        while materializing few rows is exactly the leapfrog win, and a
        candidate-based rule would demote the triangle's 14.8x speedup
        (candidates/final = 2.9 there). The memo key mirrors
        ``classify_join_strategy``'s exactly, so the demotion takes
        effect on the very next same-template query, and a knob flip or
        store mutation re-arms the estimate-driven decision."""
        stats = getattr(q, "join_stats", None)
        if (not stats or q.result.status_code != ErrorCode.SUCCESS
                or str(Global.join_strategy).strip().lower() != "auto"
                or self.planner is None or not Global.enable_planner):
            return
        sig = template_signature(q)
        if sig is None:
            return
        final = max(int(stats[-1]["rows_out"]), 1)
        peak = max(int(lv["rows_out"]) for lv in stats)
        measured = peak / final
        key = (*self._plan_version(), "auto", int(Global.wcoj_ratio),
               int(Global.wcoj_min_rows))
        self._plan_cache.put_aux("wcoj_measured", sig, key,
                                 round(measured, 2))
        # STRICTLY above the ratio: a prefix that stays at ~final rows
        # measures exactly 1.0, and a forced wcoj_ratio of 1 must not
        # demote the shapes wcoj is winning on
        if measured > max(float(Global.wcoj_ratio), 1.0):
            self._plan_cache.put_aux("strategy", sig, key, "walk")
            self._m_join_demoted.inc()
            note_feedback("strategy", "demote_walk")
            log_info(f"wcoj auto-routing: template demoted to the walk "
                     f"(measured prefix blowup {measured:.1f}x > "
                     f"wcoj_ratio {Global.wcoj_ratio} — wcoj did not keep "
                     "intermediates near the fragment)")

    def wcoj(self):
        """Lazily-built WCOJ executor over the host partition (its sorted
        edge tables are cached per store version, so dynamic inserts and
        stream commits self-invalidate like the plan cache)."""
        if self._wcoj is None:  # unguarded: double-checked fast path — an atomic reference read; construction is serialized below
            with self._batcher_init_lock:
                if self._wcoj is None:
                    from wukong_tpu.join.wcoj import WCOJExecutor

                    self._wcoj = WCOJExecutor(
                        self.g, self.str_server,
                        stats=getattr(self.planner, "stats", None))
        return self._wcoj  # unguarded: write-once reference, non-None past init

    def wcoj_dist(self):
        """Lazily-built DISTRIBUTED WCOJ executor over the sharded
        store's host partitions: hash-partitions the first eliminated
        variable and fans the per-partition joins out on the heavy lane
        (join/dist.py), so a cyclic query on a sharded store no longer
        funnels through one engine. The pool resolves lazily — slices run
        inline until the host engine pool exists."""
        if self._wcoj_dist is None:  # unguarded: double-checked fast path, as wcoj()
            with self._batcher_init_lock:
                if self._wcoj_dist is None:
                    from wukong_tpu.join.dist import DistributedWCOJExecutor

                    self._wcoj_dist = DistributedWCOJExecutor(
                        self.dist.sstore.stores, self.str_server,
                        stats=getattr(self.planner, "stats", None),
                        pool=lambda: self._pool)
        return self._wcoj_dist  # unguarded: write-once reference, non-None past init

    # ------------------------------------------------------------------
    # heavy-lane routing (runtime/batcher.py heavy path)
    # ------------------------------------------------------------------
    def classify_lane(self, q: SPARQLQuery) -> str:
        """Plan-time light/heavy routing: index-origin starts are heavy
        (wide-table scans — the Wukong+G CPU-vs-GPU split); other shapes
        are heavy when the optimizer's ``estimate_chain`` peak reaches
        ``heavy_rows_threshold``. Memoized per template signature + store
        version through the plan cache, so the estimate walk runs once per
        template, not per query."""
        if getattr(q, "_knn_wide", False):
            # a wide knn scan is index-origin-shaped work: a full-store
            # pass, slice-range split across the pool (the PR 8 split)
            return "heavy"
        try:
            if q.start_from_index():
                return "heavy"
        except WukongError:
            return "light"
        if self.planner is None or not Global.enable_planner:
            return "light"
        sig = template_signature(q)
        if sig is None:
            return "light"  # recursive shapes: unestimated, route light
        pats = list(q.pattern_group.patterns)

        threshold = max(int(Global.heavy_rows_threshold), 1)

        def compute() -> str:
            try:
                ests = self.planner.estimate_chain(pats)
            except Exception:
                ests = None
            return "heavy" if ests and max(ests) >= threshold else "light"

        # the threshold is runtime-mutable: it joins the memo key so a
        # knob change takes effect immediately instead of serving stale
        # decisions until the next store-version bump
        return self._plan_cache.aux(
            "lane", sig, (*self._plan_version(), threshold), compute)

    def heavy_index_batch(self, q: SPARQLQuery) -> int:
        """Plan-cache-backed device slice count for an index-origin query:
        ``suggest_index_batch`` memoized on template signature + store
        version and capped by ``heavy_batch_max`` (the emulator's old
        per-query-object ``_heavy_b`` hack, now a shared plan fact)."""
        if self.tpu is None:
            return 1
        cap = max(int(Global.heavy_batch_max), 1)
        sig = template_signature(q)
        # cap in the memo key: heavy_batch_max is runtime-mutable (e.g.
        # shrunk after a device OOM) and must apply to already-seen
        # templates immediately
        return int(self._plan_cache.aux(
            "heavy_b", sig, (*self._plan_version(), cap),
            lambda: max(min(self.tpu.suggest_index_batch(q, cap=cap), cap),
                        1)))

    # ------------------------------------------------------------------
    # serving-path micro-batching (runtime/batcher.py)
    # ------------------------------------------------------------------
    def batcher(self) -> "QueryBatcher":
        """Lazily-started request coalescer. Groups ride the engine pool's
        batch lane when the pool is running, else they run inline on the
        batcher's flusher thread."""
        if self._batcher is None:  # unguarded: double-checked fast path — an atomic reference read; construction is serialized below
            with self._batcher_init_lock:  # concurrent first dispatches
                if self._batcher is None:  # must share ONE coalescer
                    cpu = self.cpu or (self.tpu.cpu
                                       if self.tpu is not None else None)
                    self._batcher = QueryBatcher(
                        cpu, self.tpu, pool=lambda: self._pool,
                        suggest_heavy_b=self.heavy_index_batch)
        return self._batcher  # unguarded: write-once reference, non-None past init

    def _serve_execute(self, q: SPARQLQuery, eng,
                       pinned: bool = False) -> SPARQLQuery:
        """One serving-path dispatch: with ``enable_batching`` on,
        compatible queries coalesce into fused device dispatches; the
        default (off) and every bypass go straight to the engine — the
        single allowlisted direct-dispatch site for interactive queries.
        ``pinned`` (an explicit device= request) always bypasses: the
        batcher picks its own engine, which would silently override the
        caller's pin. A query the planner routed ``wcoj`` executes on the
        tensor-join engine first — any join-phase failure (unsupported
        residue, injected ``join.materialize`` fault, a bug) degrades to
        the walk below with the query untouched, never to an error.

        With ``enable_result_cache`` on (wukong_tpu/serve/), the dispatch
        is fronted by the version-keyed result cache: a hit installs the
        cached reply and skips execution entirely; a miss may elect this
        thread the key's request-collapsing leader, whose settlement (in
        the ``finally``) fills the cache and wakes the followers —
        whichever execution path below produced the reply."""
        from wukong_tpu.runtime import faults

        # the serving-boundary fault site: SLO-plane chaos scenarios
        # (Emulator.run_tenants) inject client-visible failures here so
        # per-tenant error budgets burn through the real reply path —
        # BEFORE the cache probe, so cached traffic burns budgets too
        faults.site("proxy.serve")
        lease = None
        if Global.enable_result_cache:
            from wukong_tpu.serve import get_serve

            served, lease = get_serve().cache.acquire(q)
            if served:
                return q
        try:
            if getattr(q, "join_strategy", "walk") == "wcoj" and not pinned:
                try:
                    # a sharded store routes the DISTRIBUTED join (heavy-
                    # lane fan-out over the partitions); any failure on
                    # either executor degrades to the matching walk below
                    if eng is self.dist and self.dist is not None:
                        self.wcoj_dist().try_execute(q)
                    else:
                        self.wcoj().try_execute(q)
                    self._record_wcoj_feedback(q)
                    self._record_route_feedback(q)
                    return q
                except Exception as e:
                    reason = (e.code.name if isinstance(e, WukongError)
                              else type(e).__name__)
                    self._m_join_fallback.labels(reason=reason).inc()
                    tr = getattr(q, "trace", None)
                    if tr is not None:
                        tr.event("join.fallback", reason=reason)
                    log_info(f"wcoj degraded to the walk ({reason})")
            if getattr(q, "template_route", "host") == "device" \
                    and not pinned and eng is not self.dist \
                    and getattr(q, "knn", None) is None:
                # whole-plan compiled execution: one fused XLA dispatch
                # serves the query byte-identically, or the plan shape
                # is refused (False) and the walk below owns it; any
                # compile/dispatch FAILURE latches a per-template
                # demotion so same-template queries stop re-paying the
                # failed device attempt until a store mutation re-arms
                try:
                    if self.template_engine().try_execute(q):
                        self._record_template_feedback(q)
                        return q
                except Exception as e:
                    from wukong_tpu.engine.template_compile import \
                        latch_demotion

                    reason = (e.code.name if isinstance(e, WukongError)
                              else type(e).__name__)
                    latch_demotion(getattr(q, "_tsig", None), reason,
                                   getattr(self.g, "version", 0))
                    self._m_template_fallback.labels(reason=reason).inc()
                    tr = getattr(q, "trace", None)
                    if tr is not None:
                        tr.event("template.fallback", reason=reason)
                    log_info(f"compiled template degraded to the walk "
                             f"({reason})")
            if Global.enable_batching and not pinned and eng is not None \
                    and eng is not self.dist \
                    and getattr(q, "knn", None) is None:
                # knn queries bypass the coalescer: their scan dispatch
                # is the batch (one fused matmul over the whole store)
                pend = self.batcher().offer(q)
                if pend is not None:
                    timeout = _batch_wait_timeout(q)
                    try:
                        pend.wait(timeout)
                    except TimeoutError:
                        # a wedged batcher must not hang the serving
                        # thread forever (the stream lane bounds its wait
                        # the same way) — surface the failure instead
                        log_error(f"batched dispatch not settled in "
                                  f"{timeout:.0f}s; batcher wedged?")
                        raise
                    return q
            if getattr(q, "knn", None) is not None:
                self._maybe_presolve_knn(q)
            eng.execute(q)  # batcher bypass: direct dispatch
            self._record_knn_feedback(q)
            return q
        finally:
            if lease is not None:
                # leader settlement: fill on SUCCESS+admission, and wake
                # the followers either way (a failed leader must never
                # strand its collapsed waiters)
                lease.settle(q)

    def serve_query(self, text: str, blind: bool | None = None,
                    device: str | None = None,
                    tenant: str = "default") -> SPARQLQuery:
        """The lean serving entry (no repeats, no result printing): parse
        (cached) -> plan (cached) -> batched or direct execution, with the
        same shape/capacity fallbacks as run_single_query. This is the
        path live traffic takes; run_single_query is the console surface.
        ``tenant`` is the caller's identity — stamped on the query, the
        trace, and every reply-side metric (bounded to ``max_tenants``
        label values), and fed to the SLO tracker at reply.

        With ``enable_result_cache`` on, a repeated text whose key is
        resident at the current store version serves on the zero-parse
        fast path: the text resolves straight to its cache key (learned
        at fill time), skipping parse + plan entirely — the reply-side
        accounting (tenant admission, SLO, reuse observatory, the
        ``proxy.serve`` fault site) still runs in full."""
        if Global.enable_result_cache and device is None \
                and not Global.enable_tracing:
            q = self._serve_fast_hit(text, blind, tenant)
            if q is not None:
                return q
        trace = maybe_start_trace(kind="query", text=text)
        t0_us = get_usec()
        ten = self._admit(tenant)
        if trace is not None:
            trace.tenant = ten

        adm_d = None

        def prepare():
            qq = self._parse_text(text)
            if trace is not None:
                qq.trace = trace
                qq.qid = trace.qid
            self._plan_prepared(qq, blind, None, tenant=ten)
            if adm_d is not None:
                adm_d.apply(qq)
            return qq

        try:
            adm_d = self._consult_admission(ten)
            with activate(trace):
                q, _us = self._run_repeats(prepare, 1, device, trace)
        except Exception as e:
            code = e.code if isinstance(e, WukongError) else "ERROR"
            self._m_queries.labels(
                status=code.name if isinstance(code, ErrorCode)
                else str(code), tenant=ten).inc()
            if trace is not None:
                self.recorder.on_complete(trace, code)
            self._observe_slo(ten, get_usec() - t0_us, ok=False,
                              status=code, trace=trace)
            raise
        status = q.result.status_code
        self._m_queries.labels(status=status.name, tenant=ten).inc()
        if trace is not None:
            self.recorder.on_complete(trace, status)
            self._attribute(trace, q, text)
        # SLO accounting after the trace is finished/recorded (burn
        # dumps serialize a completed trace)
        self._observe_slo(ten, get_usec() - t0_us,
                          ok=status == ErrorCode.SUCCESS, status=status,
                          trace=trace)
        self._note_admission_reply(ten, q)
        self._observe_reuse(q, ten, text)
        return q

    def _serve_fast_hit(self, text: str, blind, tenant: str):
        """The zero-parse cached-serving path: resolve the text to its
        cache key via the fill-time memo and, on a fresh-version hit,
        reply from the cached entry without parsing or planning. Returns
        None on any miss — the caller falls through to the full path
        (which probes the same key again, with collapsing). Skipped
        under tracing (a traced reply keeps its parse/plan spans) and
        for pinned-device requests."""
        from wukong_tpu.serve import get_serve

        eff_blind = Global.silent if blind is None else bool(blind)
        rc = get_serve().cache
        found = rc.fast_probe(text, eff_blind,
                              int(getattr(self.g, "version", 0)))
        if found is None:
            return None
        key, ent = found
        t0_us = get_usec()
        ten = self._admit(tenant)
        try:
            from wukong_tpu.runtime import faults

            # cached hits consume no engine capacity: only the q/s +
            # in-flight quotas apply (cached=True skips the ladder)
            self._consult_admission(ten, cached=True)
            # chaos parity: cached traffic crosses the same serving
            # boundary (and burns the same SLO budgets) as executed
            # traffic
            faults.site("proxy.serve")
        except Exception as e:
            code = e.code if isinstance(e, WukongError) else "ERROR"
            self._m_queries.labels(
                status=code.name if isinstance(code, ErrorCode)
                else str(code), tenant=ten).inc()
            self._observe_slo(ten, get_usec() - t0_us, ok=False,
                              status=code, trace=None)
            raise
        q = rc.build_reply(key, ent)
        q.tenant = ten
        self._m_queries.labels(status="SUCCESS", tenant=ten).inc()
        self._observe_slo(ten, get_usec() - t0_us, ok=True,
                          status=ErrorCode.SUCCESS, trace=None)
        self._observe_reuse(q, ten, text)
        return q

    # ------------------------------------------------------------------
    # introspection (obs/profile.py): EXPLAIN / EXPLAIN ANALYZE + the
    # latency-attribution regression sentinel
    # ------------------------------------------------------------------
    def explain_query(self, text: str, analyze: bool = False,
                      device: str | None = None,
                      plan_text: str | None = None) -> dict:
        """EXPLAIN: parse + plan and render the plan tree with the
        planner's per-step cost/cardinality estimates. EXPLAIN ANALYZE:
        additionally execute under a forced trace and join actual per-step
        rows/wall-time/fetches against the estimates, plus the end-to-end
        latency decomposition. Returns structured JSON; ``rendered`` holds
        the table (console verbs ``explain`` / ``analyze``)."""
        from wukong_tpu.obs.profile import explain_query

        return explain_query(self, text, analyze=analyze, device=device,
                             plan_text=plan_text)

    def _attribute(self, trace, q: SPARQLQuery, text: str) -> None:
        """Reply-side latency attribution: fold the finished trace into
        its template's rolling baseline; the sentinel auto-dumps the trace
        on a regression. One knob check when attribution is off."""
        if not Global.enable_attribution:
            return
        from wukong_tpu.obs.profile import get_attributor, template_key

        verdict = get_attributor().observe(
            trace, template_key(q, text),
            example=" ".join(text.split())[:120])
        if verdict is not None:
            log_error(
                f"latency regression ({verdict['reason']}): template "
                f"{verdict['template']} {verdict['total_us']:,}us vs "
                f"baseline p95 {verdict['baseline_p95_us']:,}us, worst "
                f"component {verdict['component']} "
                f"{verdict['share_drift_pts']:+.1f}pts — trace "
                f"{trace.trace_id} dumped")

    def print_result(self, q: SPARQLQuery, rows: int) -> None:
        """Render rows through the string server (proxy.hpp:247-294)."""
        for i in range(rows):
            vals = []
            for v in q.result.required_vars:
                col = q.result.v2c_map.get(v)
                if col is None:
                    vals.append("?")
                    continue
                vid = int(q.result.table[i, col])
                vals.append(self.str_server.id2str(vid)
                            if self.str_server.exist_id(vid) else str(vid))
            log_info(f"  {i + 1}: " + "\t".join(vals))

    # ------------------------------------------------------------------
    def fill_template(self, tmpl: SPARQLTemplate) -> None:
        """Collect candidate constants per %placeholder by running the
        type/predicate index (proxy.hpp:69-129)."""
        tmpl.candidates = []
        for tid, (pi, fld) in zip(tmpl.ptypes, tmpl.pos):
            if tid == "fromPredicate":
                # %<fromPredicate> (proxy.hpp:76-99): candidates are the
                # pattern's predicate index — subject slots draw its
                # subjects (IN side), object slots its objects (OUT side)
                pat = tmpl.query.pattern_group.patterns[pi]
                d = IN if fld == "subject" else OUT
                cands = np.asarray(self.g.get_index(pat.predicate, d))
                if len(cands) == 0:
                    raise WukongError(
                        ErrorCode.UNKNOWN_SUB,
                        f"no candidates for predicate {pat.predicate}")
                tmpl.candidates.append(cands)
                continue
            if not is_tpid(tid):
                raise WukongError(ErrorCode.SYNTAX_ERROR,
                                  f"placeholder type {tid} is not an index id")
            cands = np.asarray(self.g.get_index(tid, IN))
            if len(cands) == 0:
                raise WukongError(ErrorCode.UNKNOWN_SUB,
                                  f"no instances for placeholder type {tid}")
            tmpl.candidates.append(cands)

    # ------------------------------------------------------------------
    def dynamic_load_data(self, dirname: str, check_dup: bool = False) -> None:
        """`load -d <dir> [-c]` (proxy.hpp:548 -> RDFEngine -> DynamicLoader).

        -c (check_dup) opts into duplicate dropping, like the reference's
        dedup-on-insert option. Inserts reach the host store AND every
        distributed shard (their version bump restages device caches).
        """
        from wukong_tpu.loader.hdfs import resolve_dataset_dir
        from wukong_tpu.store.dynamic import load_dir_into

        dirname = resolve_dataset_dir(dirname)  # hdfs:// paths stage locally
        n = load_dir_into(self._insert_targets(), dirname, dedup=check_dup)
        if self.dist is not None and self.dist.sstore.check_version():
            # compiled chains bake per-segment probe/depth bounds
            self._fn_cache_clear()
        # plan recipes are version-keyed (stale ones can never apply), but
        # an insert obsoletes every cached plan's cost basis — free them
        self._plan_cache.clear()
        log_info(f"dynamic load: {n:,} new subject-side edges from {dirname}")

    # ------------------------------------------------------------------
    # streaming verbs (Wukong+S surface; wukong_tpu/stream/)
    # ------------------------------------------------------------------
    def stream_context(self, use_pool: bool = False):
        """Lazily-assembled StreamContext over this proxy's store(s).

        Inserts reach the host store and every distributed shard (like
        `load -d`); delta evaluation runs on the host partition. With
        use_pool the delta queries ride the engine pool's stream lane,
        interleaving with one-shot queries. The flag only matters on first
        call — the context is built once.
        """
        if self._stream is None:
            from wukong_tpu.stream import StreamContext

            self._stream = StreamContext(
                self._insert_targets(), self.str_server,
                pool=self.engine_pool() if use_pool else None,
                monitor=self.monitor)
        return self._stream

    def _insert_targets(self) -> list:
        """Every store online inserts must reach: the host partition first,
        then the distributed shards (the `load -d` fan-out), then any
        shard replicas — a mirror that missed a write would serve stale
        data on failover."""
        targets = [self.g]
        if self.dist is not None:
            targets += [g for g in self.dist.sstore.stores if g is not self.g]
            targets += self.dist.sstore.replica_stores()
        return targets

    def _checkpoint_targets(self) -> list:
        """The checkpointed primaries (no replicas: they are re-cloned
        from the restored primaries, not persisted twice)."""
        targets = [self.g]
        if self.dist is not None:
            targets += [g for g in self.dist.sstore.stores if g is not self.g]
        return targets

    # ------------------------------------------------------------------
    # fault tolerance (runtime/recovery.py)
    # ------------------------------------------------------------------
    def recovery(self):
        """Lazily-assembled RecoveryManager over this proxy's stores,
        stream context, and sharded store."""
        if self._recovery is None:  # unguarded: double-checked fast path — an atomic reference read; construction is serialized below
            with self._recovery_init_lock:
                if self._recovery is None:
                    from wukong_tpu.runtime.recovery import RecoveryManager

                    self._recovery = RecoveryManager(
                        self._checkpoint_targets,  # live view across heals
                        stream=self.stream_context(),
                        sstore=getattr(self.dist, "sstore", None),
                        pool=lambda: self._pool,
                        on_change=self._on_store_change)
        return self._recovery  # unguarded: write-once reference, non-None past init

    def _on_store_change(self) -> None:
        """Restore/rebuild invalidation: exactly the dynamic-insert
        contract — compiled chains and cached plans must re-derive."""
        if self.dist is not None and self.dist.sstore.check_version():
            self._fn_cache_clear()
        self._plan_cache.clear()

    def checkpoint(self) -> str:
        """Console `checkpoint` verb: write one atomic checkpoint bundle
        (partitions + stream registry) and truncate the covered WAL."""
        return self.recovery().checkpoint()

    def recover(self) -> dict:
        """Console `recover` verb: restore the newest checkpoint and
        replay the WAL tail (boot-time crash recovery)."""
        return self.recovery().recover()

    def stream_register(self, text: str, window=None, base_triples=None,
                        callback=None) -> int:
        """Register a standing SPARQL query; returns its stream qid.
        ``callback`` is the push-mode sink: invoked per committed
        ResultDelta next to the pull poll() surface (exceptions contained
        and surfaced as the stream-callback-error metric)."""
        return self.stream_context().register(text, window=window,
                                              base_triples=base_triples,
                                              callback=callback)

    def stream_unregister(self, qid: int) -> None:
        self.stream_context().unregister(qid)

    def stream_poll(self, qid: int, since_epoch: int = -1) -> list:
        """Read a standing query's append-only result deltas."""
        return self.stream_context().poll(qid, since_epoch)

    def stream_prune(self, qid: int, upto_epoch: int) -> int:
        """Free a standing query's consumed sink history behind a cursor."""
        return self.stream_context().prune(qid, upto_epoch)

    def stream_feed(self, triples, ts=None):
        """Commit one triple batch as the next stream epoch; standing
        queries are incrementally evaluated before this returns. Device
        caches restage lazily via the store version bump, and compiled
        distributed chains are re-specialized like dynamic_load_data."""
        rec = self.stream_context().feed(triples, ts=ts)
        if self.dist is not None and self.dist.sstore.check_version():
            self._fn_cache_clear()
        self._plan_cache.clear()  # stream commit: same contract as load -d
        return rec

    def _fn_cache_clear(self) -> None:
        cache = getattr(self.dist, "_fn_cache", None)
        if cache is not None:
            cache.clear()

    def gstore_check(self, index_check: bool = True, normal_check: bool = True) -> int:
        from wukong_tpu.store.checker import check_partition

        errors = check_partition(self.g, index_check, normal_check)
        for e in errors[:20]:
            log_error(f"gsck: {e}")
        log_info(f"gsck: {'PASS' if not errors else f'{len(errors)} violations'}")
        return len(errors)
