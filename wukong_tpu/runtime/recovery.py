"""Recovery manager: crash-consistent checkpoint/restore + shard healing.

The missing rung between PR 1's "degrade" and production: this module turns
degraded shards back into healthy ones and restarts back into the exact
acknowledged state.

- :meth:`RecoveryManager.checkpoint` — write one atomic checkpoint bundle:
  every primary partition (base + materialized dynamic deltas, versioned +
  checksummed via store/persist.py) plus the stream registry/window state,
  under a manifest recording the WAL high-water mark; then truncate WAL
  segments the checkpoint fully covers.
- :meth:`RecoveryManager.recover` — boot-time restore: load the newest
  valid checkpoint into the existing store objects IN PLACE, re-clone
  replicas, then replay the WAL tail through the normal mutation paths
  (suppressed re-logging) to a byte-identical store. A mid-epoch crash
  replays to completion; a torn WAL tail (the unacknowledged batch) is
  dropped — exactly the acknowledged-write contract.
- :meth:`RecoveryManager.heal_once` / :meth:`start` — runtime healing: the
  watcher observes ``failover_shards`` / ``degraded_shards`` / tripped
  breakers on the sharded store and rebuilds the failed primary in the
  background (from its replica, else from checkpoint+WAL), then promotes
  it and closes the breaker. Rebuilds ride the engine pool's ``rebuild``
  lane when a pool is running, so healing soaks idle capacity instead of
  displacing interactive queries.

Consistency note: checkpoint serialization holds the WAL *mutation lock*
(store/wal.py), so every batch commit is either fully inside the bundle
(seq <= the manifest's ``wal_seq``) or fully after it (replayed on
restore) — never half-captured. Writes pause for the checkpoint window;
reads are unaffected. Replay is at-least-once: an epoch whose commit
failed after its WAL append (a "ghost") re-applies at its recorded epoch
number alongside the acknowledged one — unacknowledged writes may appear,
acknowledged writes are never lost.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import time
import zlib

from wukong_tpu.analysis.lockdep import make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.events import emit_event
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.trace import trace_event
from wukong_tpu.store.persist import (
    adopt_gstore,
    checkpoint_part_path,
    load_gstore,
    save_gstore,
)
from wukong_tpu.store.wal import active_wal
from wukong_tpu.utils.errors import (
    CheckpointCorrupt,
    ErrorCode,
    WukongError,
)
from wukong_tpu.utils.logger import log_error, log_info, log_warn

MANIFEST_VERSION = (1, 0)
HEAL_BACKOFF_S = 2.0  # min spacing between rebuild attempts per shard
# checkpoints retained on disk. The WAL is truncated behind the OLDEST
# retained bundle, not the newest — recover() falls back to an older
# bundle when the newest is corrupt, and that fallback is only sound if
# the older bundle's WAL tail still exists.
CKPT_RETAIN = 2

_M_CKPTS = get_registry().counter(
    "wukong_checkpoint_writes_total", "Checkpoints written")
_M_RESTORES = get_registry().counter(
    "wukong_recovery_restores_total", "Checkpoint restores completed")
_M_REPLAYED = get_registry().counter(
    "wukong_recovery_replayed_total", "WAL records re-applied by recovery",
    labels=("kind",))


class RebuildJob:
    """A background shard rebuild riding the engine pool's ``rebuild``
    lane (scheduler.py): fire-and-forget like a fused batch — ``run`` does
    the work, ``fail_all`` absorbs pool-death so nothing strands."""

    def __init__(self, fn, label: str = ""):
        self._fn = fn
        self.label = label
        self.done = threading.Event()

    def run(self, _engine) -> None:
        try:
            self._fn()
        finally:
            self.done.set()

    def fail_all(self, exc) -> None:
        log_warn(f"rebuild job {self.label} not executed: {exc!r}")
        self.done.set()


class RecoveryManager:
    """One process's fault-tolerance coordinator.

    ``stores`` are the checkpointed primaries (host partition first, then
    the distributed shards); ``stream`` is the StreamContext whose registry
    rides the checkpoint; ``sstore`` is the ShardedDeviceStore watched for
    failed shards; ``pool`` is a zero-arg callable returning the engine
    pool (or None) for background rebuilds; ``on_change`` runs after any
    restore/rebuild so the owner can drop derived caches (compiled chains,
    plan cache, stream insert-target lists).
    """

    def __init__(self, stores, stream=None, sstore=None,
                 ckpt_dir: str | None = None, pool=None, on_change=None):
        # ``stores`` may be a zero-arg callable returning the CURRENT
        # primaries: rebuild_shard replaces store objects in the sharded
        # store's list, and a frozen snapshot here would keep checkpointing
        # (and fanning mutations into) the dead primary after a heal
        self._stores_src = stores
        self.stream = stream
        self.sstore = sstore
        # an explicit ckpt_dir pins; otherwise the runtime-mutable knob is
        # read at use time (the console can set it after the proxy booted)
        self._ckpt_dir_override = ckpt_dir
        self.pool = pool or (lambda: None)
        self.on_change = on_change
        # heal bookkeeping is shared between the background watcher, the
        # console/drill thread, and the pool engine running a RebuildJob —
        # the claim (inflight check + backoff check + attempt stamp) must
        # be one atomic step or two sweeps double-queue a shard's rebuild
        self._heal_lock = make_lock("recovery.heal")
        self._heal_attempts: dict[int, float] = {}  # guarded by: _heal_lock
        # shards with a rebuild queued/running on the pool's rebuild lane:
        # the lane drains only when every other lane is empty, so without
        # this the watcher would enqueue a duplicate job per sweep while
        # one waits out a busy pool
        self._heal_inflight: set[int] = set()  # guarded by: _heal_lock
        self._lock = make_lock("recovery.ckpt")
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []  # lock-free: start()/stop() are operator-thread only

    @property
    def stores(self) -> list:
        src = self._stores_src
        return list(src() if callable(src) else src)

    @property
    def ckpt_dir(self) -> str:
        return (self._ckpt_dir_override if self._ckpt_dir_override is not None
                else Global.checkpoint_dir)

    # ------------------------------------------------------------------
    # checkpoint side
    # ------------------------------------------------------------------
    def _mutation_targets(self) -> list:
        """The full insert fan-out: primaries plus every live replica —
        WAL replay must mirror writes exactly like the live path does."""
        targets = list(self.stores)
        if self.sstore is not None:
            targets += self.sstore.replica_stores()
        return targets

    def checkpoint(self) -> str:
        """Write one atomic checkpoint bundle; returns its path. The
        ``checkpoint.write`` fault site fires before any bytes land."""
        from wukong_tpu.runtime import faults

        if not self.ckpt_dir:
            raise WukongError(ErrorCode.FILE_NOT_FOUND,
                              "checkpoint_dir is not configured")
        faults.site("checkpoint.write")
        from wukong_tpu.store.wal import mutation_lock

        with self._lock, mutation_lock():
            # the mutation lock excludes in-flight batch commits for the
            # serialization window: every mutation is either fully inside
            # this bundle (seq <= wal_seq) or fully after it (replayed on
            # restore) — never half-captured. Writes pause for the
            # checkpoint duration; reads are unaffected.
            os.makedirs(self.ckpt_dir, exist_ok=True)
            n = self._next_index()
            final = os.path.join(self.ckpt_dir, f"ckpt-{n:06d}")
            tmp = final + ".tmp"
            os.makedirs(tmp, exist_ok=True)
            wal = active_wal()
            wal_seq = (wal.next_seq - 1) if wal is not None else -1
            t0 = time.monotonic()
            parts = []
            ckpt_bytes = 0
            for idx, g in enumerate(self.stores):
                ppath = checkpoint_part_path(tmp, idx)
                save_gstore(g, ppath)
                nbytes = os.path.getsize(ppath)
                ckpt_bytes += nbytes
                parts.append({"sid": int(g.sid),
                              "num_workers": int(g.num_workers),
                              "bytes": int(nbytes)})
                # the placement ledger's predicted-move-bytes source:
                # each DISTRIBUTED shard's measured on-disk size (the
                # host partition spans every shard — recording it under
                # its sid would overwrite shard 0's real size)
                if (self.sstore is None
                        or int(g.num_workers) == self.sstore.D):
                    from wukong_tpu.obs.placement import get_lineage

                    get_lineage().note_checkpoint(int(g.sid), nbytes)
            man = {"format": list(MANIFEST_VERSION), "wal_seq": int(wal_seq),
                   "parts": parts, "stream": False, "epoch": 0}
            if self.stream is not None:
                state = {"registry": self.stream.continuous.export_state(),
                         "epoch": int(self.stream.ingestor.epoch)}
                blob = pickle.dumps(state,
                                    protocol=pickle.HIGHEST_PROTOCOL)
                with open(os.path.join(tmp, "stream.pkl"), "wb") as f:
                    f.write(blob)
                man["stream"] = True
                man["stream_crc"] = zlib.crc32(blob)
                man["epoch"] = state["epoch"]
            with open(os.path.join(tmp, "MANIFEST.json"), "w") as f:
                json.dump(man, f)
            os.rename(tmp, final)  # atomic publish: no torn checkpoints
            self._retire_old_checkpoints(wal)
            _M_CKPTS.inc()
            trace_event("checkpoint.write", path=final, wal_seq=wal_seq,
                        parts=len(parts))
            emit_event("checkpoint.write", path=final, wal_seq=wal_seq,
                       parts=len(parts), bytes=int(ckpt_bytes))
            log_info(f"checkpoint {final} written in "
                     f"{time.monotonic() - t0:.2f}s "
                     f"({len(parts)} part(s), wal_seq={wal_seq})")
            return final

    def _retire_old_checkpoints(self, wal) -> None:
        """Keep the newest CKPT_RETAIN bundles, drop the rest, and
        truncate the WAL behind the oldest retained bundle (every
        retained bundle keeps its full replay tail)."""
        import shutil

        found = list(self._checkpoints())  # newest first
        for path, _man in found[CKPT_RETAIN:]:
            shutil.rmtree(path, ignore_errors=True)
        retained = found[:CKPT_RETAIN]
        if wal is not None and retained:
            wal.truncate_upto(min(int(m["wal_seq"]) for _p, m in retained))

    def _next_index(self) -> int:
        idxs = [int(name[5:]) for name in os.listdir(self.ckpt_dir)
                if name.startswith("ckpt-") and name[5:].isdigit()]
        return (max(idxs) + 1) if idxs else 1

    def _checkpoints(self):
        """Yield (path, manifest) of checkpoint candidates, newest first;
        invalid ones (missing/corrupt manifest, newer-major format) are
        skipped with a warning so one bad bundle never blocks recovery
        from an older one."""
        if not self.ckpt_dir or not os.path.isdir(self.ckpt_dir):
            return
        names = sorted((n for n in os.listdir(self.ckpt_dir)
                        if n.startswith("ckpt-") and n[5:].isdigit()),
                       reverse=True)
        for name in names:
            path = os.path.join(self.ckpt_dir, name)
            try:
                with open(os.path.join(path, "MANIFEST.json")) as f:
                    man = json.load(f)
                if int(man["format"][0]) > MANIFEST_VERSION[0]:
                    log_warn(f"checkpoint {path}: manifest format "
                             f"{man['format']} is newer than this build; "
                             "skipping")
                    continue
                yield path, man
            except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
                log_warn(f"checkpoint {path}: unreadable manifest ({e}); "
                         "skipping")

    def newest_checkpoint(self) -> tuple[str, dict] | None:
        return next(self._checkpoints(), None)

    # ------------------------------------------------------------------
    # restore side
    # ------------------------------------------------------------------
    def recover(self) -> dict:
        """Boot-time restore: newest checkpoint into the live store
        objects, replicas re-cloned, stream registry restored, WAL tail
        replayed through the normal mutation paths. Returns stats."""
        from wukong_tpu.obs import get_recorder, maybe_start_trace
        from wukong_tpu.obs.trace import activate

        trace = maybe_start_trace(kind="recovery")
        stats = {"checkpoint": None, "restored_parts": 0,
                 "replayed": {"insert": 0, "epoch": 0, "vector": 0},
                 "epoch": 0, "standing_queries": 0}
        with activate(trace):
            self._recover_impl(stats, trace)
        if trace is not None:
            get_recorder().on_complete(trace)
        return stats

    def _load_bundle(self, path: str, man: dict) -> dict:
        """Read + validate EVERY payload of one checkpoint without
        mutating any live state — a corrupt part file must surface here,
        where falling back to an older checkpoint is still possible, never
        halfway through an in-place restore."""
        targets = self.stores
        if len(man["parts"]) != len(targets):
            # a topology change (e.g. single-host checkpoint restored into
            # a --dist boot) silently leaving some shards at base state is
            # worse than refusing: the fallback loop tries older bundles,
            # and failing that the full WAL replays onto base consistently
            raise CheckpointCorrupt(
                f"bundle has {len(man['parts'])} parts but this process "
                f"has {len(targets)} stores", path=path)
        parts = []
        for idx, part in enumerate(man["parts"]):
            g = targets[idx]
            g2 = load_gstore(checkpoint_part_path(path, idx))
            if g2.sid != g.sid or g2.num_workers != g.num_workers:
                raise CheckpointCorrupt(
                    f"part {idx} is partition {g2.sid}/{g2.num_workers}, "
                    f"target is {g.sid}/{g.num_workers}", path=path)
            parts.append((g, g2))
        state = None
        if man.get("stream") and self.stream is not None:
            with open(os.path.join(path, "stream.pkl"), "rb") as f:
                blob = f.read()
            if zlib.crc32(blob) != man.get("stream_crc"):
                raise CheckpointCorrupt("stream state checksum mismatch",
                                        path=path)
            state = pickle.loads(blob)
        return {"path": path, "man": man, "parts": parts, "stream": state}

    def _recover_impl(self, stats: dict, trace) -> None:
        bundle = None
        for path, man in self._checkpoints():
            try:
                bundle = self._load_bundle(path, man)
                break
            except (WukongError, OSError) as e:
                log_warn(f"checkpoint {path} unusable ({e}); trying an "
                         "older one")
        after_seq = -1
        if bundle is not None:
            path, man = bundle["path"], bundle["man"]
            sp = trace.start_span("recovery.restore",
                                  path=path) if trace else None
            for g, g2 in bundle["parts"]:  # validated: cannot fail partway
                adopt_gstore(g, g2)
            if self.sstore is not None and self.sstore.replicas:
                self.sstore.refresh_replicas()
            if bundle["stream"] is not None:
                state = bundle["stream"]
                self.stream.continuous.import_state(state["registry"])
                self.stream.ingestor.epoch = int(state["epoch"])
                stats["standing_queries"] = len(
                    state["registry"]["queries"])
            after_seq = int(man["wal_seq"])
            stats["checkpoint"] = path
            stats["restored_parts"] = len(man["parts"])
            if sp is not None:
                trace.end_span(sp, parts=len(man["parts"]),
                               wal_seq=after_seq)
            _M_RESTORES.inc()
            emit_event("recovery.restore", path=path,
                       parts=len(man["parts"]), wal_seq=after_seq)
        # the stream context's insert fan-out list may reference replicas
        # that refresh_replicas just replaced — rebind before replay
        if self.stream is not None:
            self.stream.ingestor.stores = self._mutation_targets()
        self._replay_wal(after_seq, stats, trace)
        # cache-coherence telemetry (obs/reuse.py): a restore force-bumps
        # every partition's version and replaces array contents wholesale
        # — a version-keyed result cache purges conservatively (the
        # restored world's versions are not comparable to the cached
        # keys'), and the edge lands as one cache.invalidate event
        from wukong_tpu.obs.reuse import maybe_note_invalidation

        maybe_note_invalidation("restore", version=None,
                                checkpoint=stats["checkpoint"])
        # the serving plane's actuator edge (wukong_tpu/serve/): the
        # restored world's version counters are not comparable to the
        # cached keys' — the real result cache purges conservatively.
        # One knob check when the cache is off.
        from wukong_tpu.serve import notify_mutation

        notify_mutation("restore")
        if self.on_change is not None:
            self.on_change()
        log_info(f"recovery: checkpoint={stats['checkpoint']} "
                 f"replayed={stats['replayed']} "
                 f"epoch={self._current_epoch()}")
        stats["epoch"] = self._current_epoch()

    def _current_epoch(self) -> int:
        return self.stream.ingestor.epoch if self.stream is not None else 0

    def _replay_wal(self, after_seq: int, stats: dict, trace) -> None:
        from wukong_tpu.store.dynamic import insert_triples

        wal = active_wal()
        if wal is None:
            return
        sp = trace.start_span("recovery.replay",
                              after_seq=after_seq) if trace else None
        prev_seq = after_seq
        with wal.suppress():
            for rec in wal.replay(after_seq=after_seq):
                # seqs are contiguous by construction: a gap means the
                # records between were truncated away (e.g. behind a
                # checkpoint that is NOT the one we restored) — applying
                # the rest would silently skip acknowledged mutations
                if rec.seq != prev_seq + 1:
                    raise CheckpointCorrupt(
                        f"WAL gap: record {rec.seq} follows {prev_seq} — "
                        "the tail for this checkpoint was truncated",
                        path=wal.dir)
                prev_seq = rec.seq
                if rec.kind == "epoch" and self.stream is not None:
                    # re-commit at the RECORDED epoch number. Every record
                    # with seq > wal_seq is fully outside the checkpoint
                    # (the mutation lock guarantees it), so none may be
                    # skipped; forcing the number keeps ghost records —
                    # an epoch whose commit failed after its append — from
                    # shifting later acknowledged epochs (a ghost replays
                    # at the same number the acknowledged one reuses:
                    # at-least-once, unacknowledged-may-appear)
                    ep = int(rec.payload.get("epoch",
                                             self.stream.ingestor.epoch + 1))
                    self.stream.ingestor.epoch = ep - 1
                    self.stream.ingestor.commit_epoch(
                        rec.payload["triples"], ts=rec.payload.get("ts"))
                elif rec.kind == "vector":
                    # embedding mutation: re-apply into every target's
                    # vstore (attaches one if the checkpoint predates the
                    # vector plane); version numbering re-derives, same as
                    # graph versions do
                    from wukong_tpu.vector.vstore import apply_vector_record

                    for g in self._mutation_targets():
                        apply_vector_record(g, rec.payload)
                else:
                    # plain insert — or an epoch with no stream context to
                    # re-evaluate it: the data still must not be lost
                    for g in self._mutation_targets():
                        insert_triples(g, rec.payload["triples"],
                                       dedup=rec.payload["dedup"],
                                       check_ids=False)
                kind = rec.kind if rec.kind in ("epoch", "vector") \
                    else "insert"
                stats["replayed"][kind] += 1
                _M_REPLAYED.labels(kind=kind).inc()
        if sp is not None:
            trace.end_span(sp, **stats["replayed"])
        if sum(stats["replayed"].values()):
            emit_event("recovery.replay", after_seq=after_seq,
                       **stats["replayed"])

    # ------------------------------------------------------------------
    # runtime healing
    # ------------------------------------------------------------------
    def sick_shards(self) -> list[int]:
        if self.sstore is None:
            return []
        ss = self.sstore
        sick = set(ss.failover_shards) | set(ss.degraded_shards)
        sick |= {k for k in ss.breaker.tripped_keys() if isinstance(k, int)}
        return sorted(sick)

    def heal_once(self, background: bool = False,
                  force: bool = False) -> list[int]:
        """One healing sweep: rebuild + promote every sick shard (rate
        limited per shard by HEAL_BACKOFF_S unless ``force`` — the
        explicit console/drill path must not be skipped just because the
        background watcher attempted recently). With ``background`` and a
        running pool, rebuilds ride the pool's rebuild lane; otherwise
        they run inline. Returns the shards healed (inline mode)."""
        healed = []
        now = time.monotonic()
        for i in self.sick_shards():
            pool = self.pool() if background else None
            with self._heal_lock:
                # the whole claim is one atomic step: inflight check,
                # backoff check, attempt stamp, and (background mode) the
                # inflight mark — a concurrent sweep sees either nothing
                # or a fully-claimed shard, never a half-claim
                if i in self._heal_inflight:
                    continue  # one queued/running rebuild per shard, ever
                if not force and now - self._heal_attempts.get(
                        i, -1e18) < HEAL_BACKOFF_S:
                    continue
                self._heal_attempts[i] = now
                if pool is not None:
                    self._heal_inflight.add(i)
            if pool is not None:
                def _job(i=i):
                    try:
                        self._rebuild_shard(i)
                    finally:
                        with self._heal_lock:
                            self._heal_inflight.discard(i)

                job = RebuildJob(_job, label=f"shard-{i}")
                if pool.submit(job, lane="rebuild") == -1 and job.done.is_set():
                    # dead pool settled it via fail_all without running
                    with self._heal_lock:
                        self._heal_inflight.discard(i)
            elif self._rebuild_shard(i):
                healed.append(i)
        return healed

    def _rebuild_shard(self, i: int) -> bool:
        """Rebuild shard ``i``'s primary from its replica, else from the
        newest checkpoint + WAL tail; promote on success. Runs under the
        WAL mutation lock: a batch committing mid-rebuild would otherwise
        land only in the OLD store objects (or tear the replica clone),
        and the promoted primary would silently miss it."""
        from wukong_tpu.store.wal import mutation_lock

        with mutation_lock():
            return self._rebuild_shard_locked(i)

    def _rebuild_shard_locked(self, i: int) -> bool:
        from wukong_tpu.store.dynamic import insert_triples

        ss = self.sstore
        if ss is None:
            return False
        if ss.rebuild_shard(i, source="replica"):
            log_info(f"shard {i} rebuilt from replica and promoted")
            emit_event("shard.heal", shard=int(i), source="replica")
            self._after_rebuild()
            return True
        found = self.newest_checkpoint()
        if found is None:
            log_warn(f"shard {i} has no replica and no checkpoint — "
                     "cannot rebuild")
            return False
        path, man = found
        idx = next((j for j, p in enumerate(man["parts"])
                    if p["sid"] == i and p["num_workers"] == ss.D), None)
        if idx is None:
            log_warn(f"shard {i}: no matching partition in {path}")
            return False
        try:
            g_new = load_gstore(checkpoint_part_path(path, idx))
        except WukongError as e:
            log_error(f"shard {i}: checkpoint partition unreadable: {e}")
            return False
        wal = active_wal()
        if wal is not None:
            # direct per-partition inserts: no WAL hook fires here, so no
            # suppress() — holding the process-wide suppression on this
            # background thread would let concurrent LIVE commits skip
            # their WAL appends (acknowledged-but-unlogged writes)
            from wukong_tpu.vector.vstore import apply_vector_record

            for rec in wal.replay(after_seq=int(man["wal_seq"])):
                if rec.kind == "vector":
                    apply_vector_record(g_new, rec.payload)
                else:
                    insert_triples(g_new, rec.payload["triples"],
                                   dedup=rec.payload["dedup"],
                                   check_ids=False)
        ss.rebuild_shard(i, store=g_new, source="checkpoint")
        log_info(f"shard {i} rebuilt from {path} + WAL tail and promoted")
        emit_event("shard.heal", shard=int(i), source="checkpoint")
        self._after_rebuild()
        return True

    def _after_rebuild(self) -> None:
        # a promoted primary is a NEW object: rebind the stream context's
        # insert fan-out and let the owner drop derived caches
        if self.stream is not None:
            self.stream.ingestor.stores = self._mutation_targets()
        if self.on_change is not None:
            self.on_change()

    # ------------------------------------------------------------------
    # background threads
    # ------------------------------------------------------------------
    def start(self, watch_interval_s: float = 0.5) -> None:
        """Launch the heal watcher (when a sharded store is attached) and
        the periodic checkpointer (when checkpoint_interval_s asks for
        one). Idempotent; both threads are daemons."""
        if self._threads:
            return
        if self.sstore is not None:
            t = threading.Thread(target=self._watch_loop,
                                 args=(watch_interval_s,), daemon=True,
                                 name="recovery-watcher")
            t.start()
            self._threads.append(t)
        if Global.checkpoint_interval_s > 0 and self.ckpt_dir:
            t = threading.Thread(target=self._checkpoint_loop, daemon=True,
                                 name="recovery-checkpointer")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads = []
        self._stop = threading.Event()

    def _watch_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                if self.sick_shards():
                    self.heal_once(background=True)
            except Exception as e:  # the watcher must never die silently
                log_error(f"recovery watcher: {e!r}")

    def _checkpoint_loop(self) -> None:
        while not self._stop.wait(max(Global.checkpoint_interval_s, 1)):
            try:
                self.checkpoint()
            except Exception as e:
                log_error(f"periodic checkpoint failed: {e!r}")
