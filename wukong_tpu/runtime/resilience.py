"""Resilience layer: deadlines, work budgets, retry/backoff, circuit breakers.

The reference executes every query optimistically — a hung fetch stalls an
engine thread forever and a result blowup OOMs the process (its only failure
handling is turning engine exceptions into a reply status). This module adds
the machinery GPU-side Datalog engines use to survive instead:

- :class:`Deadline` — per-query wall-clock limit + intermediate-row work
  budget, carried on the query (``q.deadline``) and checked at every BGP
  step / chain attempt. Expiry raises structured ``QueryTimeout`` /
  ``BudgetExceeded`` from utils/errors.py.
- :func:`retry_call` — exponential backoff with decorrelated jitter around
  transient failure points (shard fetches, HDFS reads, chain dispatch).
- :class:`CircuitBreaker` — per-key consecutive-failure breaker with a
  half-open probe after a cooldown, so a persistently-down shard is routed
  around instead of re-paying its timeout on every query.
- :func:`mark_partial` — graceful degradation: tag the reply incomplete
  (``result.complete = False``) with the dropped patterns, keeping the rows
  produced so far, instead of crashing the engine pool.

All clocks/sleeps are injectable so the chaos suite replays schedules
deterministically (tests/test_chaos.py).
"""

from __future__ import annotations

import random
import threading
import time

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.trace import trace_event
from wukong_tpu.utils.errors import (
    BudgetExceeded,
    QueryTimeout,
    RetryExhausted,
    ShardUnavailable,
)

# observability: retry attempts and breaker trips publish into the shared
# registry and, when a trace is ambient, appear as span events — the chaos
# suite asserts a faulted query's trace carries them (tests/test_obs.py)
_M_RETRIES = get_registry().counter(
    "wukong_retry_attempts_total",
    "Failed attempts that entered retry backoff", labels=("site",))
_M_BREAKER_TRIPS = get_registry().counter(
    "wukong_breaker_trips_total",
    "Circuit breaker open/reopen transitions", labels=("key",))

# breaker state locks are innermost by design: holding one while calling
# into any other locked subsystem (tracing, metrics push with tracked
# locks, the WAL) is an ordering inversion lockdep flags
declare_leaf("breaker.state")


def _emit_breaker_event(kind: str, key) -> None:
    """Cluster-event journal hook for breaker transitions (obs/events.py):
    per-shard breaker keys carry the shard as a correlation key — a
    (shard, host) replica key correlates on the shard too. Fires OUTSIDE
    the breaker lock like every other hook here."""
    from wukong_tpu.obs.events import emit_event

    shard = key if isinstance(key, int) else (
        key[0] if isinstance(key, tuple) and key
        and isinstance(key[0], int) else None)
    emit_event(kind, shard=shard, key=str(key))


# serializes Deadline.charge_rows across threads sharing one deadline
# (heavy split slices, distributed join partitions); declared leaf —
# nothing is ever acquired under it
declare_leaf("resilience.charge")
_CHARGE_LOCK = make_lock("resilience.charge")


class Deadline:
    """Wall-clock deadline + intermediate-row budget for one query."""

    __slots__ = ("_clock", "_expires_at", "budget_rows", "rows_charged")

    def __init__(self, timeout_ms: int = 0, budget_rows: int = 0,
                 clock=time.monotonic):
        self._clock = clock
        self._expires_at = (clock() + timeout_ms / 1e3
                            if timeout_ms and timeout_ms > 0 else None)
        self.budget_rows = int(budget_rows or 0)
        self.rows_charged = 0

    @classmethod
    def from_config(cls) -> "Deadline | None":
        """A Deadline per the Global knobs, or None when both are off."""
        if Global.query_deadline_ms <= 0 and Global.query_budget_rows <= 0:
            return None
        return cls(Global.query_deadline_ms, Global.query_budget_rows)

    def expired(self) -> bool:
        return self._expires_at is not None and self._clock() >= self._expires_at

    def remaining_s(self) -> float | None:
        if self._expires_at is None:
            return None
        return max(self._expires_at - self._clock(), 0.0)

    def check(self, where: str = "") -> None:
        if self.expired():
            raise QueryTimeout(where)

    def charge_rows(self, n: int, where: str = "") -> None:
        # module-level lock, not per-instance: a Deadline may be SHARED
        # by concurrent chargers (heavy-lane split slices, distributed
        # join partitions), and a bare += is a lost-update race that
        # under-enforces the budget; a lock attribute would make queries
        # carrying deadlines undeepcopyable, so the (nanoseconds-held)
        # process-wide lock serializes instead
        with _CHARGE_LOCK:
            self.rows_charged += int(n)
            total = self.rows_charged
        if self.budget_rows and total > self.budget_rows:
            raise BudgetExceeded(
                f"{total:,} rows > budget "
                f"{self.budget_rows:,}" + (f" at {where}" if where else ""))


def check_query(q, where: str = "") -> None:
    """Deadline check for a query that may or may not carry one."""
    dl = getattr(q, "deadline", None)
    if dl is not None:
        dl.check(where)


def charge_query(q, rows: int, where: str = "") -> None:
    """Charge a step's output rows against the query's work budget."""
    dl = getattr(q, "deadline", None)
    if dl is not None:
        dl.charge_rows(rows, where)


def mark_partial(q, exc) -> None:
    """Graceful degradation on deadline/budget expiry: keep the rows
    produced so far, record what was dropped, surface the structured code."""
    res = q.result
    res.status_code = exc.code
    res.complete = False
    dropped = [repr(p) for p in q.pattern_group.patterns[q.pattern_step:]]
    if q.pattern_group.unions and not q.union_done:
        dropped.append(f"UNION x{len(q.pattern_group.unions)}")
    dropped += [f"OPTIONAL#{i}" for i in
                range(q.optional_step, len(q.pattern_group.optional))]
    res.dropped_patterns = dropped
    if not Global.enable_partial_results:
        import numpy as np

        res.table = np.empty((0, res.col_num), dtype=np.int64)
        res.nrows = 0


# ---------------------------------------------------------------------------
# retry with exponential backoff + jitter
# ---------------------------------------------------------------------------

_retry_rng = random.Random()  # jitter source; tests inject their own


def retry_call(fn, *, site: str = "", attempts: int | None = None,
               base_ms: float | None = None, max_ms: float | None = None,
               retry_on: tuple = (), breaker: "CircuitBreaker | None" = None,
               key=None, rng: random.Random | None = None, sleep=time.sleep,
               deadline: Deadline | None = None):
    """Call ``fn()``; on an exception in ``retry_on`` back off and retry.

    Backoff is exponential with equal jitter: half the window fixed, half
    uniform, so synchronized retry storms decorrelate. A breaker (keyed by
    ``key``) short-circuits calls while open and records outcomes; a
    deadline bounds the total retry time. Non-retryable exceptions (and
    faults.ShardDown) propagate immediately. Exhaustion raises
    RetryExhausted carrying the last exception.
    """
    from wukong_tpu.runtime.faults import TransientFault

    attempts = Global.retry_max_attempts if attempts is None else attempts
    base_ms = Global.retry_base_ms if base_ms is None else base_ms
    max_ms = Global.retry_max_ms if max_ms is None else max_ms
    retry_on = tuple(retry_on) or (TransientFault, OSError)
    rng = rng or _retry_rng
    attempts = max(int(attempts), 1)
    last: BaseException | None = None
    for i in range(attempts):
        if breaker is not None and not breaker.allow(key):
            trace_event("breaker.open", site=site, key=str(key))
            raise ShardUnavailable(
                f"circuit open for {key!r} at {site}", shard=key
                if isinstance(key, int) else None)
        # past this point an admitted half-open trial MUST be settled on
        # every exit path (success/failure/abort) or the breaker wedges with
        # its trial slot held forever
        if deadline is not None:
            try:
                deadline.check(site)
            except BaseException:
                if breaker is not None:
                    # cancelled before dispatch: release the trial slot
                    # without judging the shard either way
                    breaker.record_abort(key)
                raise
        try:
            out = fn()
        except retry_on as e:
            last = e
            trace_event("retry", site=site, attempt=i, error=repr(e))
            _M_RETRIES.labels(site=site or "?").inc()
            if breaker is not None:
                breaker.record_failure(key)
            if i == attempts - 1:
                break
            window = min(base_ms * (2 ** i), max_ms) / 1e3
            delay = window / 2 + rng.random() * window / 2
            if deadline is not None:
                rem = deadline.remaining_s()
                if rem is not None and delay >= rem:
                    raise QueryTimeout(
                        f"deadline inside retry backoff at {site}") from e
            sleep(delay)
        except BaseException:
            # non-retryable failure (ShardDown, a store bug, ...): the call
            # did run and did fail — count it so persistent faults trip the
            # breaker, and so an admitted half-open trial is settled
            if breaker is not None:
                breaker.record_failure(key)
            raise
        else:
            if breaker is not None:
                breaker.record_success(key)
            return out
    raise RetryExhausted(
        f"{attempts} attempts failed at {site}: {last!r}", last=last)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Per-key consecutive-failure circuit breaker.

    closed -> (threshold consecutive failures) -> open -> (cooldown) ->
    half-open: one trial call is allowed; success closes the breaker,
    failure reopens it for another cooldown. Thread-safe — the engine pool
    and the proxy share one instance per subsystem.
    """

    def __init__(self, threshold: int | None = None,
                 cooldown_ms: float | None = None, clock=time.monotonic):
        self.threshold = (Global.breaker_threshold
                          if threshold is None else int(threshold))
        self.cooldown_s = (Global.breaker_cooldown_ms
                           if cooldown_ms is None else cooldown_ms) / 1e3
        self._clock = clock
        # a declared lockdep LEAF: this class deliberately publishes its
        # trace events / metrics OUTSIDE the lock ("hooks must not hold
        # breaker state") — the checker now enforces that discipline
        # instead of a comment merely requesting it
        self._lock = make_lock("breaker.state")
        # key -> [consecutive_failures, opened_at | None, half_open_inflight]
        self._st: dict = {}  # guarded by: _lock
        # key -> clock time of the most recent open/reopen (trip); survives
        # the breaker closing again, so operators can see flap history
        self._last_trip: dict = {}  # guarded by: _lock

    def _slot(self, key):  # caller holds: _lock
        return self._st.setdefault(key, [0, None, False])

    def _state_of(self, slot, now: float) -> str:
        """Classify one slot; caller holds the lock (state machine lives
        here once — state() and snapshot() must never disagree)."""
        fails, opened_at, half = slot
        if opened_at is None:
            return "closed"
        if half or now - opened_at >= self.cooldown_s:
            return "half_open"
        return "open"

    def state(self, key) -> str:
        with self._lock:
            return self._state_of(self._slot(key), self._clock())

    def allow(self, key) -> bool:
        """True when a call may proceed. The transition to half-open admits
        ONE trial at a time; concurrent callers keep getting False until
        the trial reports an outcome."""
        with self._lock:
            slot = self._slot(key)
            fails, opened_at, half = slot
            if opened_at is None:
                return True
            if half:
                return False  # a trial is already in flight
            if self._clock() - opened_at >= self.cooldown_s:
                slot[2] = True  # admit the half-open trial
                return True
            return False

    def record_success(self, key) -> None:
        with self._lock:
            was_open = self._st.get(key, [0, None, False])[1] is not None
            self._st[key] = [0, None, False]
        if was_open:  # a half-open trial just recovered the key
            trace_event("breaker.close", key=str(key))
            _emit_breaker_event("breaker.close", key)

    def record_abort(self, key) -> None:
        """The admitted call never dispatched (e.g. deadline expiry between
        allow() and the call): release a held half-open trial slot without
        judging the shard either way. No-op for closed keys."""
        with self._lock:
            self._slot(key)[2] = False

    def record_failure(self, key) -> None:
        tripped = False
        with self._lock:
            slot = self._slot(key)
            slot[0] += 1
            if slot[1] is not None:
                # failed half-open trial (or failure while open): reopen
                slot[1] = self._clock()
                slot[2] = False
                self._last_trip[key] = slot[1]
                tripped = True
            elif slot[0] >= self.threshold:
                slot[1] = self._clock()
                slot[2] = False
                self._last_trip[key] = slot[1]
                tripped = True
        if tripped:  # outside the lock: hooks must not hold breaker state
            trace_event("breaker.trip", key=str(key))
            _M_BREAKER_TRIPS.labels(key=str(key)).inc()
            _emit_breaker_event("breaker.trip", key)

    def tripped(self, key) -> bool:
        return self.state(key) != "closed"

    def tripped_keys(self) -> list:
        with self._lock:
            now = self._clock()
            return [k for k, (f, o, h) in self._st.items() if o is not None]

    def snapshot(self) -> dict:
        """Per-key observability view: state, consecutive failures, and age
        of the most recent trip (None = never tripped). The Monitor prints
        this in the rolling throughput report."""
        with self._lock:
            now = self._clock()
            out = {}
            for k, slot in self._st.items():
                trip = self._last_trip.get(k)
                out[k] = {"state": self._state_of(slot, now),
                          "consecutive_failures": slot[0],
                          "last_trip_age_s":
                              (now - trip) if trip is not None else None}
            return out
