"""Host engine pool: per-engine run queues with work stealing.

The reference runs N engine threads per server, each with a private queue,
priority handling for fork-join sub-queries, work stealing from neighbors
("work obliger", pair or ring patterns per Global::stealing_pattern), and an
adaptive busy-poll/snooze loop (core/engine/engine.hpp:78-219). This module
reproduces that runtime structure for the host-side engines: inter-query
parallelism across a thread pool (numpy/JAX release the GIL on the heavy ops),
deque-based queues stolen from the back, and the same pair/ring neighbor
selection.
"""

from __future__ import annotations

import collections
import threading
import time

from wukong_tpu.config import Global
from wukong_tpu.utils.timer import get_usec


class EnginePool:
    def __init__(self, num_engines: int | None = None, make_engine=None):
        """make_engine(tid) -> object with .execute(query) (one per thread,
        mirroring per-thread SPARQLEngine instances)."""
        self.n = num_engines or Global.num_engines
        self.queues = [collections.deque() for _ in range(self.n)]
        self.locks = [threading.Lock() for _ in range(self.n)]
        self._make_engine = make_engine
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._pending = threading.Semaphore(0)
        self._results: dict[int, object] = {}
        self._results_lock = threading.Lock()
        self._next_qid = 0
        self._done = {}
        self._completed = collections.deque()  # finished qids (poll() feed)

    # ------------------------------------------------------------------
    def start(self) -> None:
        for tid in range(self.n):
            t = threading.Thread(target=self._run_engine, args=(tid,),
                                 daemon=True, name=f"engine-{tid}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for _ in self._threads:
            self._pending.release()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()

    # ------------------------------------------------------------------
    def submit(self, query, tid: int | None = None) -> int:
        """Enqueue a query; returns a handle. tid routes like the reference's
        proxy dst engine choice (round-robin default, proxy.hpp:143-160)."""
        with self._results_lock:
            qid = self._next_qid
            self._next_qid += 1
            self._done[qid] = threading.Event()
        t = qid % self.n if tid is None else tid % self.n
        with self.locks[t]:
            self.queues[t].append((qid, query))
        self._pending.release()
        return qid

    def wait(self, qid: int, timeout: float | None = None):
        """Returns the engine's result, or raises TimeoutError (the result
        stays claimable by a later wait — no stranded entries)."""
        if not self._done[qid].wait(timeout):
            raise TimeoutError(f"query {qid} still running")
        with self._results_lock:
            self._done.pop(qid, None)
            try:
                self._completed.remove(qid)
            except ValueError:
                pass
            return self._results.pop(qid, None)

    def poll(self) -> list:
        """Drain finished queries as (qid, result) pairs — the open-loop
        receive side (proxy.hpp tryrecv_reply analogue). A pool user should
        consume completions via EITHER wait() or poll(), not both."""
        out = []
        while True:
            try:
                qid = self._completed.popleft()
            except IndexError:
                break
            with self._results_lock:
                if qid not in self._done:  # already consumed via wait()
                    continue
                self._done.pop(qid)
                out.append((qid, self._results.pop(qid, None)))
        return out

    # ------------------------------------------------------------------
    def _neighbors(self, tid: int) -> list[int]:
        """Stealing pattern (engine.hpp:186-207): 0=pair, 1=ring."""
        if self.n <= 1:
            return []
        if Global.stealing_pattern == 1:  # ring: next engine
            return [(tid + 1) % self.n]
        return [tid ^ 1] if (tid ^ 1) < self.n else []  # pair

    def _pop_work(self, tid: int):
        # own queue first (front)
        with self.locks[tid]:
            if self.queues[tid]:
                return self.queues[tid].popleft()
        # steal from neighbors (back — leave the owner its freshest work)
        for nb in self._neighbors(tid):
            with self.locks[nb]:
                if self.queues[nb]:
                    return self.queues[nb].pop()
        return None

    def _run_engine(self, tid: int) -> None:
        from wukong_tpu.runtime.bind import get_binder

        get_binder().bind_thread(tid)  # no-op unless core binding is enabled
        engine = self._make_engine(tid)
        snooze_us = 10
        while not self._stop.is_set():
            item = self._pop_work(tid)
            if item is None:
                # adaptive snooze (engine.hpp:120-150: busy poll, then
                # exponential 10 -> 80 us relax); semaphore bounds the sleep
                got = self._pending.acquire(timeout=snooze_us / 1e6)
                snooze_us = 10 if got else min(snooze_us * 2, 80)
                continue
            qid, query = item
            try:
                out = engine.execute(query)
            except Exception as e:  # engine errors become the reply
                out = e
            with self._results_lock:
                self._results[qid] = out
                ev = self._done[qid]  # capture: a racing poll() may pop it
            # append BEFORE set(): a wait()er woken by set() must find the
            # qid already in _completed so its remove() never races the append
            self._completed.append(qid)
            ev.set()
