"""Host engine pool: per-engine run queues with work stealing.

The reference runs N engine threads per server, each with a private queue,
priority handling for fork-join sub-queries, work stealing from neighbors
("work obliger", pair or ring patterns per Global::stealing_pattern), and an
adaptive busy-poll/snooze loop (core/engine/engine.hpp:78-219). This module
reproduces that runtime structure for the host-side engines: inter-query
parallelism across a thread pool (numpy/JAX release the GIL on the heavy ops),
deque-based queues stolen from the back, and the same pair/ring neighbor
selection.

Beyond the reference: a shared low-priority *stream lane*
(``submit(q, lane="stream")``) for standing-query delta work
(stream/continuous.py). Engines drain it only after their own queue and
their steal targets are empty, so interactive one-shot queries always go
first and continuous evaluation soaks up the idle capacity — under the same
per-query deadline/budget machinery (expired stream items are shed from the
queue exactly like interactive ones). Stream-lane completions are reserved
for wait() and never returned by poll(), so an open-loop poll() consumer
(the emulator) can share the pool with the stream context without racing
its completions; the one-consumer discipline (wait XOR poll) still applies
among default-lane users.
"""

from __future__ import annotations

import collections
import threading
import time

from wukong_tpu.analysis.lockdep import make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.slo import maybe_note_queue_delay, maybe_note_shed
from wukong_tpu.utils.timer import get_usec

# pool-level observability: submissions/sheds/respawns push counters; queue
# depth is a pull gauge registered per pool (the hot loop never updates it)
_M_SUBMITTED = get_registry().counter(
    "wukong_pool_submitted_total", "Queries submitted to the engine pool",
    labels=("lane",))
_M_SHED = get_registry().counter(
    "wukong_pool_shed_total",
    "Queries shed from the queue with an expired deadline")
_M_RESPAWNS = get_registry().counter(
    "wukong_pool_engine_respawns_total", "Engine-thread crash respawns")

# one registry-level queue-depth gauge summed over every LIVE pool (weakly
# referenced: a stopped, dropped pool reads as gone, never as stale depth)
import weakref  # noqa: E402

_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def _queue_depth() -> int:
    return sum(sum(len(dq) for dq in p.queues) + len(p.stream_queue)
               + len(p.batch_queue) + len(p.heavy_queue)
               + len(p.heavy_slices) + len(p.rebuild_queue)
               + (len(f) if (f := p._fair) is not None else 0)
               for p in list(_POOLS))


get_registry().gauge(
    "wukong_pool_queue_depth",
    "Queries waiting in pool queues (incl. stream/batch/heavy/rebuild lanes)"
).set_function(_queue_depth)


def _lane_depth_series() -> dict:
    """Per-lane queue depth across every live pool — the /top lane view's
    pull source (depth by lane, not just the total)."""
    acc = {"default": 0, "batch": 0, "heavy": 0, "stream": 0, "rebuild": 0}
    for p in list(_POOLS):
        acc["default"] += sum(len(dq) for dq in p.queues)
        acc["batch"] += len(p.batch_queue)
        acc["heavy"] += len(p.heavy_queue) + len(p.heavy_slices)
        acc["stream"] += len(p.stream_queue)
        acc["rebuild"] += len(p.rebuild_queue)
        f = p._fair  # the DRR sub-lane exists only once admission armed
        if f is not None:
            acc["fair"] = acc.get("fair", 0) + len(f)
    return {(k,): v for k, v in acc.items()}


get_registry().gauge(
    "wukong_pool_lane_depth", "Queries waiting per pool lane",
    labels=("lane",)).set_function(_lane_depth_series)


def _pool_utilization() -> float:
    """Busy fraction of live engines across every live pool — an
    ADMISSION_INPUTS signal (obs/slo.py) for item 4's admission control."""
    busy = alive = 0
    for p in list(_POOLS):
        for t in range(p.n):
            if not p._dead[t]:  # unguarded: report-only snapshot, like health()
                alive += 1
                if p._busy_since[t]:
                    busy += 1
    return busy / alive if alive else 0.0


get_registry().gauge(
    "wukong_pool_utilization",
    "Busy fraction of live pool engines").set_function(_pool_utilization)


def dead_engine_count() -> int:
    """Engines declared dead (respawn budget exhausted) across every live
    pool — a /healthz readiness input (obs/httpd.py health_report)."""
    return sum(1 for p in list(_POOLS) for t in range(p.n)
               if p._dead[t])  # unguarded: report-only snapshot, like health()


def _live_engine_count() -> int:
    """Engines NOT declared dead across every live pool — the admission
    plane's derived in-flight capacity base (runtime/admission.py
    ``_inflight_cap``: structural config, not a telemetry signal)."""
    return sum(1 for p in list(_POOLS) for t in range(p.n)
               if not p._dead[t])  # unguarded: report-only snapshot, like health()


class EnginePool:
    # engine-thread crashes (outside the per-query try) respawn up to this
    # many times per tid; past it the engine is declared dead, its queue is
    # redistributed, and routing skips it. The reference has NO failure
    # handling at all (wukong.cpp:252 TODO; a dead pthread strands its ring).
    MAX_RESPAWNS = 3

    # idle relax bounds (ROADMAP follow-up i): the reference busy-polls
    # 10 -> 80us (engine.hpp:120-150), which keeps every idle engine waking
    # 12.5k times/s — on this 2-core container a 4-engine idle pool burned
    # a full core (each timed-semaphore wake costs ~170-500us of CPU here)
    # and doubled co-located serve_query p50 (617us -> 1,230us). The
    # semaphore acquire IS the wake-on-submit event (a submit releases a
    # permit and wakes one sleeper immediately), so a deep cap costs
    # nothing in pickup latency on the submit path; it only bounds the
    # poll cadence for work that arrives via stealing races (an item
    # stranded in a busy non-neighbor's queue). Measured at 20ms: idle
    # burn ~100% -> ~11% of a core, co-located p50 restored to ~baseline
    # (BENCH_SERVE.json idle_backoff).
    IDLE_SNOOZE_MIN_US = 10
    IDLE_SNOOZE_MAX_US = 20000

    def __init__(self, num_engines: int | None = None, make_engine=None):
        """make_engine(tid) -> object with .execute(query) (one per thread,
        mirroring per-thread SPARQLEngine instances)."""
        self.n = num_engines or Global.num_engines
        # per-engine run queues, each guarded by the matching element of
        # `locks` (declared in analysis/guarded.py GUARDED_BY_REGISTRY —
        # per-element guards have no single annotation line)
        self.queues = [collections.deque() for _ in range(self.n)]
        self.locks = [make_lock("pool.queue") for _ in range(self.n)]
        self._make_engine = make_engine
        self._threads: list[threading.Thread | None] = [None] * self.n  # lock-free: start/stop/respawn are operator-or-dying-thread only
        self._stop = threading.Event()
        self._pending = threading.Semaphore(0)
        self._results: dict[int, object] = {}  # guarded by: _results_lock
        self._results_lock = make_lock("pool.results")
        self._next_qid = 0  # guarded by: _results_lock
        self._done = {}  # guarded by: _results_lock
        # finished qids (poll() feed); append-before-set protocol relies
        # on CPython deque append/popleft atomicity
        self._completed = collections.deque()  # lock-free: atomic deque ops, see _fail()
        self._respawns = [0] * self.n  # lock-free: per-tid slot, single writer (the engine thread / its respawner)
        self._dead = [False] * self.n  # guarded by: _route_lock
        # serializes dead-state transitions against routing: submit's
        # dead-check + enqueue must not interleave with declare-dead's
        # drain, or a query lands in a queue nobody will ever pop
        self._route_lock = make_lock("pool.route")
        self._busy_since = [0] * self.n  # lock-free: per-tid slot, single writer; health() reads a snapshot
        self._inflight: list = [None] * self.n  # lock-free: per-tid slot, single writer (engine thread; death handler runs after it stopped)
        # stream lane: shared low-priority queue for standing-query work
        self.stream_queue = collections.deque()  # guarded by: _stream_lock
        self._stream_lock = make_lock("pool.stream")
        # batch lane: coalesced serving-path groups (runtime/batcher.py).
        # A group is ONE item — work stealing cannot split it — popped
        # right after the engine's own queue (batched queries are
        # interactive traffic, unlike the stream lane's background work).
        # Groups deliver results through their members' futures, so items
        # here are fire-and-forget for the pool's result bookkeeping.
        self.batch_queue = collections.deque()  # guarded by: _batch_lock
        self._batch_lock = make_lock("pool.batch")
        # heavy lane: fused index-origin dispatches + their split slices
        # (runtime/batcher.py HeavyGroup/_HeavySlice), same fire-and-forget
        # contract as the batch lane but WEIGHTED: at most
        # ceil(n * heavy_lane_pct / 100) engines (min 1) execute heavy
        # items concurrently, so a heavy flood can never occupy every
        # engine — interactive light traffic always keeps capacity.
        self.heavy_queue = collections.deque()  # guarded by: _heavy_lock
        # split-slice continuations in their own deque: they are
        # cap-exempt (their group already holds a slot) and exist only
        # during an active split, so the pop path stays O(1) instead of
        # scanning the group queue for them
        self.heavy_slices = collections.deque()  # guarded by: _heavy_lock
        self._heavy_lock = make_lock("pool.heavy")
        self._heavy_inflight = 0  # guarded by: _heavy_lock
        # rebuild lane: background shard-rebuild jobs (runtime/recovery.py
        # RebuildJob), drained only when every other lane is empty —
        # healing soaks idle capacity, never displaces serving traffic.
        # Items share the batch lane's fire-and-forget contract
        # (run(engine) + fail_all(exc)).
        self.rebuild_queue = collections.deque()  # guarded by: _rebuild_lock
        self._rebuild_lock = make_lock("pool.rebuild")
        # stream-lane qids are reserved for wait(): poll() skips them, so
        # an open-loop poll() consumer (the emulator) sharing this pool
        # can't steal the stream context's completions
        self._stream_qids: set = set()  # guarded by: _results_lock
        # weighted-fair sub-lane (runtime/admission.py FairQueue): created
        # lazily on the first admission-armed submission so the off-knob
        # pop path pays one attribute read, nothing else
        self._fair = None  # guarded by: _route_lock
        # heavy-lane slots currently held per tenant — the per-tenant
        # weighted cap (admission heavy_cap_for) counts against this
        self._heavy_by_tenant: dict = {}  # guarded by: _heavy_lock
        _POOLS.add(self)  # feeds the wukong_pool_queue_depth gauge

    # ------------------------------------------------------------------
    def start(self) -> None:
        for tid in range(self.n):
            self._spawn(tid)

    def _spawn(self, tid: int) -> None:
        t = threading.Thread(target=self._run_engine, args=(tid,),
                             daemon=True, name=f"engine-{tid}")
        t.start()
        self._threads[tid] = t

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            if t is not None:
                self._pending.release()
        for t in self._threads:
            if t is not None:
                t.join(timeout=5)
        self._threads = [None] * self.n

    # ------------------------------------------------------------------
    # failure detection / recovery (beyond the reference: its engine
    # pthreads have no supervision — wukong.cpp:245-252)
    # ------------------------------------------------------------------
    def health(self) -> dict:
        """Per-engine liveness snapshot: alive flag, respawn count, and how
        long the current query has been executing (0 = idle). A stuck
        engine shows a growing busy_us — report-only (Python threads cannot
        be preempted safely); dead engines are routed around."""
        now = get_usec()
        return {
            tid: {"alive": not self._dead[tid],  # unguarded: report-only snapshot; a stale bool here only ages the health report by one call
                  "respawns": self._respawns[tid],
                  "busy_us": (now - b) if (b := self._busy_since[tid]) else 0}
            for tid in range(self.n)}

    def _fail(self, qid: int, exc: Exception) -> None:
        """Deliver an error result, honoring the append-before-set protocol
        (one place: wait()/poll() race discipline lives here only)."""
        with self._results_lock:
            self._results[qid] = exc
            ev = self._done[qid]
        self._completed.append(qid)
        ev.set()

    @staticmethod
    def _stamp_enqueue(query, lane: str) -> None:
        """Queue-delay accounting for the overload signal bus (obs/slo.py):
        submit stamps the enqueue clock, the popping engine charges the
        per-lane delay EWMA. One knob check when accounting is off;
        ``__slots__`` items (split slices) skip silently."""
        if not Global.enable_tenant_accounting:
            return
        try:
            query._slo_enq_us = get_usec()
            query._slo_lane = lane
        except AttributeError:
            pass

    @staticmethod
    def _charge_queue_delay(query) -> None:
        enq = getattr(query, "_slo_enq_us", None)
        if enq is not None:
            query._slo_enq_us = None
            maybe_note_queue_delay(getattr(query, "_slo_lane", "default"),
                                   get_usec() - enq)

    @staticmethod
    def _end_queue_span(query, **attrs) -> None:
        """Close a traced query's pool.queue span. Every exit from the
        queue — popped by an engine, shed, or failed without ever being
        popped (dead pool, stranded redistribution) — must end it, or the
        open span keeps accruing time and swallows later trace events."""
        qs = getattr(query, "_obs_queue_span", None)
        if qs is not None:
            query.trace.end_span(qs, **attrs)
            query._obs_queue_span = None

    def _on_engine_death(self, tid: int, exc: BaseException) -> None:
        from wukong_tpu.utils.logger import log_error, log_warn

        # the in-flight query (if any) likely triggered the crash: fail it
        # rather than retry it into every engine, and never strand its waiter
        self._busy_since[tid] = 0
        item = self._inflight[tid]
        self._inflight[tid] = None
        if item is not None:
            qid, _q = item
            if qid is None:  # batch-lane group: settle its member futures
                self._heavy_done(_q)  # a heavy slot died with the thread
                fail = getattr(_q, "fail_all", None)
                if fail is not None:
                    fail(RuntimeError(
                        f"engine-{tid} crashed executing a fused batch: "
                        f"{exc!r}"))
            else:
                self._fail(qid, RuntimeError(
                    f"engine-{tid} crashed executing query {qid}: {exc!r}"))
        self._respawns[tid] += 1
        _M_RESPAWNS.inc()
        if self._respawns[tid] <= self.MAX_RESPAWNS and not self._stop.is_set():
            log_warn(f"engine-{tid} died ({exc!r}); respawning "
                     f"({self._respawns[tid]}/{self.MAX_RESPAWNS})")
            self._spawn(tid)  # its queue is intact; the new thread drains it
            return
        # crash loop: declare dead, push queued work to the neighbors so
        # nothing strands, and stop routing here (submit skips dead tids).
        # _route_lock makes the drain atomic against concurrent submits and
        # other deaths — nothing can enqueue into the drained queue after.
        log_error(f"engine-{tid} dead after {self._respawns[tid]} crashes; "
                  "redistributing its queue")
        with self._route_lock:
            self._dead[tid] = True
            with self.locks[tid]:
                stranded = list(self.queues[tid])
                self.queues[tid].clear()
            live = [t for t in range(self.n) if not self._dead[t]]
            for k, item in enumerate(stranded):
                if not live:  # whole pool dead: fail queries, don't hang
                    self._end_queue_span(item[1], dead_pool=True)
                    self._fail(item[0], RuntimeError("engine pool dead"))
                    continue
                dst = live[k % len(live)]
                with self.locks[dst]:
                    self.queues[dst].append(item)
                self._pending.release()
            if not live:  # nobody left to drain the stream lane either
                # ...starting with the fair sub-lane: pop until dry (the
                # DRR order is irrelevant now, every item fails the same)
                f = self._fair
                while f is not None:
                    it = f.pop()
                    if it is None:
                        break
                    self._end_queue_span(it[1], dead_pool=True)
                    self._fail(it[0], RuntimeError("engine pool dead"))
                with self._stream_lock:
                    stream_stranded = list(self.stream_queue)
                    self.stream_queue.clear()
                for item in stream_stranded:
                    self._end_queue_span(item[1], dead_pool=True)
                    self._fail(item[0], RuntimeError("engine pool dead"))
                # ...or the batch lane: settle fused groups' member futures
                with self._batch_lock:
                    batch_stranded = list(self.batch_queue)
                    self.batch_queue.clear()
                for _qid, group in batch_stranded:
                    fail = getattr(group, "fail_all", None)
                    if fail is not None:
                        fail(RuntimeError("engine pool dead"))
                # ...or the heavy lane: groups and split slices alike
                with self._heavy_lock:
                    heavy_stranded = (list(self.heavy_queue)
                                      + list(self.heavy_slices))
                    self.heavy_queue.clear()
                    self.heavy_slices.clear()
                for _qid, item2 in heavy_stranded:
                    fail = getattr(item2, "fail_all", None)
                    if fail is not None:
                        fail(RuntimeError("engine pool dead"))
                # ...or the rebuild lane: same fire-and-forget settlement
                with self._rebuild_lock:
                    rebuild_stranded = list(self.rebuild_queue)
                    self.rebuild_queue.clear()
                for _qid, job in rebuild_stranded:
                    fail = getattr(job, "fail_all", None)
                    if fail is not None:
                        fail(RuntimeError("engine pool dead"))

    # ------------------------------------------------------------------
    def submit(self, query, tid: int | None = None,
               lane: str | None = None) -> int:
        """Enqueue a query; returns a handle. tid routes like the reference's
        proxy dst engine choice (round-robin default, proxy.hpp:143-160).

        lane="stream" bypasses per-engine routing into the shared
        low-priority stream queue: any engine drains it, but only after its
        own queue and its steal targets are empty (standing-query work never
        displaces interactive queries).

        lane="batch" enqueues a coalesced FusedGroup (runtime/batcher.py)
        as ONE indivisible item; the group delivers results through its
        members' futures, so no pool-side result entry is created (returns
        -1). A dead pool fails the group immediately via fail_all.

        lane="heavy" enqueues a fused heavy dispatch (HeavyGroup) or one of
        its split slices with the batch lane's fire-and-forget contract,
        drained under the weighted heavy_lane_pct concurrency cap so heavy
        work never starves interactive traffic.

        lane="rebuild" enqueues a background shard-rebuild job
        (runtime/recovery.py RebuildJob) with the same fire-and-forget
        contract, drained only when every other lane is empty."""
        if lane in ("batch", "heavy", "rebuild"):
            _M_SUBMITTED.labels(lane=lane).inc()
            lock = {"batch": self._batch_lock, "heavy": self._heavy_lock,
                    "rebuild": self._rebuild_lock}[lane]
            if lane == "heavy" and getattr(query, "heavy_continuation",
                                           False):
                queue = self.heavy_slices  # unguarded: binds the deque reference only (immutable attr); mutated below under `lock`
            else:
                queue = {"batch": self.batch_queue,  # unguarded: reference binding only, as above
                         "heavy": self.heavy_queue,  # unguarded: reference binding only, as above
                         "rebuild": self.rebuild_queue}[lane]  # unguarded: reference binding only, as above
            self._stamp_enqueue(query, lane)
            with self._route_lock:
                if all(self._dead[k] for k in range(self.n)):
                    fail = getattr(query, "fail_all", None)
                    if fail is not None:
                        fail(RuntimeError("engine pool dead"))
                    return -1
                with lock:
                    queue.append((None, query))
            self._pending.release()
            return -1
        with self._results_lock:
            qid = self._next_qid
            self._next_qid += 1
            self._done[qid] = threading.Event()
        _M_SUBMITTED.labels(lane=lane or "default").inc()
        # traced queries get a queue span opened here and closed by the
        # engine thread that pops them (cross-thread end is supported)
        tr = getattr(query, "trace", None)
        if tr is not None:
            query._obs_queue_span = tr.start_span(
                "pool.queue", qid=qid, lane=lane or "default")
        self._stamp_enqueue(query, lane or "default")
        if lane == "stream":
            if Global.enable_admission and getattr(query, "owner_tenant",
                                                   None):
                # priority inheritance: a standing query's maintenance
                # work rides the fair sub-lane at its OWNER's weight
                # instead of the last-priority stream lane
                return self._submit_fair(qid, query, stream=True)
            with self._results_lock:
                self._stream_qids.add(qid)
            with self._route_lock:
                if all(self._dead[k] for k in range(self.n)):
                    self._end_queue_span(query, dead_pool=True)
                    self._fail(qid, RuntimeError("engine pool dead"))
                    return qid
                with self._stream_lock:
                    self.stream_queue.append((qid, query))
            self._pending.release()
            return qid
        if tid is None and Global.enable_admission:
            # default-lane traffic with no routing pin rides the DRR fair
            # sub-lane: per-tenant sub-queues drained by weight, so a
            # bulk flood cannot monopolize the interactive engines
            return self._submit_fair(qid, query)
        t = qid % self.n if tid is None else tid % self.n
        with self._route_lock:  # atomic dead-check + enqueue vs declare-dead
            if self._dead[t]:  # route around dead engines
                live = [k for k in range(self.n) if not self._dead[k]]
                if not live:
                    self._end_queue_span(query, dead_pool=True)
                    self._fail(qid, RuntimeError("engine pool dead"))
                    return qid
                t = live[qid % len(live)]
            with self.locks[t]:
                self.queues[t].append((qid, query))
        self._pending.release()
        return qid

    def _submit_fair(self, qid: int, query, stream: bool = False) -> int:
        """Enqueue into the weighted-fair sub-lane (admission armed).

        The tenant is the EFFECTIVE one (``owner_tenant`` wins — priority
        inheritance for standing-query maintenance) and the DRR weight is
        resolved HERE, by the caller, from the lock-free quota map:
        FairQueue never calls out under ``admission.queue``, keeping that
        lock a lockdep leaf."""
        from wukong_tpu.runtime.admission import (FairQueue,
                                                  effective_tenant,
                                                  get_admission)

        ten = effective_tenant(query)
        w = get_admission().weight(ten)
        if stream:
            with self._results_lock:
                self._stream_qids.add(qid)
        with self._route_lock:  # atomic dead-check + enqueue, as above
            if all(self._dead[k] for k in range(self.n)):
                self._end_queue_span(query, dead_pool=True)
                self._fail(qid, RuntimeError("engine pool dead"))
                return qid
            f = self._fair
            if f is None:
                f = self._fair = FairQueue()
            f.push(ten, (qid, query), weight=w)
        self._pending.release()
        return qid

    def wait(self, qid: int, timeout: float | None = None):
        """Returns the engine's result, or raises TimeoutError (the result
        stays claimable by a later wait — no stranded entries)."""
        # capture the event under the lock: the bare `self._done[qid]`
        # read raced concurrent dict mutation (found by the guarded-by
        # analysis gate when _done was annotated)
        with self._results_lock:
            ev = self._done[qid]
        if not ev.wait(timeout):
            raise TimeoutError(f"query {qid} still running")
        with self._results_lock:
            self._done.pop(qid, None)
            self._stream_qids.discard(qid)
            try:
                self._completed.remove(qid)
            except ValueError:
                pass
            return self._results.pop(qid, None)

    def poll(self) -> list:
        """Drain finished queries as (qid, result) pairs — the open-loop
        receive side (proxy.hpp tryrecv_reply analogue). A pool user should
        consume completions via EITHER wait() or poll(), not both."""
        out = []
        while True:
            try:
                qid = self._completed.popleft()
            except IndexError:
                break
            with self._results_lock:
                if qid not in self._done:  # already consumed via wait()
                    continue
                if qid in self._stream_qids:
                    # stream-lane completions belong to the stream
                    # context's wait() — leave them claimable
                    continue
                self._done.pop(qid)
                out.append((qid, self._results.pop(qid, None)))
        return out

    # ------------------------------------------------------------------
    def alive_count(self) -> int:
        """Engines not declared dead (the heavy split fan-out bound)."""
        return sum(1 for t in range(self.n) if not self._dead[t])  # unguarded: report-only snapshot, like health()

    def _heavy_cap(self) -> int:
        """Max engines concurrently executing heavy-lane items."""
        return max((self.n * max(int(Global.heavy_lane_pct), 0)) // 100, 1)

    def _heavy_done(self, query) -> None:
        """Release the weighted heavy slot an engine-loop pop took. Keyed
        on the item's lane tag: only slot-counted heavy pops incremented
        (cap-exempt slice continuations did not take one)."""
        if getattr(query, "lane", None) != "heavy" \
                or getattr(query, "heavy_continuation", False):
            return
        ten = getattr(query, "_adm_heavy_ten", None)
        with self._heavy_lock:
            self._heavy_inflight = max(self._heavy_inflight - 1, 0)
            if ten is not None:
                query._adm_heavy_ten = None
                left = self._heavy_by_tenant.get(ten, 1) - 1
                if left <= 0:
                    self._heavy_by_tenant.pop(ten, None)
                else:
                    self._heavy_by_tenant[ten] = left

    def _heavy_pick_locked(self) -> int:  # caller holds: _heavy_lock
        """Index of the first heavy-queue group whose tenant is under its
        weighted per-tenant slot share, or -1 when every queued tenant is
        at cap (caller holds ``_heavy_lock``). ``heavy_cap_for`` is a pure
        function of the lock-free quota map — no lock is taken under the
        heavy lock, so ``pool.heavy`` ordering is unchanged."""
        if not Global.enable_admission:
            return 0 if self.heavy_queue else -1
        from wukong_tpu.runtime.admission import get_admission

        adm = get_admission()
        cap = self._heavy_cap()
        for i, (_qid, g) in enumerate(self.heavy_queue):
            ten = getattr(g, "tenant", None)
            if ten is None:
                return i  # untagged groups predate admission: no cap
            if (self._heavy_by_tenant.get(ten, 0)
                    < adm.heavy_cap_for(ten, cap, self._heavy_by_tenant)):
                return i
        return -1

    # ------------------------------------------------------------------
    def _neighbors(self, tid: int) -> list[int]:
        """Stealing pattern (engine.hpp:186-207): 0=pair, 1=ring."""
        if self.n <= 1:
            return []
        if Global.stealing_pattern == 1:  # ring: next engine
            return [(tid + 1) % self.n]
        return [tid ^ 1] if (tid ^ 1) < self.n else []  # pair

    def _pop_work(self, tid: int):
        # own queue first (front)
        with self.locks[tid]:
            if self.queues[tid]:
                return self.queues[tid].popleft()
        # batch lane next: coalesced groups are interactive traffic, popped
        # whole (a group is one item — stealing can never split it)
        with self._batch_lock:
            if self.batch_queue:
                return self.batch_queue.popleft()
        # weighted-fair sub-lane (admission armed): one DRR pop serves the
        # per-tenant sub-queues by weight — still interactive priority,
        # ahead of stealing (a fair item has no owner engine to steal from)
        f = self._fair  # unguarded: reads the set-once published reference
        if f is not None:
            item = f.pop()
            if item is not None:
                return item
        # steal from neighbors (back — leave the owner its freshest work)
        for nb in self._neighbors(tid):
            with self.locks[nb]:
                if self.queues[nb]:
                    return self.queues[nb].pop()
        # heavy lane after every interactive source, under the weighted
        # concurrency cap: fused index-origin dispatches soak the engines
        # light traffic is not using, never all of them. Split SLICES are
        # cap-exempt continuations — their group already holds a slot, and
        # capping them would stall its gather barrier behind itself.
        with self._heavy_lock:
            if self.heavy_slices:
                return self.heavy_slices.popleft()
            if self.heavy_queue and self._heavy_inflight < self._heavy_cap():
                i = self._heavy_pick_locked()
                if i >= 0:
                    item = self.heavy_queue[i]
                    del self.heavy_queue[i]
                    self._heavy_inflight += 1
                    ten = getattr(item[1], "tenant", None)
                    if ten is not None and Global.enable_admission:
                        # stamp the counted tenant on the group so
                        # _heavy_done releases the SAME slot even if the
                        # knob or quota map changes mid-flight
                        try:
                            item[1]._adm_heavy_ten = ten
                            self._heavy_by_tenant[ten] = (
                                self._heavy_by_tenant.get(ten, 0) + 1)
                        except AttributeError:
                            pass  # __slots__ item: skip tenant accounting
                    return item
        # stream lane next-to-last: standing-query work fills idle capacity
        with self._stream_lock:
            if self.stream_queue:
                return self.stream_queue.popleft()
        # rebuild lane last: background shard healing is fully deferrable —
        # failover keeps results complete while the rebuild waits
        with self._rebuild_lock:
            if self.rebuild_queue:
                return self.rebuild_queue.popleft()
        return None

    def _run_engine(self, tid: int) -> None:
        try:
            self._engine_loop(tid)
        except BaseException as e:  # thread death (not per-query errors)
            if not self._stop.is_set():
                self._on_engine_death(tid, e)

    def _engine_loop(self, tid: int) -> None:
        from wukong_tpu.runtime.bind import get_binder

        get_binder().bind_thread(tid)  # no-op unless core binding is enabled
        engine = self._make_engine(tid)
        snooze_us = self.IDLE_SNOOZE_MIN_US
        while not self._stop.is_set():
            item = self._pop_work(tid)
            if item is None:
                # capped exponential idle backoff with wake-on-submit: the
                # semaphore wakes a sleeper the moment anything is
                # submitted, so deep relax costs no submit-path latency;
                # the doubling only thins the *poll* cadence (10us ->
                # IDLE_SNOOZE_MAX_US) so an idle pool no longer starves
                # co-located fused dispatches (ROADMAP follow-up i —
                # before/after in BENCH_SERVE.json idle_backoff)
                got = self._pending.acquire(timeout=snooze_us / 1e6)
                snooze_us = (self.IDLE_SNOOZE_MIN_US if got
                             else min(snooze_us * 2, self.IDLE_SNOOZE_MAX_US))
                continue
            qid, query = item
            self._inflight[tid] = item
            self._busy_since[tid] = get_usec()
            self._charge_queue_delay(query)  # overload bus: per-lane EWMA
            if qid is None:  # batch/heavy lanes: fire-and-forget items
                try:
                    from wukong_tpu.runtime import faults

                    faults.site("pool.execute", shard=tid)
                    query.run(engine)
                except Exception as e:
                    # run() settles its members on internal errors; this
                    # catches the re-raise (and fault injection) so the
                    # engine thread survives — fail_all is idempotent
                    fail = getattr(query, "fail_all", None)
                    if fail is not None:
                        fail(e)
                self._heavy_done(query)  # release the weighted heavy slot
                self._busy_since[tid] = 0
                self._inflight[tid] = None
                self._respawns[tid] = 0
                continue
            # close the queue span opened at submit (the wait IS the span)
            self._end_queue_span(query, engine=tid)
            try:
                # a query whose deadline expired while queued fails fast
                # with a structured QueryTimeout instead of occupying the
                # engine (the resilience layer's load-shedding path); the
                # pool keeps serving — nothing wedges
                dl = getattr(query, "deadline", None)
                if dl is not None and dl.expired():
                    from wukong_tpu.utils.errors import QueryTimeout

                    _M_SHED.inc()
                    maybe_note_shed("queue_deadline",
                                    getattr(query, "tenant", "default"))
                    raise QueryTimeout(
                        f"deadline expired in engine-{tid} queue")
                from wukong_tpu.runtime import faults

                faults.site("pool.execute", shard=tid)
                out = engine.execute(query)
            except Exception as e:  # engine errors become the reply
                out = e
            # cleared HERE, not in a finally: a thread-killing exception
            # must leave the in-flight marker for _on_engine_death to fail
            # the query instead of stranding its waiter
            self._busy_since[tid] = 0
            self._inflight[tid] = None
            # a served query proves the engine healthy: reset the crash
            # budget so isolated poison queries spread over time never
            # accumulate into a permanent declare-dead
            self._respawns[tid] = 0
            with self._results_lock:
                self._results[qid] = out
                ev = self._done[qid]  # capture: a racing poll() may pop it
            # append BEFORE set(): a wait()er woken by set() must find the
            # qid already in _completed so its remove() never races the append
            self._completed.append(qid)
            ev.set()
