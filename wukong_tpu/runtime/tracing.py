"""RETIRED (PR 7): the deprecation shim from PR 3 is gone.

``StepTrace`` lives in :mod:`wukong_tpu.obs.trace` and ``device_trace`` in
:mod:`wukong_tpu.obs.export`; the full replacement is the per-query
:class:`wukong_tpu.obs.QueryTrace` + flight recorder. The shim carried old
imports for one release; no in-repo importer remains, so importing this
module is now a hard, explanatory error (tests pin the message).
"""

raise ImportError(
    "wukong_tpu.runtime.tracing was retired: import StepTrace from "
    "wukong_tpu.obs.trace and device_trace from wukong_tpu.obs.export "
    "(or use wukong_tpu.obs.QueryTrace for per-query tracing)")
