"""DEPRECATED: absorbed into :mod:`wukong_tpu.obs` (PR 3, observability).

``StepTrace`` now lives in ``wukong_tpu.obs.trace`` and ``device_trace`` in
``wukong_tpu.obs.export``; the full replacement for what this module stubbed
out is the per-query :class:`wukong_tpu.obs.QueryTrace` + flight recorder.
This shim keeps old imports working one more release.
"""

from __future__ import annotations

from wukong_tpu.obs.export import device_trace  # noqa: F401
from wukong_tpu.obs.trace import StepTrace  # noqa: F401

__all__ = ["StepTrace", "device_trace"]
