"""Tracing/profiling hooks (reference: Monitor micro-timing + timer sprinkles,
SURVEY §5 — "no pervasive tracing framework").

This build adds what the reference lacks: a scoped device profiler around any
query (JAX profiler traces viewable in XProf/TensorBoard) and a per-step
host-side trace recorder the engines can feed.
"""

from __future__ import annotations

import contextlib
from collections import defaultdict

from wukong_tpu.utils.timer import get_usec


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a JAX profiler trace of everything inside the block."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTrace:
    """Per-query step timings: step label -> [usec]. Feed from engine loops."""

    def __init__(self):
        self.records: dict[str, list[int]] = defaultdict(list)
        self._open: dict[str, int] = {}

    @contextlib.contextmanager
    def span(self, label: str):
        t0 = get_usec()
        try:
            yield
        finally:
            self.records[label].append(get_usec() - t0)

    def summary(self) -> dict[str, dict]:
        out = {}
        for label, xs in self.records.items():
            out[label] = {"count": len(xs), "total_us": sum(xs),
                          "max_us": max(xs)}
        return out
