"""Transport seam: the data plane's remote-fetch boundary, made explicit.

Until PR 20 every "distributed" fetch was a Python closure run against a
GStore object in the same interpreter — a threading guarantee wearing a
distributed costume. This module is the seam that makes the process
boundary real without giving up the in-proc default:

- **Ops, not closures.** Each remote-readable operation is a named op
  (``"segment"``, ``"index"``, ...) executed by :func:`run_op` against one
  partition. The sharded store passes ``(op, args)`` down its fetch path
  instead of a closure, so both transports serve the identical code path.
- **Two transports.** :class:`LoopbackTransport` (default) executes ops
  directly against the local store — byte-for-byte the pre-PR-20 behavior,
  zero serialization, zero touch. :class:`SocketTransport` speaks a
  length-prefixed + CRC framed wire protocol over TCP to the per-shard-
  group worker processes (runtime/procs.py), with per-connection send/recv
  timeouts, ``retry_call`` backoff, and per-(peer, shard) circuit breakers.
- **A closed message registry.** Every wire message type is declared in
  the literal :data:`MESSAGE_REGISTRY` with an explicit serialize +
  deserialize pair and a server-side handler in :data:`OP_HANDLERS`; the
  ``transport-contract`` analysis gate (analysis/transportgate.py) holds
  the registry, the handlers, and the call sites in sync mechanically.

Framing (the WAL's discipline, applied to the wire): every frame is
``MAGIC | u32 length | u32 crc32 | payload``. A torn frame (short header
or body) drops only the unacknowledged trailing message — the bytes before
it all parse; a mid-buffer CRC mismatch is a structured
``TRANSPORT_CORRUPT`` (never a silent skip); a frame above the
``transport_max_frame_mb`` knob raises ``FRAME_TOO_LARGE`` naming the
limit, on both the encode and decode side.

Fault sites: ``transport.connect`` / ``transport.send`` /
``transport.recv`` fire before their syscall, so injected chaos exercises
the exact reconnect/retry/breaker paths a dead worker process does.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import zlib

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.utils.errors import (
    ErrorCode,
    FrameTooLarge,
    ShardUnavailable,
    TransportCorrupt,
    WukongError,
)

FRAME_MAGIC = b"WKTX"
_FRAME_HDR = struct.Struct("<II")  # (payload length, payload crc32)

# the per-connection send/recv lock: innermost by construction (nothing
# is acquired while a frame is on the wire), so a declared leaf
declare_leaf("transport.conn")


def _max_frame_bytes(max_bytes: int | None = None) -> int:
    return (int(max_bytes) if max_bytes is not None
            else Global.transport_max_frame_mb * (1 << 20))


# ---------------------------------------------------------------------------
# framing (pure functions — golden-tested without sockets)
# ---------------------------------------------------------------------------

def encode_frame(payload: bytes, max_bytes: int | None = None) -> bytes:
    """One wire frame: MAGIC + length + crc + payload. Oversized payloads
    raise FRAME_TOO_LARGE naming the knob — the sender must refuse what
    the receiver would refuse, or the error surfaces a timeout away."""
    limit = _max_frame_bytes(max_bytes)
    if len(payload) > limit:
        raise FrameTooLarge(
            f"frame payload is {len(payload)} bytes, over the "
            f"transport_max_frame_mb limit ({limit} bytes)")
    return (FRAME_MAGIC + _FRAME_HDR.pack(len(payload), zlib.crc32(payload))
            + payload)


def decode_frames(buf: bytes, max_bytes: int | None = None
                  ) -> tuple[list[bytes], int]:
    """Parse every complete frame from ``buf``; returns (payloads,
    consumed). A torn tail (short magic/header/body) stops the parse —
    only the unacknowledged trailing message is dropped, the WAL's
    torn-tail contract. A bad magic or a CRC mismatch on a COMPLETE frame
    raises TRANSPORT_CORRUPT (corruption mid-stream is never skippable);
    an oversized declared length raises FRAME_TOO_LARGE naming the limit."""
    limit = _max_frame_bytes(max_bytes)
    out: list[bytes] = []
    off = 0
    n = len(buf)
    hdr = len(FRAME_MAGIC) + _FRAME_HDR.size
    while off < n:
        if off + hdr > n:
            break  # torn header: wait for (or drop) the rest
        if buf[off:off + len(FRAME_MAGIC)] != FRAME_MAGIC:
            raise TransportCorrupt(
                f"bad frame magic at offset {off}")
        blen, crc = _FRAME_HDR.unpack_from(buf, off + len(FRAME_MAGIC))
        if blen > limit:
            raise FrameTooLarge(
                f"frame declares {blen} bytes, over the "
                f"transport_max_frame_mb limit ({limit} bytes)")
        body = buf[off + hdr: off + hdr + blen]
        if len(body) < blen:
            break  # torn body: the unacknowledged message
        if zlib.crc32(body) != crc:
            raise TransportCorrupt(
                f"frame crc mismatch at offset {off}")
        out.append(body)
        off += hdr + blen
    return out, off


class FrameDecoder:
    """Incremental frame parser for a stream socket: feed chunks, yield
    complete payloads, keep the torn tail buffered for the next chunk."""

    def __init__(self, max_bytes: int | None = None):
        self._buf = b""
        self._max = max_bytes

    def feed(self, chunk: bytes) -> list[bytes]:
        self._buf += chunk
        frames, consumed = decode_frames(self._buf, self._max)
        self._buf = self._buf[consumed:]
        return frames


# ---------------------------------------------------------------------------
# ops: the remote-readable operations, executed against ONE partition.
# Each mirrors exactly what the pre-seam closure in sharded_store.py did,
# so the loopback transport is byte-for-byte the old behavior.
# ---------------------------------------------------------------------------

def _op_ping(g, seq: int):
    """Liveness + staleness probe: the supervisor's heartbeat payload."""
    return {"sid": int(g.sid), "version": int(getattr(g, "version", 0)),
            "seq": int(seq)}


def _op_segment(g, pid: int, d: int):
    """One (pid, dir) CSR fetch: (keys, offsets, edges); the TYPE_ID/IN
    pseudo-segment routes through the type-index CSR like the old
    closure's ``self._type_csr`` branch did."""
    import numpy as np

    from wukong_tpu.engine.device_store import type_index_csr
    from wukong_tpu.types import IN, TYPE_ID

    if int(pid) == TYPE_ID and int(d) == IN:
        return type_index_csr(g)
    host = g.segments.get((int(pid), int(d)))
    if host is None:
        return (np.empty(0, np.int64), np.zeros(1, np.int64),
                np.empty(0, np.int64))
    return (host.keys, host.offsets, host.edges)


def _op_versatile(g, d: int):
    """Combined variable-predicate adjacency of direction d."""
    from wukong_tpu.engine.device_store import combined_adjacency

    return combined_adjacency(g, int(d))


def _op_index(g, tpid: int, d: int):
    import numpy as np

    return np.asarray(g.get_index(int(tpid), int(d)), dtype=np.int32)


def _op_digest(g):
    """Content CRC over every persisted array — the rejoin proof: a
    restarted worker serves only after its digest matches the parent's."""
    from wukong_tpu.store.persist import gstore_digest

    return int(gstore_digest(g))


def _op_sync(g, upto_seq: int):
    """Worker-side WAL catch-up hook. On the parent (loopback) the store
    IS the mutation target, so there is nothing to sync; the worker
    process overrides the handler binding at serve time
    (runtime/procs.py) with its WAL-tail replay."""
    return 0


def _op_snapshot(g):
    """Serialize one partition through the checkpoint wire format — the
    migration transfer's payload (a byte-identical copy by the save/load
    roundtrip contract)."""
    from wukong_tpu.store.persist import gstore_to_bytes

    return gstore_to_bytes(g)


# serialize / deserialize pairs: the explicit wire schema of each message
# type's REQUEST arguments (results ride the generic response envelope).
# Requests are plain ints on purpose — a message type that needs to ship
# an object must grow an explicit schema here, reviewed as a diff.

def pack_ping(args) -> dict:
    (seq,) = args
    return {"seq": int(seq)}


def unpack_ping(d: dict) -> tuple:
    return (int(d["seq"]),)


def pack_segment(args) -> dict:
    pid, d = args
    return {"pid": int(pid), "d": int(d)}


def unpack_segment(d: dict) -> tuple:
    return (int(d["pid"]), int(d["d"]))


def pack_versatile(args) -> dict:
    (d,) = args
    return {"d": int(d)}


def unpack_versatile(d: dict) -> tuple:
    return (int(d["d"]),)


def pack_index(args) -> dict:
    tpid, d = args
    return {"tpid": int(tpid), "d": int(d)}


def unpack_index(d: dict) -> tuple:
    return (int(d["tpid"]), int(d["d"]))


def pack_digest(args) -> dict:
    return {}


def unpack_digest(d: dict) -> tuple:
    return ()


def pack_sync(args) -> dict:
    (upto_seq,) = args
    return {"upto_seq": int(upto_seq)}


def unpack_sync(d: dict) -> tuple:
    return (int(d["upto_seq"]),)


def pack_snapshot(args) -> dict:
    return {}


def unpack_snapshot(d: dict) -> tuple:
    return ()


# THE central wire-message registry: every message type the transport can
# carry, with its serialize + deserialize sides. The ``transport-contract``
# analysis gate (analysis/transportgate.py) enforces that this stays a
# literal, that every entry has both sides and a server handler, that
# every op named at a call site is declared here, and that every entry is
# exercised by at least one test. Adding a message type = add the pack/
# unpack pair, the handler, the registry row, and a test.
MESSAGE_REGISTRY = {
    "ping": (pack_ping, unpack_ping),
    "segment": (pack_segment, unpack_segment),
    "versatile": (pack_versatile, unpack_versatile),
    "index": (pack_index, unpack_index),
    "digest": (pack_digest, unpack_digest),
    "sync": (pack_sync, unpack_sync),
    "snapshot": (pack_snapshot, unpack_snapshot),
}

# server-side executors, one per registry row (same key set — gate-held)
OP_HANDLERS = {
    "ping": _op_ping,
    "segment": _op_segment,
    "versatile": _op_versatile,
    "index": _op_index,
    "digest": _op_digest,
    "sync": _op_sync,
    "snapshot": _op_snapshot,
}


def run_op(op: str, g, *args):
    """Execute one declared op against a local partition — the loopback
    execution path AND the worker's server dispatch."""
    h = OP_HANDLERS.get(op)
    if h is None:
        raise TransportCorrupt(f"undeclared transport op {op!r}")
    return h(g, *args)


def pack_message(op: str, sid: int, args: tuple) -> bytes:
    """Request wire form: pickled (op, sid, schema-packed args)."""
    ent = MESSAGE_REGISTRY.get(op)
    if ent is None:
        raise TransportCorrupt(f"undeclared transport op {op!r}")
    return pickle.dumps((op, int(sid), ent[0](args)),
                        protocol=pickle.HIGHEST_PROTOCOL)


def unpack_message(payload: bytes) -> tuple[str, int, tuple]:
    """Inverse of :func:`pack_message`; every malformation is a structured
    TRANSPORT_CORRUPT, never a bare KeyError/UnpicklingError."""
    try:
        op, sid, d = pickle.loads(payload)
    except Exception as e:  # noqa: BLE001 — pickle raises many shapes
        raise TransportCorrupt(f"unreadable request: {e}") from None
    ent = MESSAGE_REGISTRY.get(op)
    if ent is None:
        raise TransportCorrupt(f"undeclared transport op {op!r}")
    try:
        args = ent[1](d)
    except (KeyError, TypeError, ValueError) as e:
        raise TransportCorrupt(
            f"malformed {op!r} request: {e}") from None
    return op, int(sid), args


def pack_reply(result) -> bytes:
    return pickle.dumps(("ok", result), protocol=pickle.HIGHEST_PROTOCOL)


def pack_error(code: int, detail: str) -> bytes:
    return pickle.dumps(("err", int(code), str(detail)),
                        protocol=pickle.HIGHEST_PROTOCOL)


def unpack_reply(payload: bytes):
    try:
        t = pickle.loads(payload)
    except Exception as e:  # noqa: BLE001
        raise TransportCorrupt(f"unreadable reply: {e}") from None
    try:
        if t[0] == "ok":
            return t[1]
        if t[0] == "err":
            # re-raise the peer's structured code, taxonomy-preserving
            code = ErrorCode(int(t[1]))
            raise WukongError(code, t[2])
        kind = t[0]
    except (TypeError, IndexError, ValueError) as e:
        # a reply that is not ("ok", r) / ("err", code, detail) is
        # corruption, never a bare TypeError a timeout away from its cause
        raise TransportCorrupt(f"malformed reply envelope: {e}") from None
    raise TransportCorrupt(f"unknown reply kind {kind!r}")


def _metrics():
    from wukong_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.counter("wukong_transport_messages_total",
                    "Wire messages sent by the socket transport",
                    labels=("op", "result")),
        reg.counter("wukong_transport_bytes_total",
                    "Wire bytes moved by the socket transport",
                    labels=("direction",)),
    )


# ---------------------------------------------------------------------------
# the Transport interface + both implementations
# ---------------------------------------------------------------------------

class LoopbackTransport:
    """In-process transport: ops execute directly against the local store
    object — byte-for-byte today's behavior, zero serialization. The
    default (``transport_mode loopback``), and the zero-touch guarantee
    the BENCH_SERVE 2-hop micro band pins."""

    mode = "loopback"

    def fetch(self, shard: int, store, op: str, args: tuple):
        return run_op(op, store, *args)

    def dispatch(self, fn, *args):
        """Compiled-chain dispatch seam (parallel/dist_engine.py): the
        mesh is process-local on every backend we have, so both
        transports execute in place — the seam exists so the call path
        is the same object the fetch path routes through."""
        return fn(*args)

    def snapshot(self, shard: int, store):
        """Migration transfer copy (runtime/migration.py clone phase)."""
        from wukong_tpu.store.persist import clone_gstore

        return clone_gstore(store)

    def peer_for(self, shard: int):
        return None

    def close(self) -> None:
        pass


class SocketTransport(LoopbackTransport):
    """Framed TCP transport to the per-shard-group worker processes.

    Shards with a registered peer fetch over the wire; shards without one
    (or whose worker is being restarted) fall back to the local store —
    the parent keeps the authoritative copy, so correctness never depends
    on a worker being alive, only the process-isolation story does."""

    mode = "socket"

    def __init__(self, timeout_ms: int | None = None,
                 connect_timeout_ms: int | None = None):
        from wukong_tpu.runtime.resilience import CircuitBreaker

        self._timeout_ms = timeout_ms
        self._connect_timeout_ms = connect_timeout_ms
        self.peers: dict[int, tuple] = {}  # lock-free: whole-entry puts/pops; fetch reads a snapshot get
        # per-(peer, shard) breaker: a sick worker is routed around per
        # shard, independent of the parent-side sstore breaker
        self.breaker = CircuitBreaker()
        # addr -> (sock, decoder, leaf send/recv lock)
        self._conns: dict[tuple, tuple] = {}  # guarded by: _conn_lock
        self._conn_lock = make_lock("transport.conn")
        self._m_msgs, self._m_bytes = _metrics()

    # -- peer registry ---------------------------------------------------
    def register_peer(self, shard: int, addr: tuple) -> None:
        self.peers[int(shard)] = tuple(addr)

    def deregister_peer(self, shard: int) -> None:
        self.peers.pop(int(shard), None)
        self.breaker.record_success(int(shard))

    def peer_for(self, shard: int):
        return self.peers.get(int(shard))

    # -- connection management ------------------------------------------
    @property
    def timeout_s(self) -> float:
        ms = (self._timeout_ms if self._timeout_ms is not None
              else Global.transport_timeout_ms)
        return max(int(ms), 1) / 1000.0

    @property
    def connect_timeout_s(self) -> float:
        ms = (self._connect_timeout_ms if self._connect_timeout_ms is not None
              else Global.transport_connect_timeout_ms)
        return max(int(ms), 1) / 1000.0

    def _connection(self, addr: tuple):
        from wukong_tpu.runtime import faults

        with self._conn_lock:
            ent = self._conns.get(addr)
        if ent is not None:
            return ent
        faults.site("transport.connect")
        sock = socket.create_connection(addr,
                                        timeout=self.connect_timeout_s)
        sock.settimeout(self.timeout_s)
        ent = (sock, FrameDecoder(), make_lock("transport.conn"))
        with self._conn_lock:
            old = self._conns.get(addr)
            if old is not None:
                # lost the connect race: keep the established one
                sock.close()
                return old
            self._conns[addr] = ent
        return ent

    def _drop_connection(self, addr: tuple) -> None:
        with self._conn_lock:
            ent = self._conns.pop(addr, None)
        if ent is not None:
            try:
                ent[0].close()
            except OSError:
                pass

    def close(self) -> None:
        with self._conn_lock:
            conns, self._conns = dict(self._conns), {}
        for (sock, _dec, _lk) in conns.values():
            try:
                sock.close()
            except OSError:
                pass

    # -- the wire call ---------------------------------------------------
    def call(self, addr: tuple, op: str, sid: int, args: tuple = ()):
        """One framed request/reply on the peer connection. Socket-level
        failures surface as TransientFault (the connection is dropped so
        the retry reconnects); the caller's retry_call owns the backoff."""
        from wukong_tpu.runtime import faults

        frame = encode_frame(pack_message(op, sid, args))
        try:
            sock, dec, lk = self._connection(addr)
        except OSError as e:
            self._m_msgs.labels(op=op, result="connect_error").inc()
            raise faults.TransientFault(
                f"transport connect to {addr} failed: {e}") from e
        try:
            with lk:
                faults.site("transport.send")
                sock.sendall(frame)
                self._m_bytes.labels(direction="sent").inc(len(frame))
                while True:
                    faults.site("transport.recv")
                    chunk = sock.recv(1 << 20)
                    if not chunk:
                        raise TransportCorrupt(
                            f"peer {addr} closed mid-reply (torn frame "
                            "dropped; the request was never acknowledged)")
                    self._m_bytes.labels(direction="recv").inc(len(chunk))
                    frames = dec.feed(chunk)
                    if frames:
                        break
        except (OSError, TransportCorrupt, faults.TransientFault) as e:
            # one request per connection at a time (the leaf lock), so a
            # failed exchange leaves no interleaved reply behind: drop
            # the connection and let the retry rebuild it
            self._drop_connection(addr)
            self._m_msgs.labels(op=op, result="error").inc()
            if isinstance(e, faults.TransientFault):
                raise
            raise faults.TransientFault(
                f"transport {op} to {addr} failed: {e}") from e
        self._m_msgs.labels(op=op, result="ok").inc()
        return unpack_reply(frames[0])

    def _retry_call(self, shard: int, op: str, args: tuple):
        from wukong_tpu.runtime import faults
        from wukong_tpu.runtime.resilience import retry_call

        addr = self.peers.get(int(shard))
        if addr is None:
            raise ShardUnavailable(
                f"no transport peer registered for shard {shard}",
                shard=int(shard))
        return retry_call(
            lambda: self.call(addr, op, int(shard), args),
            site=f"transport.{op}[{shard}@{addr[1]}]",
            retry_on=(faults.TransientFault,),
            breaker=self.breaker, key=(addr, int(shard)))

    # -- Transport interface --------------------------------------------
    def fetch(self, shard: int, store, op: str, args: tuple):
        if int(shard) not in self.peers:
            # no worker owns this shard (or it was deregistered for a
            # restart window): the parent's copy is authoritative
            return run_op(op, store, *args)
        return self._retry_call(int(shard), op, args)

    def snapshot(self, shard: int, store):
        """Migration transfer as a real transport copy: pull the shard
        from its worker over the wire when one serves it (after a WAL
        catch-up to the parent's committed seq, so the copy is exact at
        the caller's mutation-locked snapshot point); otherwise round-trip
        the parent's copy through the checkpoint wire codec — the same
        bytes a remote pull would move."""
        from wukong_tpu.store.persist import gstore_from_bytes, gstore_to_bytes
        from wukong_tpu.store.wal import active_wal

        if int(shard) in self.peers:
            wal = active_wal()
            upto = (wal.next_seq - 1) if wal is not None else -1
            self._retry_call(int(shard), "sync", (upto,))
            blob = self._retry_call(int(shard), "snapshot", ())
        else:
            blob = gstore_to_bytes(store)
            self._m_bytes.labels(direction="local").inc(len(blob))
        return gstore_from_bytes(blob)


def make_transport():
    """The sharded store's construction-time transport choice: the
    ``transport_mode`` knob (loopback default; ``socket`` arms the wire
    path, whose peers the process supervisor registers as workers come
    up — peerless sockets serve locally, so flipping the knob alone is
    still byte-identical)."""
    mode = (Global.transport_mode or "loopback").strip().lower()
    if mode == "socket":
        return SocketTransport()
    if mode != "loopback":
        raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                          f"unknown transport_mode {mode!r} "
                          "(expected loopback|socket)")
    return LoopbackTransport()
