"""The materialized-view serving plane (ROADMAP item 7's actuator).

Two rungs over PR 13's serving-cache observatory:

- :mod:`wukong_tpu.serve.result_cache` — rung i, the version-keyed
  full-result cache in the proxy reply path (admission by the popularity
  ledger's verdicts, request collapsing, bounded bytes);
- :mod:`wukong_tpu.serve.views` — rung ii, hot templates promoted into
  incrementally-maintained standing results via the Wukong+S semi-naive
  delta planner, so cache hits survive store-version edges.

:func:`notify_mutation` is THE mutation hook (the ``cache.invalidate``
edge set, ``MUTATION_EDGES`` — gate-enforced against
``INVALIDATION_CAUSES``): insert batches and stream epochs call it
INSIDE the WAL-mutation-locked commit so a view is never visible at a
version it doesn't match; migration cutover and recovery restore call
it at their swap points for the conservative purge. One knob check when
the cache is off (``enable_result_cache``, default OFF — the serving
path is byte-for-byte unchanged, the PR 12 actuator posture).
"""

from __future__ import annotations

from wukong_tpu.config import Global
from wukong_tpu.serve.result_cache import ResultCache
from wukong_tpu.serve.views import ViewRegistry

__all__ = ["ServePlane", "get_serve", "notify_mutation"]


class ServePlane:
    """The process-wide serving-reuse plane: one result cache + one view
    registry, wired so a cache key's version-edge votes promote its
    template and a view's survival verdict re-keys its entries."""

    def __init__(self):
        self.cache = ResultCache()
        self.views = ViewRegistry()
        self.cache.on_promote = self.views.promote

    def attach(self, gstore, str_server) -> None:
        """Bind to a (new) serving world (the proxy's host partition):
        stale entries and old-world view registrations drop."""
        self.views.attach(gstore, str_server)
        self.cache.purge()

    def on_mutation(self, cause: str, version=None, triples=None) -> None:
        """One journaled mutation edge (MUTATION_EDGES semantics)."""
        if cause in ("cutover", "restore"):
            self.cache.purge()
            return
        survivors = set()
        if Global.enable_views and triples is not None:
            survivors = self.views.on_mutation(triples, version or 0)
        self.cache.apply_edge(version or 0, survivors)

    def reset(self) -> None:
        from wukong_tpu.serve.result_cache import reset_divergence

        self.cache.reset()
        self.views.reset()
        reset_divergence()


_plane = ServePlane()


def get_serve() -> ServePlane:
    return _plane


def notify_mutation(cause: str, version=None, triples=None,
                    shard=None) -> None:
    """THE serving-plane mutation hook (cache-coherence gate contract:
    every declared invalidation cause has exactly this consumer). One
    knob check when the result cache is off."""
    if not Global.enable_result_cache:
        return
    _plane.on_mutation(cause, version=version, triples=triples)
