"""The version-keyed result cache: ROADMAP item 7's rung i, the actuator.

PR 13's serving-cache observatory (obs/reuse.py) proved what a
version-keyed full-result cache would achieve — an 86% hit rate on the
Zipfian read-mostly mix — and journaled exactly which mutation paths
would invalidate it. This module is that cache, built to the PR 12
posture: a pure actuator over an already-landed decision substrate.

- **The key is the shadow cache's key, verbatim**: ``classify(q)``'s
  material (plan-cache signature digest + abstracted constants + filters
  + projection + blind mode) plus the PLAN-time store version
  (``q._rver`` — the version the read executed under, stashed where the
  plan cache read it). A write landing between plan and reply can never
  file a result under a version the read did not see.
- **Admission reads the observatory, never its own counters**: a reply
  is admitted only when the popularity ledger's arrival/cacheability
  verdict for its template says yes — read through
  :func:`wukong_tpu.obs.reuse.read_cache_input` by the literal
  ``CACHE_INPUTS`` names declared in :data:`CONSUMED_INPUTS`
  (the ``PLACEMENT_INPUTS``/``ADMISSION_INPUTS`` consumer contract,
  gate-enforced). With ``enable_reuse`` off the ledger is empty and the
  cache admits nothing: the actuator is inert without its substrate.
- **Request collapsing** (the heavy lane's per-template chaining posture
  applied to the light path): concurrent misses on the same key elect
  ONE leader; followers wait on the leader's settlement and re-probe —
  a thundering herd on a hot template costs one execution, not N.
- **Bounded bytes** (``result_cache_mb``): entries are LRU-evicted by
  held bytes; an entry over a quarter of the budget is refused outright
  (one mega-result must not evict the whole working set).
- **Invalidation is the four journaled ``cache.invalidate`` edges**
  (:data:`MUTATION_EDGES`, keys == ``INVALIDATION_CAUSES`` —
  gate-enforced): insert batches and stream epochs drop stale-version
  entries (or re-key them when a materialized view proves the template
  untouched — serve/views.py, rung ii); migration cutover and recovery
  restore purge conservatively (their version counters are not
  comparable across the swap).

Result tables are stored write-protected (``setflags(write=False)``)
and handed back by reference: a hit costs dict probes and metadata
copies, never an array copy, and any downstream mutation attempt raises
instead of corrupting the cached bytes.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.types import AttrType
from wukong_tpu.utils.timer import get_usec

_SID = int(AttrType.SID_t)

#: every observatory signal this cache's admission path consumes, by its
#: ``CACHE_INPUTS`` name — read exclusively through
#: ``obs.reuse.read_cache_input`` (the cache-coherence gate verifies each
#: entry is a declared cache input)
CONSUMED_INPUTS = ("template_popularity", "uncacheable")

#: what the serving plane does on each journaled mutation edge. The keys
#: must equal ``obs/reuse.py::INVALIDATION_CAUSES`` exactly
#: (gate-enforced): a mutation class the observatory journals but the
#: actuator ignores would serve stale bytes silently.
MUTATION_EDGES = {
    "insert": "drop stale-version entries; re-key entries whose "
              "materialized view proves the template untouched",
    "epoch": "drop stale-version entries; re-key entries whose "
             "materialized view proves the template untouched",
    "cutover": "conservative full purge (read-path swap: version "
               "counters are not comparable across the publication)",
    "restore": "conservative full purge (checkpointed world: restored "
               "versions are not comparable to the cached keys')",
    "vector": "drop stale-version entries (embedding mutations carry no "
              "triples, so no view can prove a template untouched — every "
              "key re-keys at the bumped version or dies)",
}

#: ceiling on a follower's wait for its leader's settlement (a wedged
#: leader surfaces as a plain miss, never a hung client); the member's
#: own deadline tightens it further
COLLAPSE_WAIT_S = 60.0

# entries / in-flight leader table / promotion votes are dict updates
# only — innermost by construction, like reuse.ledger/reuse.shadow (the
# probe fires from the serving path, the edge hook under the WAL
# mutation lock; nothing is ever acquired under it)
declare_leaf("serve.cache")

_M_CACHE = get_registry().counter(
    "wukong_result_cache_total",
    "Real result-cache outcomes (hit/miss per probe; fill/evict/killed "
    "per entry; collapsed per follower served off a leader's execution; "
    "refused per reply the admission rules rejected)",
    labels=("result",))
_M_DIVERGE = get_registry().counter(
    "wukong_cache_divergence_total",
    "Probes where the real result cache and the shadow cache disagreed "
    "on the same key (hit vs miss)")

# pre-resolved label children for the per-probe outcomes: labels() costs
# a kwargs hash + dict probe per call, and the hit path pays it per reply
_C_HIT = _M_CACHE.labels(result="hit")
_C_MISS = _M_CACHE.labels(result="miss")
_C_FILL = _M_CACHE.labels(result="fill")
_C_REFUSED = _M_CACHE.labels(result="refused")


def _modifier_refusal(q) -> str | None:
    """Result-shaping modifiers and attribute patterns change the reply
    BYTES without changing the shadow key — a result cache must refuse
    them (the shadow's key covers the plan cache's refusals; these are
    the reply-side shapes only a byte cache cares about)."""
    if q.distinct or q.orders or q.limit >= 0 or q.offset > 0:
        return "modifier"
    if getattr(q, "mt_factor", 1) > 1:
        return "mt_factor"
    if any(p.pred_type != _SID for p in q.pattern_group.patterns):
        return "attr"
    return None


class _Entry:
    """One cached reply: the write-protected result table + the metadata
    needed to rebuild a byte-identical reply object."""

    __slots__ = ("version", "table", "v2c_map", "col_num", "nrows",
                 "blind", "required_vars", "nvars", "nbytes", "t_us",
                 "cost_us")

    def __init__(self, version: int, q) -> None:
        res = q.result
        table = res.table
        table.setflags(write=False)
        self.version = int(version)
        self.table = table
        self.v2c_map = dict(res.v2c_map)  # lock-free: write-once snapshot, never mutated after construction
        self.col_num = int(res.col_num)
        self.nrows = int(res.nrows)
        self.blind = bool(res.blind)
        self.required_vars = list(res.required_vars)  # lock-free: write-once snapshot, never mutated after construction
        self.nvars = int(res.nvars)
        self.nbytes = int(table.nbytes) + 256  # metadata overhead
        self.t_us = get_usec()
        # recompute cost: the leader's measured execution time (stamped
        # by _Lease.settle), else a rows-based estimate — the cost-model
        # admission bar and eviction scoring read this
        self.cost_us = (max(float(q.__dict__.get("_exec_us", 0.0)), 0.0)
                        or self.nrows * 2.0 + 50.0)


class _Lease:
    """The leader's obligation: settle (fill on success, or just release)
    exactly once, waking every follower queued on the key."""

    __slots__ = ("cache", "key", "version", "event", "_settled", "t0_us")

    def __init__(self, cache: "ResultCache", key, version: int,
                 event: threading.Event) -> None:
        self.cache = cache
        self.key = key
        self.version = version
        self.event = event
        self._settled = False
        self.t0_us = get_usec()  # recompute-cost clock (cost model)

    def settle(self, q) -> None:
        if self._settled:  # idempotent: finally-paths may double-call
            return
        self._settled = True
        # the lease's lifetime IS the leader's execution: stamp the
        # recompute cost for the fill's cost-model admission (unless an
        # outer layer already measured it more precisely)
        if "_exec_us" not in q.__dict__:
            q._exec_us = get_usec() - self.t0_us
        try:
            self.cache.fill(self.key, self.version, q)
        finally:
            with self.cache._lock:
                if self.cache._inflight.get(self.key) is self.event:
                    self.cache._inflight.pop(self.key, None)
            self.event.set()


class ResultCache:
    """Bounded-bytes version-keyed full-result cache with request
    collapsing. One live version per key material: a fill replaces any
    older-version entry (which a version bump made unreachable anyway).
    """

    def __init__(self, capacity_mb: int | None = None):
        self._capacity_mb = capacity_mb
        self._lock = make_lock("serve.cache")
        self._entries: OrderedDict = OrderedDict()  # guarded by: _lock
        # key -> the collapsing leader's settlement Event
        self._inflight: dict = {}  # guarded by: _lock
        # version-edge promotion votes: material -> (last fill version,
        # edge-refill count) — the rung-ii promotion signal ("stays hot
        # across version edges"), bounded like reuse._DIGESTS
        self._votes: dict = {}  # guarded by: _lock
        self._votes_cap = 8192
        # (query text, blind) -> key material, learned at fill time: the
        # zero-parse fast path resolves repeated texts straight to their
        # cache key, skipping parse + plan entirely on a hit. Bounded
        # like _votes; entries never go stale (a text's material depends
        # only on the text — version freshness is checked per probe).
        self._texts: dict = {}  # guarded by: _lock
        self.bytes_held = 0  # guarded by: _lock
        self.hits = 0  # guarded by: _lock
        self.misses = 0  # guarded by: _lock
        self.fills = 0  # guarded by: _lock
        self.evicts = 0  # guarded by: _lock
        self.killed = 0  # guarded by: _lock
        self.collapsed = 0  # guarded by: _lock
        self.refused = 0  # guarded by: _lock
        self.purges = 0  # guarded by: _lock
        # rung-ii wiring (set by the ServePlane): called as
        # on_promote(material, text) when a key's votes cross
        # view_promote_edges
        self.on_promote = None

    def _cap_bytes(self) -> int:
        mb = self._capacity_mb or max(int(Global.result_cache_mb), 1)
        return int(mb) << 20

    # ------------------------------------------------------------------
    # the serving path
    # ------------------------------------------------------------------
    def acquire(self, q) -> tuple[bool, "_Lease | None"]:
        """One serving-path probe for a PLANNED query. Returns
        ``(served, lease)``: served=True installed a cached reply (done);
        otherwise the caller must execute, and a non-None lease makes it
        the key's collapsing leader (settle it in a finally)."""
        from wukong_tpu.obs.reuse import classify

        version = q.__dict__.get("_rver")
        if version is None:  # no plan-time version: user plan file etc.
            return False, None
        reason = _modifier_refusal(q)
        if reason is None:
            key, reason = classify(q)
            # the reply-side observatory reuses this verdict instead of
            # re-classifying (modifier refusals are NOT stashed: their
            # reasons are cache-local, not UNCACHEABLE_REASONS members)
            q._ckey = (key, reason)
        if reason is not None:
            _C_REFUSED.inc()
            with self._lock:
                self.refused += 1
            return False, None
        served, lease, wait = self._probe(key, int(version), q)
        if wait is None:
            return served, lease
        # follower: wait out the leader's execution, then re-probe once
        timeout = COLLAPSE_WAIT_S
        dl = getattr(q, "deadline", None)
        if dl is not None:
            rem = dl.remaining_s()
            if rem is not None:
                timeout = min(max(rem, 0.0), COLLAPSE_WAIT_S)
        wait.wait(timeout)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.version == int(version):
                self._entries.move_to_end(key)
                self.hits += 1
                self.collapsed += 1
            else:
                ent = None
                self.misses += 1
        if ent is not None:
            _C_HIT.inc()
            _M_CACHE.labels(result="collapsed").inc()
            self._install(q, ent)
            return True, None
        # the leader failed or was refused admission: execute directly
        # (no new lease — a failing key must not convoy its followers)
        _C_MISS.inc()
        q._rc_probe = "miss"
        return False, None

    def _probe(self, key, version: int, q):
        """(served, lease, wait_event) under one lock acquisition."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.version == version:
                self._entries.move_to_end(key)
                self.hits += 1
                hit = ent
            else:
                hit = None
                ev = self._inflight.get(key)
                if ev is not None:
                    return False, None, ev  # follower: wait outside
                self._inflight[key] = ev = threading.Event()
                lease = _Lease(self, key, version, ev)
                self.misses += 1
        if hit is not None:
            _C_HIT.inc()
            self._install(q, hit)
            return True, None, None
        _C_MISS.inc()
        q._rc_probe = "miss"
        return False, lease, None

    def fast_probe(self, text: str, blind: bool, version: int):
        """The zero-parse fast path's probe: resolve a repeated query
        text straight to its key material (learned at fill time) and
        return ``(key, entry)`` on a fresh-version hit, else None — the
        caller falls through to the full parse/plan/probe path. Counts
        as a hit; misses are NOT counted here (the slow path will probe
        and count the same key properly)."""
        with self._lock:
            key = self._texts.get((text, blind))
            if key is None:
                return None
            ent = self._entries.get(key)
            if ent is None or ent.version != version:
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        _C_HIT.inc()
        return key, ent

    def build_reply(self, key, ent: "_Entry"):
        """A reply shell for a fast-path hit: a fresh SPARQLQuery with
        the cached result installed and the classification verdict
        stashed (the reply-side observatory never needs the patterns)."""
        from wukong_tpu.sparql.ir import SPARQLQuery

        q = SPARQLQuery()
        self._install(q, ent)
        res = q.result
        res.required_vars = list(ent.required_vars)
        res.nvars = ent.nvars
        q._ckey = (key, None)
        q._rver = ent.version
        return q

    def _vote_locked(self, key, version: int) -> int:  # caller holds: _lock
        """Promotion bookkeeping at fill time: a re-fill at a NEWER
        version than the key's last fill means the template stayed hot
        across a store-version edge — rung ii's promotion signal.
        Returns the key's accumulated edge votes."""
        if len(self._votes) >= self._votes_cap:
            self._votes.clear()  # rare full reset beats an LRU here
        last, n = self._votes.get(key, (None, 0))
        if last is not None and last < version:
            n += 1
        self._votes[key] = (version, n)
        return n

    @staticmethod
    def _install(q, ent: "_Entry") -> None:
        """Rebuild the reply from a cached entry (the table is shared,
        write-protected; metadata is copied)."""
        from wukong_tpu.utils.errors import ErrorCode

        res = q.result
        res.status_code = ErrorCode.SUCCESS
        res.complete = True
        res.dropped_patterns = []
        res.table = ent.table
        res.nrows = ent.nrows
        res.col_num = ent.col_num
        res.v2c_map = dict(ent.v2c_map)
        res.blind = ent.blind
        q.pattern_step = len(q.pattern_group.patterns)
        q._rc_probe = "hit"

    # ------------------------------------------------------------------
    # fills + admission
    # ------------------------------------------------------------------
    @staticmethod
    def _admit_bar(ent: "_Entry") -> int:
        """The popularity bar this entry must clear, cost-weighted
        (``result_cache_cost_model``): bytes held per microsecond of
        recompute saved is the caching-benefit density — a bulky reply
        that recomputes cheaply must prove 2-4x the popularity before it
        may displace working-set bytes, while compact expensive entries
        keep the base bar. Off-knob: the flat ``result_cache_min_reads``."""
        base = max(int(Global.result_cache_min_reads), 0)
        if not Global.result_cache_cost_model:
            return base
        density = ent.nbytes / max(ent.cost_us, 1.0)  # bytes per us saved
        if density >= 4096.0:
            return max(base, 1) * 4
        if density >= 512.0:
            return max(base, 1) * 2
        return base

    def _pick_victim_locked(self, keep):  # caller holds: _lock
        """Eviction victim: pure LRU head off-knob; with the cost model
        on, the LOWEST benefit score (recompute us per byte held) among
        the 8 oldest entries — a cheap-to-recompute giant goes before an
        expensive small entry even when slightly fresher. ``keep`` (the
        just-filled key) is never chosen."""
        it = (k for k in self._entries if k != keep)
        victim = next(it)
        if not Global.result_cache_cost_model:
            return victim
        best = (self._entries[victim].cost_us
                / max(self._entries[victim].nbytes, 1))
        for _ in range(7):
            k = next(it, None)
            if k is None:
                break
            s = self._entries[k].cost_us / max(self._entries[k].nbytes, 1)
            if s < best:
                victim, best = k, s
        return victim

    def fill(self, key, version: int, q) -> bool:
        """Admit one executed reply (the leader's settlement path).
        Admission: SUCCESS + complete, the popularity ledger's verdict
        for the template (read through the ``CACHE_INPUTS`` map), and
        the byte bound."""
        from wukong_tpu.obs.reuse import read_cache_input
        from wukong_tpu.utils.errors import ErrorCode

        res = q.result
        if res.status_code != ErrorCode.SUCCESS or not res.complete:
            _C_REFUSED.inc()
            with self._lock:
                self.refused += 1
            return False
        # the popularity/cacheability verdict, with THIS reply counted as
        # its own evidence (the ledger charges at the reply point, after
        # this fill): reads+1 must clear the arrival bar — weighted by
        # the entry's cost model (cheap-to-recompute giants must prove
        # MORE popularity) — and a template never seen before is clean
        # by definition
        ent = _Entry(version, q)
        v = read_cache_input("template_popularity", template=key[0])
        unc = read_cache_input("uncacheable", template=key[0])
        if (v["reads"] + 1 < self._admit_bar(ent)
                or (v["reads"] > 0 and sum(unc.values()) > 0)):
            _C_REFUSED.inc()
            with self._lock:
                self.refused += 1
            return False
        cap = self._cap_bytes()
        if ent.nbytes > cap // 4:
            _C_REFUSED.inc()
            with self._lock:
                self.refused += 1
            return False
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes_held -= old.nbytes
                self.killed += 1  # the version bump already made it stale
            self._entries[key] = ent
            self.bytes_held += ent.nbytes
            self.fills += 1
            while self.bytes_held > cap and len(self._entries) > 1:
                _k = self._pick_victim_locked(keep=key)
                dead = self._entries.pop(_k)
                self.bytes_held -= dead.nbytes
                evicted += 1
            self.evicts += evicted
            votes = self._vote_locked(key, int(version))
            # teach the zero-parse fast path this text's key material
            text = q.__dict__.get("_qtext")
            if text:
                if len(self._texts) >= self._votes_cap:
                    self._texts.clear()
                self._texts[(text, ent.blind)] = key
        _C_FILL.inc()
        if old is not None:
            _M_CACHE.labels(result="killed").inc()
        if evicted:
            _M_CACHE.labels(result="evict").inc(evicted)
        # rung-ii promotion: the template survived view_promote_edges
        # version edges while staying hot — hand it to the view registry
        if (self.on_promote is not None and Global.enable_views
                and votes >= max(int(Global.view_promote_edges), 1)):
            text = q.__dict__.get("_qtext")
            if text:
                self.on_promote(key, text)
        return True

    # ------------------------------------------------------------------
    # mutation edges (ServePlane.on_mutation; caller holds the WAL
    # mutation lock on insert/epoch edges)
    # ------------------------------------------------------------------
    def apply_edge(self, new_version: int, survivors) -> int:
        """One append-only version edge: entries whose material a
        materialized view proved untouched are re-keyed to the new
        version (the hit survives the write); every other stale-version
        entry drops. Returns the kill count.

        Only entries at the IMMEDIATE pre-edge version re-key: this
        edge's survivorship proves only that THIS batch left the
        template's bytes unchanged. An entry that lagged further (a fill
        that raced an earlier edge landed at an older version while the
        template had no resident entry to judge) never received that
        edge's touch verdict — re-keying it could publish bytes a
        touching write already changed, so it drops instead. Mutation
        edges bump the host version by exactly one (one insert_triples
        per batch/epoch), so the pre-edge version is new_version - 1."""
        new_version = int(new_version)
        killed = 0
        with self._lock:
            for key in list(self._entries):
                ent = self._entries[key]
                if ent.version == new_version:
                    continue  # a racing fill already refreshed it
                if key in survivors and ent.version == new_version - 1:
                    ent.version = new_version
                else:
                    self.bytes_held -= ent.nbytes
                    del self._entries[key]
                    killed += 1
            self.killed += killed
        if killed:
            _M_CACHE.labels(result="killed").inc(killed)
        return killed

    def purge(self) -> int:
        """Conservative full purge (cutover/restore edges, world
        re-attach): every entry drops; in-flight leaders settle normally
        (their fills land at post-purge versions)."""
        with self._lock:
            killed = len(self._entries)
            self._entries.clear()
            self.bytes_held = 0
            self.killed += killed
            self.purges += 1
            self._votes.clear()
            # a purge may mean a NEW WORLD (attach/restore): the same
            # text then parses to different ids, so the text memo is
            # conservatively dropped with the entries
            self._texts.clear()
        if killed:
            _M_CACHE.labels(result="killed").inc(killed)
        return killed

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            probes = self.hits + self.misses
            return {"hits": self.hits, "misses": self.misses,
                    "hit_rate": (round(self.hits / probes, 4)
                                 if probes else None),
                    "entries": len(self._entries),
                    "bytes_held": self.bytes_held,
                    "capacity_bytes": self._cap_bytes(),
                    "fills": self.fills, "evicts": self.evicts,
                    "killed": self.killed, "collapsed": self.collapsed,
                    "refused": self.refused, "purges": self.purges,
                    "inflight": len(self._inflight)}

    def hit_rate(self) -> float | None:
        with self._lock:
            n = self.hits + self.misses
            return self.hits / n if n else None

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._votes.clear()
            self._texts.clear()
            self.bytes_held = 0
            self.hits = self.misses = self.fills = self.evicts = 0
            self.killed = self.collapsed = self.refused = self.purges = 0


# ---------------------------------------------------------------------------
# real-vs-shadow divergence (the observatory stays honest about itself)
# ---------------------------------------------------------------------------

_diverged = 0  # lock-free: GIL-atomic int bump; an approximate tally feeding a counter


def note_shadow_outcome(q, shadow_hit) -> None:
    """Fold the shadow cache's verdict for THIS reply against the real
    cache's (stamped on the query at probe time): a disagreement on the
    same key means the observatory's prediction model has drifted from
    the actuator it predicts — counted, never corrected silently."""
    global _diverged
    if shadow_hit is None:
        return
    real = q.__dict__.get("_rc_probe")
    if real is None:
        return
    if (real == "hit") != bool(shadow_hit):
        _diverged += 1
        _M_DIVERGE.inc()


def divergence_total() -> int:
    return _diverged


def reset_divergence() -> None:
    global _diverged
    _diverged = 0


# registry pull gauges: scrape-time reads of the live cache (the plane
# singleton resolves lazily so import order never matters)
def _plane_cache():
    from wukong_tpu.serve import get_serve

    return get_serve().cache


get_registry().gauge(
    "wukong_result_cache_bytes",
    "Result bytes held by the real serving cache"
).set_function(lambda: _plane_cache().stats()["bytes_held"])
get_registry().gauge(
    "wukong_result_cache_entries",
    "Entries resident in the real serving cache"
).set_function(lambda: _plane_cache().stats()["entries"])
