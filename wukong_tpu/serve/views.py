"""Materialized hot-template views: ROADMAP item 7's rung ii.

Rung i's result cache still dies on every store-version edge: the shadow
cache measured the consequence (86% -> 52% -> 28% hit rate as the write
rate rises 0 -> 2% -> 8%). This module is the Wukong+S answer — a hot
template that stays hot across version edges is promoted into an
*incrementally maintained* standing result, so its cache entry survives
writes instead of dying on every version bump.

The machinery is deliberately NOT new: a promoted template is registered
through :class:`wukong_tpu.stream.continuous.ContinuousEngine` — the
PR 2/9 semi-naive delta planner. Registration buys three things:

- **the rejection rules**: UNION / OPTIONAL / variable predicates /
  ORDER/LIMIT/OFFSET / cartesian shapes raise ``UNSUPPORTED_SHAPE`` at
  registration, exactly the shapes with no incremental semantics — the
  template is banned back to plain (version-keyed) cache entries;
- **the per-term plans**: each pattern's frontier-seeded remainder,
  planned once (``plan_seeded_group``), replayed per edge;
- **the SupportIndex**: per-result evidence bookkeeping, armed so the
  windowed retraction path (windows.py) applies unchanged if a view is
  ever scoped to a window (the append-only main store never retires
  epochs, so retraction never fires here — evidence is telemetry).

Per mutation edge (insert batch / stream epoch — called INSIDE the
WAL-mutation-locked commit, so a view is never visible at a version it
doesn't match) each view runs the semi-naive term union over the batch:
seed pattern i's frontier from the epoch delta (``match_delta``), run
the planned remainder against the merged store, and count DERIVED ROWS
— not fresh-vs-seen rows, because a duplicate derivation of an
already-known row still appends a duplicate row to the uncached reply
(non-dedup inserts are real), and byte-identity is the contract. Zero
derived rows across every term proves the template's reply bytes are
unchanged by the edge: the cache entry is RE-KEYED to the new version
and the hit survives the write. Any derived row marks the view touched:
its entry drops and the next read re-fills it at the new version (the
lazy refresh — the mutation-locked commit pays only the delta
evaluation, never a full re-execution).

Demotion (``view_demote_touch_pct``): a view touched on most recent
edges is paying delta evaluation per write for no surviving hits — it
is demoted back to plain cache entries, like a registration rejection.
"""

from __future__ import annotations

from wukong_tpu.analysis.lockdep import make_lock
from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.utils.errors import WukongError
from wukong_tpu.utils.logger import log_info, log_warn

_M_VIEWS = get_registry().counter(
    "wukong_views_total",
    "Materialized-view lifecycle events (promoted/rejected/demoted per "
    "template; survived/touched per view per mutation edge)",
    labels=("event",))
get_registry().gauge(
    "wukong_views_registered",
    "Templates currently maintained as materialized views"
).set_function(lambda: _registered_count())


def _registered_count() -> int:
    from wukong_tpu.serve import get_serve

    return get_serve().views.count()


class MaterializedView:
    """One promoted template: its standing-query registration plus the
    maintenance-economics counters the demotion rule reads."""

    __slots__ = ("material", "text", "qid", "edges_seen", "touched",
                 "survived")

    def __init__(self, material, text: str, qid: int):
        self.material = material
        self.text = text
        self.qid = qid
        self.edges_seen = 0
        self.touched = 0
        self.survived = 0


class ViewRegistry:
    """The promoted-template registry over one host partition.

    ``_lock`` is an ordinary tracked lock (NOT a lockdep leaf): it is
    held across standing-query registration and per-edge delta
    evaluation, both of which execute engine queries. ``on_mutation``
    additionally runs under the WAL mutation lock (its caller's), so
    maintenance is serialized against commits by construction.
    """

    def __init__(self):
        self._lock = make_lock("serve.views")
        # material -> MaterializedView / rejected+demoted materials /
        # the lazy ContinuousEngine + CPUEngine over the attached world
        self._views: dict = {}  # guarded by: _lock
        self._banned: set = set()  # guarded by: _lock
        self._ce = None  # guarded by: _lock
        self._engine = None  # guarded by: _lock
        self._g = None  # guarded by: _lock
        self._ss = None  # guarded by: _lock
        self.promoted = 0  # guarded by: _lock
        self.rejected = 0  # guarded by: _lock
        self.demoted = 0  # guarded by: _lock

    # ------------------------------------------------------------------
    def attach(self, gstore, str_server) -> None:
        """Bind to a (new) serving world: registrations from the old
        world are dropped wholesale — their plans reference its store."""
        with self._lock:
            if self._g is gstore:
                return
            self._g = gstore
            self._ss = str_server
            self._ce = None
            self._engine = None
            self._views.clear()
            self._banned.clear()

    def count(self) -> int:
        with self._lock:
            return len(self._views)

    # ------------------------------------------------------------------
    def promote(self, material, text: str) -> bool:
        """Register one hot template as a maintained view. Shapes the
        delta planner rejects (UNION/OPTIONAL/var-pred/LIMIT/cartesian)
        are banned back to plain cache entries."""
        if not Global.enable_views or not text:
            return False
        with self._lock:
            if (self._g is None or material in self._views
                    or material in self._banned):
                return False
            if len(self._views) >= max(int(Global.views_max), 1):
                return False
            if self._ce is None:
                from wukong_tpu.engine.cpu import CPUEngine
                from wukong_tpu.stream.continuous import ContinuousEngine

                self._engine = CPUEngine(self._g, self._ss)
                self._ce = ContinuousEngine(self._g, self._ss,
                                            engine=self._engine)
            try:
                qid = self._ce.register(text)
            except WukongError as e:
                # the delta-eval rejection rules: no incremental
                # semantics for this shape — plain cache entries only
                self._banned.add(material)
                self.rejected += 1
                _M_VIEWS.labels(event="rejected").inc()
                log_info(f"view promotion rejected ({e.code.name}): "
                         f"{text[:80]!r}")
                return False
            sq = self._ce.queries[qid]
            if sq.support is None:
                # arm the per-result evidence ledger (windows.py): the
                # retraction machinery's input, telemetry on the
                # append-only main store
                from wukong_tpu.stream.windows import SupportIndex

                sq.support = SupportIndex()
                sq.support.note_base(sq.seen)
            self._views[material] = MaterializedView(material, text, qid)
            self.promoted += 1
        _M_VIEWS.labels(event="promoted").inc()
        log_info(f"template promoted to a materialized view "
                 f"({material[0]}): {text[:80]!r}")
        return True

    # ------------------------------------------------------------------
    def on_mutation(self, triples, version: int) -> set:
        """One append-only edge (caller holds the WAL mutation lock):
        run every view's semi-naive term union over the batch and return
        the set of SURVIVOR materials — templates whose reply bytes the
        edge provably did not change. Touched views count toward the
        demotion rule."""
        import numpy as np

        survivors: set = set()
        if triples is None:
            return survivors
        triples = np.asarray(triples)
        with self._lock:
            if not self._views or self._ce is None:
                return survivors
            # epoch-batched device frontier (PR 19, consumer 3 of the
            # whole-plan compiled posture): EVERY view's per-term seed
            # extraction for this edge fuses into one device dispatch;
            # None (host knob / small epoch / a latched failure) keeps
            # the per-term host path, byte-identical by construction
            from wukong_tpu.stream.continuous import device_seed_extract

            flat: list = []
            spans: dict = {}
            for material, view in self._views.items():
                sq = self._ce.queries.get(view.qid)
                if sq is None:
                    continue
                spans[material] = (len(flat), len(flat) + len(sq.patterns))
                flat.extend(sq.patterns)
            all_seeds = device_seed_extract(flat, triples, owner=self)
            demote = []
            for material, view in self._views.items():
                sq = self._ce.queries.get(view.qid)
                if sq is None:
                    demote.append(material)
                    continue
                view.edges_seen += 1
                lo, hi = spans.get(material, (0, 0))
                touched = self._derives_rows(
                    sq, triples, version,
                    seeds=(all_seeds[lo:hi] if all_seeds is not None
                           else None))
                if touched:
                    view.touched += 1
                    _M_VIEWS.labels(event="touched").inc()
                else:
                    view.survived += 1
                    survivors.add(material)
                    _M_VIEWS.labels(event="survived").inc()
                # maintenance economics: a view touched on most edges
                # pays delta evaluation per write for no surviving hits
                pct = max(int(Global.view_demote_touch_pct), 1)
                if (view.edges_seen >= 8
                        and view.touched * 100 > pct * view.edges_seen):
                    demote.append(material)
            for material in demote:
                self._demote_locked(material)
        return survivors

    def _derives_rows(self, sq, triples, version: int,  # caller holds: _lock
                      seeds=None) -> bool:
        """The semi-naive term union, counting DERIVED rows (duplicates
        included): True when the batch contributes >=1 complete
        derivation — the reply bytes changed. Term failures are
        conservative touches (degraded, never a stale hit). ``seeds``
        carries this view's slice of the epoch-batched device frontier
        (on_mutation's single fused dispatch); None runs the per-term
        host extraction."""
        from wukong_tpu.stream.continuous import match_delta
        from wukong_tpu.utils.errors import ErrorCode

        derived = set()
        for i, pat in enumerate(sq.patterns):
            if seeds is not None:
                vars_, seed = seeds[i]
            else:
                vars_, seed = match_delta(pat, triples)
            if len(seed) == 0:
                continue
            q = self._ce._make_delta_query(sq, i, vars_, seed)
            try:
                out = self._engine.execute(q, from_proxy=False)
            except Exception as e:
                log_warn(f"view delta term {i} failed: {e!r}")
                return True
            if out.result.status_code != ErrorCode.SUCCESS:
                return True
            if out.result.nrows > 0:
                try:
                    derived |= self._ce._project(out.result,
                                                 sq.required_vars)
                except WukongError:
                    return True
        if derived:
            # evidence for the retraction machinery + the standing set
            # (the rows now derivable through this epoch's triples)
            if sq.support is not None:
                sq.support.note_epoch(version, derived)
            sq.seen |= derived
            return True
        return False

    def _demote_locked(self, material) -> None:  # caller holds: _lock
        view = self._views.pop(material, None)
        if view is None:
            return
        self._banned.add(material)
        self.demoted += 1
        try:
            self._ce.unregister(view.qid)
        except WukongError:
            pass
        _M_VIEWS.labels(event="demoted").inc()
        log_info(f"materialized view demoted (touched "
                 f"{view.touched}/{view.edges_seen} edges): "
                 f"{view.text[:80]!r}")

    # ------------------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            views = [{"template": v.material[0], "edges": v.edges_seen,
                      "touched": v.touched, "survived": v.survived,
                      "text": v.text[:96]}
                     for v in self._views.values()]
            return {"registered": len(self._views),
                    "capacity": max(int(Global.views_max), 1),
                    "promoted": self.promoted,
                    "rejected": self.rejected,
                    "demoted": self.demoted,
                    "banned": len(self._banned),
                    "views": views}

    def reset(self) -> None:
        with self._lock:
            self._views.clear()
            self._banned.clear()
            self._ce = None
            self._engine = None
            self.promoted = self.rejected = self.demoted = 0
