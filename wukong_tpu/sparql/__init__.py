from wukong_tpu.sparql.ir import (  # noqa: F401
    Filter,
    FilterType,
    Order,
    Pattern,
    PatternGroup,
    SPARQLQuery,
    SPARQLTemplate,
)
from wukong_tpu.sparql.parser import Parser, SPARQLSyntaxError  # noqa: F401
