"""SPARQL query intermediate representation.

Mirrors the reference IR (core/query.hpp): ``SPARQLQuery`` holds a
``PatternGroup`` tree (patterns / unions / optionals / filters), projection +
modifiers, and an execution ``Result``. Variables are negative ssids assigned in
order of first appearance; constants are positive ids (core/type.hpp:31).

The binding table (``Result``) is a row-major numpy table with a var -> column
map (query.hpp:251-558 — flat vector<sid_t> result_table + v2c_map), which maps
directly onto the device binding-table layout of the TPU engine.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

from wukong_tpu.types import OUT, AttrType
from wukong_tpu.utils.errors import ErrorCode, WukongError

NO_RESULT = -999  # v2c_map sentinel (query.hpp NO_RESULT_COL)


@dataclass
class Pattern:
    """One triple pattern step (query.hpp:96-116) with execution direction."""

    subject: int
    predicate: int
    direction: int
    object: int
    pred_type: int = int(AttrType.SID_t)  # attr patterns carry the value-type tag

    def __repr__(self):
        d = "<-" if self.direction == 0 else "->"
        return f"({self.subject} {self.predicate}{d}{self.object})"


class FilterType(enum.IntEnum):
    """Filter expression node types (query.hpp:141-147)."""

    Or = 0; And = 1; Equal = 2; NotEqual = 3; Less = 4; LessOrEqual = 5
    Greater = 6; GreaterOrEqual = 7; Plus = 8; Minus = 9; Mul = 10; Div = 11
    Not = 12; UnaryPlus = 13; UnaryMinus = 14; Literal = 15; Variable = 16
    IRI = 17; Function = 18; ArgumentList = 19; Builtin_str = 20
    Builtin_lang = 21; Builtin_langmatches = 22; Builtin_datatype = 23
    Builtin_bound = 24; Builtin_sameterm = 25; Builtin_isiri = 26
    Builtin_isblank = 27; Builtin_isliteral = 28; Builtin_regex = 29
    Builtin_in = 30


@dataclass
class Filter:
    type: FilterType
    arg1: "Filter | None" = None
    arg2: "Filter | None" = None
    arg3: "Filter | None" = None
    value: str = ""  # constant literal / IRI text
    valueArg: int = 0  # variable ssid for Variable nodes


@dataclass
class PatternGroup:
    """patterns + nested unions/optionals + filters (query.hpp:183-230)."""

    patterns: list = field(default_factory=list)
    unions: list = field(default_factory=list)
    filters: list = field(default_factory=list)
    optional: list = field(default_factory=list)
    optional_new_vars: set = field(default_factory=set)

    def get_start(self) -> int:
        if self.patterns:
            return self.patterns[0].subject
        if self.unions:
            return self.unions[0].get_start()
        if self.optional:
            return self.optional[0].get_start()
        raise WukongError(ErrorCode.UNKNOWN_PATTERN, "empty pattern group")


@dataclass
class Order:
    id: int  # variable ssid
    descending: bool = False


@dataclass
class KNNClause:
    """One ``knn(?x, <anchor>, k)`` clause (wukong_tpu/vector/).

    The anchor is EITHER a vertex (``anchor_vid``: rank by similarity to
    that vertex's stored embedding) OR a literal vector (``anchor_vec``:
    a parenthesized number list, dim-checked against the store at
    execution). Exactly one of the two is set. ``var`` is the ranked
    variable's negative ssid; ``metric`` defaults to the ``knn_metric``
    knob at execution when empty.
    """

    var: int
    k: int
    anchor_vid: int | None = None
    anchor_vec: np.ndarray | None = None
    metric: str = ""
    # composition direction, stamped by the parser from the TEXTUAL
    # pattern order (scan | rank_then_pattern | pattern_then_rank).
    # Decided pre-planning: a planner reorder must not flip the query's
    # semantics between "rank the binding set" and "seed the chain".
    mode: str = ""


class Result:
    """Flat row-major binding table + metadata (query.hpp:251-558)."""

    def __init__(self, nvars: int = 0):
        self.nvars = nvars
        self.col_num = 0
        self.attr_col_num = 0
        self.table = np.empty((0, 0), dtype=np.int64)  # [rows, col_num]
        self.attr_table = np.empty((0, 0), dtype=np.float64)
        self.v2c_map: dict[int, int] = {}  # var ssid -> column
        self.attr_v2c_map: dict[int, tuple[int, int]] = {}  # var -> (col, type)
        self.required_vars: list[int] = []
        self.blind = False
        self.status_code = ErrorCode.SUCCESS
        self.nrows = 0  # meaningful even when blind/table cleared
        self.optional_matched_rows: np.ndarray | None = None
        # resilience: False when the reply is a graceful degradation — a
        # deadline/budget expiry kept the rows produced so far, or a down
        # shard's contribution is missing. dropped_patterns lists what was
        # not executed / not fully served (pattern reprs or shard tags).
        self.complete = True
        self.dropped_patterns: list[str] = []

    def var2col(self, var: int) -> int:
        return self.v2c_map.get(var, NO_RESULT)

    def add_var2col(self, var: int, col: int, vtype: int = int(AttrType.SID_t)) -> None:
        if vtype == int(AttrType.SID_t):
            if var not in self.v2c_map:
                self.v2c_map[var] = col
        else:
            if var not in self.attr_v2c_map:
                self.attr_v2c_map[var] = (col, vtype)

    def is_attr_var(self, var: int) -> bool:
        return var in self.attr_v2c_map

    def get_row_num(self) -> int:
        return self.nrows

    def set_table(self, table: np.ndarray) -> None:
        self.table = table
        if table.ndim == 2:  # empty tables still carry their column count
            self.col_num = table.shape[1]
        self.nrows = len(table)

    def copy_meta_from(self, other: "Result") -> None:
        self.nvars = other.nvars
        self.required_vars = list(other.required_vars)
        self.blind = other.blind


class SQState(enum.IntEnum):
    SQ_PATTERN = 0
    SQ_UNION = 1
    SQ_FILTER = 2
    SQ_OPTIONAL = 3
    SQ_FINAL = 4
    SQ_REPLY = 5


class PGType(enum.IntEnum):
    BASIC = 0
    UNION = 1
    OPTIONAL = 2
    FILTER = 3


@dataclass
class SPARQLQuery:
    """Query execution state (query.hpp:560-720)."""

    pattern_group: PatternGroup = field(default_factory=PatternGroup)
    result: Result = field(default_factory=Result)
    orders: list = field(default_factory=list)
    qid: int = -1
    pqid: int = -1
    pg_type: PGType = PGType.BASIC
    state: SQState = SQState.SQ_PATTERN
    mt_factor: int = 1
    mt_tid: int = 0
    pattern_step: int = 0
    corun_enabled: bool = False
    corun_step: int = 0
    fetch_step: int = 0
    union_done: bool = False
    optional_step: int = 0
    limit: int = -1
    offset: int = 0
    distinct: bool = False
    local_var: int = 0
    # planner proved the result empty from exact type statistics (the
    # reference's is_empty short-circuit, planner.hpp:1505-1509: "identified
    # empty result query" — generate_plan returns false and the proxy skips
    # execution). Engines honor it under Global.enable_empty_shortcircuit.
    planner_empty: bool = False
    # per-query Deadline (runtime/resilience.py) — wall-clock + row budget.
    # None = unconstrained. Engines check it at each BGP step; the proxy
    # attaches one from the Global knobs and children inherit the parent's.
    deadline: object = None
    # tenant identity (obs/slo.py): stamped by the proxy at admission
    # (bounded to max_tenants label values) and carried proxy -> batcher
    # -> scheduler -> engines so every metric, trace, queue decision, and
    # shed counter downstream is tenant-attributable. "default" keeps the
    # single-tenant path byte-identical.
    tenant: str = "default"
    # hybrid graph+vector (wukong_tpu/vector/): the parsed KNNClause, or
    # None for a pure graph query. The proxy stamps knn_mode/knn_route
    # (setattr) at plan time; the engine composes the ranked scan with
    # the BGP per the mode. Pure graph queries never touch this field
    # beyond the one None check (enable_vectors zero-touch posture).
    knn: object = None

    def get_pattern(self, step: int | None = None) -> Pattern:
        s = self.pattern_step if step is None else step
        return self.pattern_group.patterns[s]

    @property
    def has_pattern(self) -> bool:
        return bool(self.pattern_group.patterns)

    def done_patterns(self) -> bool:
        return self.pattern_step >= len(self.pattern_group.patterns)

    def start_from_index(self) -> bool:
        """First pattern starts from a predicate/type index (query.hpp:660-682)."""
        from wukong_tpu.types import PREDICATE_ID, TYPE_ID, is_tpid

        pg = self.pattern_group
        if not pg.patterns:
            return False
        if is_tpid(pg.patterns[0].subject):
            if pg.patterns[0].predicate not in (PREDICATE_ID, TYPE_ID):
                raise WukongError(ErrorCode.OBJ_ERROR,
                                  "index start requires __PREDICATE__ or rdf:type")
            return True
        return False


@dataclass
class SPARQLTemplate:
    """Parsed template query with %type placeholders (query.hpp:820-856).

    ``ptypes`` lists the placeholder type/predicate ids in pattern order;
    ``pos`` the (pattern_idx, field) slots to patch. ``candidates`` is filled by
    the proxy (fill_template) with the per-placeholder candidate constants.
    """

    query: SPARQLQuery = field(default_factory=SPARQLQuery)
    ptypes: list = field(default_factory=list)  # placeholder type ids
    pos: list = field(default_factory=list)  # (pattern index, "subject"/"object")
    candidates: list = field(default_factory=list)  # list[np.ndarray]

    def instantiate(self, rng: np.random.Generator) -> SPARQLQuery:
        import copy

        q = copy.deepcopy(self.query)
        for i, (pi, fld) in enumerate(self.pos):
            cand = self.candidates[i]
            val = int(cand[rng.integers(0, len(cand))])
            setattr(q.pattern_group.patterns[pi], fld, val)
        return q
