"""SPARQL subset lexer/parser + AST -> IR translation.

Replaces the reference's hand-written SPARQLLexer/SPARQLParser + Parser
(core/SPARQLLexer.hpp, core/SPARQLParser.hpp, core/parser.hpp). Supported
surface (the subset the reference parses — SPARQLParser.hpp):

  PREFIX decls; SELECT [DISTINCT|REDUCED] ?vars|* WHERE { ... };
  triple patterns with '.' separators; nested { } groups; UNION; OPTIONAL;
  FILTER expressions (||, &&, comparisons, arithmetic, !, bound/isIRI/isBLANK/
  isLITERAL/str/regex builtins); ORDER BY [ASC()/DESC()] ; LIMIT; OFFSET;
  plus two Wukong extensions: %prefix:name template placeholders
  (SPARQLParser.hpp template ext; query.hpp:820-856) and the __PREDICATE__
  keyword for predicate-index patterns.

Translation (core/parser.hpp:83-124): variables become negative ssids in order
of first appearance; IRIs/literals resolve through the StringServer (unknown
strings raise SYNTAX_ERROR-class failures like the reference's UNKNOWN_SUB);
attribute predicates get their value-type tag from str_attr_index.
"""

from __future__ import annotations

import re

import numpy as np

from wukong_tpu.sparql.ir import (
    Filter,
    FilterType,
    KNNClause,
    Order,
    Pattern,
    PatternGroup,
    SPARQLQuery,
    SPARQLTemplate,
)
from wukong_tpu.types import OUT, AttrType
from wukong_tpu.utils.errors import ErrorCode, WukongError

RDF_TYPE_IRI = "<http://www.w3.org/1999/02/22-rdf-syntax-ns#type>"


class SPARQLSyntaxError(WukongError):
    def __init__(self, detail: str):
        super().__init__(ErrorCode.SYNTAX_ERROR, detail)


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<WS>\s+|\#[^\n]*)
  | (?P<IRI><[^<>\s]*>)
  | (?P<VAR>[?$][A-Za-z_][A-Za-z0-9_]*)
  | (?P<STRING>"(?:[^"\\]|\\.)*"(?:\^\^[^\s.;,)]+|@[A-Za-z][A-Za-z0-9-]*)?)
  | (?P<NUM>[+-]?\d+(?:\.\d+)?)
  | (?P<TEMPLATE>%(?:[A-Za-z_][A-Za-z0-9_-]*:[A-Za-z_][A-Za-z0-9_.-]*|<[^<>\s]*>))
  | (?P<PNAME>[A-Za-z_][A-Za-z0-9_-]*:[A-Za-z_][A-Za-z0-9_.-]*)
  | (?P<KEYWORD>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<OP>&&|\|\||!=|<=|>=|[{}().,;*=<>!+\-/:])
    """,
    re.VERBOSE,
)


def tokenize(text: str) -> list[tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise SPARQLSyntaxError(f"lexer error at: {text[pos:pos + 30]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind != "WS":
            tokens.append((kind, m.group()))
    tokens.append(("EOF", ""))
    return tokens


# ---------------------------------------------------------------------------
# Parser (tokens -> IR with symbolic terms, then id resolution)
# ---------------------------------------------------------------------------


class _Term:
    """Symbolic triple-pattern element before id resolution."""

    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: str):
        self.kind = kind  # var | iri | literal | template | predicate_kw
        self.value = value

    def __repr__(self):
        return f"{self.kind}:{self.value}"


class Parser:
    """parse(text) -> SPARQLQuery; parse_template(text) -> SPARQLTemplate."""

    def __init__(self, str_server=None):
        self.str_server = str_server

    # -- public API --------------------------------------------------------
    def parse(self, text: str) -> SPARQLQuery:
        q, tmpl = self._parse_full(text)
        if tmpl.pos:
            raise SPARQLSyntaxError("template placeholders in a non-template query")
        return q

    def parse_template(self, text: str) -> SPARQLTemplate:
        q, tmpl = self._parse_full(text)
        if not tmpl.pos:
            raise SPARQLSyntaxError("no %placeholders in template query")
        tmpl.query = q
        return tmpl

    # -- grammar -----------------------------------------------------------
    def _parse_full(self, text: str):
        self.toks = tokenize(text)
        self.i = 0
        self.prefixes: dict[str, str] = {}
        self.vars: dict[str, int] = {}  # ?name -> negative ssid
        self.template = SPARQLTemplate()
        self._knn: KNNClause | None = None  # set by _resolve_group
        self._knn_leading = False  # clause appeared before any pattern

        while self._peek_kw("PREFIX"):
            self._next()
            # prefix name is either "p:" (KEYWORD + ':') or a PNAME-looking token
            kind, val = self._next()
            if kind == "KEYWORD":
                self._expect_op(":")
                pre = val
            elif kind == "PNAME":
                pre = val.split(":", 1)[0]
            else:
                raise SPARQLSyntaxError(f"bad PREFIX name {val!r}")
            iri = self._expect("IRI")
            self.prefixes[pre] = iri

        # Wukong CORUN extension (SPARQLParser.hpp:937-958):
        # `CORUN <corun_step> <fetch_step>` before SELECT
        corun_enabled = False
        corun_step = fetch_step = 0
        if self._peek_kw("CORUN"):
            self._next()
            corun_step = int(self._expect("NUM"))
            fetch_step = int(self._expect("NUM"))
            corun_enabled = True

        self._expect_kw("SELECT")
        distinct = reduced = False
        if self._peek_kw("DISTINCT"):
            self._next()
            distinct = True
        elif self._peek_kw("REDUCED"):
            self._next()
            reduced = True
        proj: list[str] | None = []
        if self._peek()[1] == "*":
            self._next()
            proj = None
        else:
            while self._peek()[0] == "VAR":
                proj.append(self._next()[1])
            if not proj:
                raise SPARQLSyntaxError("SELECT needs at least one variable or *")

        self._expect_kw("WHERE")
        group = self._parse_group()

        orders: list[tuple[str, bool]] = []
        limit, offset = -1, 0
        while True:
            if self._peek_kw("ORDER"):
                self._next()
                self._expect_kw("BY")
                while True:
                    t = self._peek()
                    if t[0] == "VAR":
                        orders.append((self._next()[1], False))
                    elif t[0] == "KEYWORD" and t[1].upper() in ("ASC", "DESC"):
                        kw = self._next()[1].upper()
                        self._expect_op("(")
                        v = self._expect("VAR")
                        self._expect_op(")")
                        orders.append((v, kw == "DESC"))
                    else:
                        break
            elif self._peek_kw("LIMIT"):
                self._next()
                limit = int(self._expect("NUM"))
            elif self._peek_kw("OFFSET"):
                self._next()
                offset = int(self._expect("NUM"))
            else:
                break
        if self._peek()[0] != "EOF":
            raise SPARQLSyntaxError(f"unexpected trailing token {self._peek()[1]!r}")

        q = SPARQLQuery()
        q.pattern_group = self._resolve_group(group)
        q.knn = self._knn
        if q.knn is not None:
            # composition direction from the TEXTUAL layout, before any
            # planner reorder: a knn clause written BEFORE a chain that
            # starts at its variable is a seeded walk
            # (rank-then-pattern); a clause written AFTER the patterns
            # ranks their binding set (pattern-then-rank); no
            # patterns/unions/optionals at all is a pure ranked scan
            pg = q.pattern_group
            if not pg.patterns and not pg.unions and not pg.optional:
                q.knn.mode = "scan"
            elif (self._knn_leading and pg.patterns
                    and pg.patterns[0].subject == q.knn.var):
                q.knn.mode = "rank_then_pattern"
            else:
                q.knn.mode = "pattern_then_rank"
        pg = q.pattern_group
        if not pg.patterns and not pg.unions and pg.optional:
            # a leading OPTIONAL with no required patterns IS the base
            # (optional/q5): the reference's planner promotes the first
            # group to the start — LeftJoin(Unit, A) = A whenever A has
            # solutions, and both formulations yield zero rows otherwise
            first = pg.optional.pop(0)
            pg.patterns = first.patterns
            pg.filters = first.filters + pg.filters
            pg.unions = first.unions
            pg.optional = first.optional + pg.optional
        q.distinct = distinct or reduced
        q.limit = limit
        q.offset = offset
        q.corun_enabled = corun_enabled
        q.corun_step = corun_step
        q.fetch_step = fetch_step
        nvars = len(self.vars)
        q.result.nvars = nvars
        if proj is None:
            q.result.required_vars = sorted(self.vars.values(), reverse=True)
        else:
            q.result.required_vars = [self._var_id(v) for v in proj]
        for vname, desc in orders:
            q.orders.append(Order(self._var_id(vname), desc))
        return q, self.template

    def _parse_group(self) -> dict:
        """Returns a symbolic group {patterns, unions, optional, filters}."""
        self._expect_op("{")
        group = {"patterns": [], "unions": [], "optional": [],
                 "filters": [], "knn": []}
        while True:
            t = self._peek()
            if t[1] == "}":
                self._next()
                break
            if t[1] == ".":  # stray '.' after a group, e.g. OPTIONAL { } .
                self._next()
                continue
            if t[1] == "{":
                # { A } UNION { B } [UNION { C }]...
                sub = self._parse_group()
                if self._peek_kw("UNION"):
                    members = [sub]
                    while self._peek_kw("UNION"):
                        self._next()
                        members.append(self._parse_group())
                    group["unions"].extend(members)
                else:
                    # plain nested group: merge
                    for k in ("patterns", "unions", "optional", "filters",
                              "knn"):
                        group[k].extend(sub[k])
                continue
            if t[0] == "KEYWORD" and t[1].upper() == "OPTIONAL":
                self._next()
                group["optional"].append(self._parse_group())
                continue
            if t[0] == "KEYWORD" and t[1].upper() == "FILTER":
                self._next()
                group["filters"].append(self._parse_filter_expr())
                continue
            if t[0] == "KEYWORD" and t[1].upper() == "KNN":
                # hybrid extension: knn(?x, <anchor|(v0 v1 ...)>, k[, metric]).
                # Clause position disambiguates the composition: written
                # BEFORE the patterns it seeds the chain, AFTER it ranks
                # the binding set
                self._next()
                c = self._parse_knn_clause()
                c["leading"] = not group["patterns"]
                group["knn"].append(c)
                continue
            # triple pattern, with the ';' predicate-object-list and ','
            # object-list shorthand (SPARQLParser.hpp:771-809 parseGraphPattern)
            s = self._parse_term()
            p = self._parse_term(predicate=True)
            o = self._parse_term()
            group["patterns"].append((s, p, o))
            while self._peek()[1] in (";", ","):
                sep = self._next()[1]
                if sep == ";":
                    nk, nv = self._peek()
                    # trailing ';' may be followed by '.', '}', another group
                    # element, or more ';' (SPARQL PropertyListNotEmpty)
                    if nv in (";", ".", "}", "{") or (
                            nk == "KEYWORD"
                            and nv.upper() in ("FILTER", "OPTIONAL")):
                        continue
                    p = self._parse_term(predicate=True)
                o = self._parse_term()
                group["patterns"].append((s, p, o))
            # reference direction terminators '<-' / '->'
            # (SPARQLParser.hpp:820-829). They are pure EXECUTION-orientation
            # hints — '<-' swaps the pattern's endpoints with direction IN,
            # which matches the same triples — and both our planners
            # re-derive orientation from bindings, so the hint is accepted
            # and dropped (the planner-off pre-oriented path is served by
            # .fmt plan files' <</>> markers instead). Matched as TWO
            # one-char OP tokens: a '<-' lexer token would break
            # FILTER(?y<-1), which must stay '<' '-1'.
            nxt = self._peek()[1]
            if nxt in ("<", "-") and self.toks[self.i + 1][1] in ("-", ">")                     and (nxt, self.toks[self.i + 1][1]) in (("<", "-"),
                                                            ("-", ">")):
                self._next()
                self._next()
            elif nxt == ".":
                self._next()
        return group

    # -- knn clause (hybrid graph+vector extension) ------------------------
    _KNN_METRICS = ("cosine", "dot", "l2")

    def _parse_knn_clause(self) -> dict:
        """``knn(?x, anchor, k[, metric])`` — anchor is an IRI/PNAME
        (rank by that vertex's stored embedding) or a parenthesized
        number list ``(0.1 0.2 ...)`` (a literal query vector). Returns
        the symbolic clause; ids resolve in ``_resolve_group``."""
        self._expect_op("(")
        var = self._expect("VAR")
        self._expect_op(",")
        kind, val = self._peek()
        if val == "(":
            self._next()
            nums = []
            while self._peek()[0] == "NUM":
                nums.append(float(self._next()[1]))
            self._expect_op(")")
            if not nums:
                raise SPARQLSyntaxError("knn() literal vector is empty")
            anchor = ("vec", nums)
        elif kind == "IRI":
            anchor = ("iri", self._next()[1])
        elif kind == "PNAME":
            anchor = ("iri", self._expand_pname(self._next()[1]))
        else:
            raise SPARQLSyntaxError(
                f"knn() anchor must be an IRI or a (v0 v1 ...) literal "
                f"vector, got {val!r}")
        self._expect_op(",")
        k = int(self._expect("NUM"))
        if k < 1:
            raise SPARQLSyntaxError("knn() k must be >= 1")
        metric = ""
        if self._peek()[1] == ",":
            self._next()
            metric = self._next()[1].lower()
            if metric not in self._KNN_METRICS:
                raise SPARQLSyntaxError(
                    f"knn() metric must be one of {self._KNN_METRICS}, "
                    f"got {metric!r}")
        self._expect_op(")")
        return {"var": var, "anchor": anchor, "k": k, "metric": metric}

    def _resolve_knn(self, clause: dict) -> KNNClause:
        var = self._var_id(clause["var"])
        akind, aval = clause["anchor"]
        if akind == "vec":
            return KNNClause(var=var, k=clause["k"],
                             anchor_vec=np.asarray(aval, dtype=np.float32),
                             metric=clause["metric"])
        if self.str_server is None:
            raise SPARQLSyntaxError("knn() anchor IRI requires a string server")
        try:
            vid = self.str_server.str2id(aval)
        except KeyError:
            raise WukongError(ErrorCode.UNKNOWN_SUB, aval)
        return KNNClause(var=var, k=clause["k"], anchor_vid=vid,
                         metric=clause["metric"])

    # -- terms -------------------------------------------------------------
    def _parse_term(self, predicate: bool = False) -> _Term:
        kind, val = self._next()
        if kind == "VAR":
            return _Term("var", val)
        if kind == "IRI":
            return _Term("iri", val)
        if kind == "PNAME":
            return _Term("iri", self._expand_pname(val))
        if kind == "TEMPLATE":
            # %prefix:name or %<full-iri> (the watdiv emulator templates use
            # the full-IRI form)
            body = val[1:]
            return _Term("template", body if body.startswith("<")
                         else self._expand_pname(body))
        if kind == "STRING":
            return _Term("literal", val)
        if kind == "NUM":
            return _Term("num", val)
        if kind == "KEYWORD":
            if val == "__PREDICATE__":
                return _Term("predicate_kw", val)
            if val.lower() == "a" and predicate:
                return _Term("iri", RDF_TYPE_IRI)
        raise SPARQLSyntaxError(f"unexpected token {val!r} in triple pattern")

    def _expand_pname(self, pname: str) -> str:
        pre, local = pname.split(":", 1)
        if pre not in self.prefixes:
            raise SPARQLSyntaxError(f"undefined prefix {pre!r}")
        base = self.prefixes[pre]
        return base[:-1] + local + ">"

    # -- filters (precedence climbing: || < && < cmp < addsub < muldiv < unary)
    def _parse_filter_expr(self) -> Filter:
        # FILTER Constraint: bracketted expression or a bare builtin call
        if self._peek()[1] == "(":
            self._next()
            f = self._parse_or()
            self._expect_op(")")
            return f
        return self._parse_unary()

    def _parse_or(self) -> Filter:
        left = self._parse_and()
        while self._peek()[1] == "||":
            self._next()
            left = Filter(FilterType.Or, left, self._parse_and())
        return left

    def _parse_and(self) -> Filter:
        left = self._parse_rel()
        while self._peek()[1] == "&&":
            self._next()
            left = Filter(FilterType.And, left, self._parse_rel())
        return left

    _REL_OPS = {"=": FilterType.Equal, "!=": FilterType.NotEqual,
                "<": FilterType.Less, "<=": FilterType.LessOrEqual,
                ">": FilterType.Greater, ">=": FilterType.GreaterOrEqual}

    def _parse_rel(self) -> Filter:
        left = self._parse_add()
        op = self._peek()[1]
        if op in self._REL_OPS:
            self._next()
            return Filter(self._REL_OPS[op], left, self._parse_add())
        return left

    def _parse_add(self) -> Filter:
        left = self._parse_mul()
        while self._peek()[1] in ("+", "-"):
            op = self._next()[1]
            t = FilterType.Plus if op == "+" else FilterType.Minus
            left = Filter(t, left, self._parse_mul())
        return left

    def _parse_mul(self) -> Filter:
        left = self._parse_unary()
        while self._peek()[1] in ("*", "/"):
            op = self._next()[1]
            t = FilterType.Mul if op == "*" else FilterType.Div
            left = Filter(t, left, self._parse_unary())
        return left

    _BUILTINS = {
        "BOUND": FilterType.Builtin_bound, "ISIRI": FilterType.Builtin_isiri,
        "ISURI": FilterType.Builtin_isiri, "ISBLANK": FilterType.Builtin_isblank,
        "ISLITERAL": FilterType.Builtin_isliteral, "STR": FilterType.Builtin_str,
        "REGEX": FilterType.Builtin_regex, "LANG": FilterType.Builtin_lang,
        "DATATYPE": FilterType.Builtin_datatype, "SAMETERM": FilterType.Builtin_sameterm,
    }

    def _parse_unary(self) -> Filter:
        kind, val = self._peek()
        if val == "!":
            self._next()
            return Filter(FilterType.Not, self._parse_unary())
        if val == "+":
            self._next()
            return Filter(FilterType.UnaryPlus, self._parse_unary())
        if val == "-":
            self._next()
            return Filter(FilterType.UnaryMinus, self._parse_unary())
        if val == "(":
            self._next()
            f = self._parse_or()
            self._expect_op(")")
            return f
        if kind == "VAR":
            self._next()
            return Filter(FilterType.Variable, valueArg=self._var_id(val))
        if kind == "STRING":
            self._next()
            return Filter(FilterType.Literal, value=val)
        if kind == "NUM":
            self._next()
            return Filter(FilterType.Literal, value=val)
        if kind == "IRI":
            self._next()
            return Filter(FilterType.IRI, value=val)
        if kind == "PNAME":
            self._next()
            return Filter(FilterType.IRI, value=self._expand_pname(val))
        if kind == "KEYWORD" and val.upper() in self._BUILTINS:
            self._next()
            ftype = self._BUILTINS[val.upper()]
            self._expect_op("(")
            args = [self._parse_or()]
            while self._peek()[1] == ",":
                self._next()
                args.append(self._parse_or())
            self._expect_op(")")
            f = Filter(ftype)
            if len(args) > 0:
                f.arg1 = args[0]
            if len(args) > 1:
                f.arg2 = args[1]
            if len(args) > 2:
                f.arg3 = args[2]
            return f
        raise SPARQLSyntaxError(f"unexpected token {val!r} in FILTER expression")

    # -- id resolution -----------------------------------------------------
    def _var_id(self, name: str) -> int:
        key = "?" + name[1:]  # normalize $x to ?x
        if key not in self.vars:
            self.vars[key] = -(len(self.vars) + 1)
        return self.vars[key]

    def _resolve_term(self, t: _Term, is_pred: bool) -> tuple[int, int]:
        """Returns (ssid, attr_type_tag)."""
        from wukong_tpu.types import PREDICATE_ID

        if t.kind == "var":
            return self._var_id(t.value), int(AttrType.SID_t)
        if t.kind == "predicate_kw":
            return PREDICATE_ID, int(AttrType.SID_t)
        if self.str_server is None:
            raise SPARQLSyntaxError("constants require a string server")
        try:
            sid = self.str_server.str2id(t.value)
        except KeyError:
            raise WukongError(ErrorCode.UNKNOWN_SUB, t.value)
        at = int(AttrType.SID_t)
        if is_pred and hasattr(self.str_server, "pid2type"):
            at = self.str_server.pid2type.get(sid, int(AttrType.SID_t))
        return sid, at

    def _resolve_group(self, group: dict, top_level: bool = True) -> PatternGroup:
        pg = PatternGroup()
        for (s, p, o) in group["patterns"]:
            if not top_level and (s.kind == "template" or o.kind == "template"):
                raise SPARQLSyntaxError(
                    "%placeholders are only supported in the top-level group")
            ssid, _ = self._resolve_term(s, False) if s.kind != "template" \
                else (self._reserve_template_slot(len(pg.patterns), "subject", s), 0)
            pid, ptype = self._resolve_term(p, True)
            osid, _ = self._resolve_term(o, False) if o.kind != "template" \
                else (self._reserve_template_slot(len(pg.patterns), "object", o), 0)
            pat = Pattern(ssid, pid, OUT, osid)
            pat.pred_type = ptype
            pg.patterns.append(pat)
        for sub in group["unions"]:
            pg.unions.append(self._resolve_group(sub, top_level=False))
        for sub in group["optional"]:
            spg = self._resolve_group(sub, top_level=False)
            pg.optional.append(spg)
        for f in group["filters"]:
            pg.filters.append(f)
        if group.get("knn"):
            if not top_level:
                raise SPARQLSyntaxError(
                    "knn() is only supported in the top-level group")
            if len(group["knn"]) > 1:
                raise SPARQLSyntaxError("at most one knn() clause per query")
            self._knn = self._resolve_knn(group["knn"][0])
            self._knn_leading = bool(group["knn"][0].get("leading"))
        return pg

    def _reserve_template_slot(self, pattern_idx: int, fld: str, t: _Term) -> int:
        """%type placeholder: record slot, resolve the placeholder's type id.
        `%<fromPredicate>` (proxy.hpp:76-99) draws candidates from the
        pattern's own predicate index instead of a type — recorded as a
        marker for fill_template, no id to resolve."""
        if "fromPredicate" in t.value:
            self.template.ptypes.append("fromPredicate")
            self.template.pos.append((pattern_idx, fld))
            return 0
        try:
            tid = self.str_server.str2id(t.value)
        except KeyError:
            raise WukongError(ErrorCode.UNKNOWN_SUB, t.value)
        self.template.ptypes.append(tid)
        self.template.pos.append((pattern_idx, fld))
        return 0  # patched at instantiation

    # -- token helpers -----------------------------------------------------
    def _peek(self):
        return self.toks[self.i]

    def _peek_kw(self, kw: str) -> bool:
        t = self.toks[self.i]
        return t[0] == "KEYWORD" and t[1].upper() == kw.upper()

    def _next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def _expect(self, kind: str) -> str:
        t = self._next()
        if t[0] != kind:
            raise SPARQLSyntaxError(f"expected {kind}, got {t[1]!r}")
        return t[1]

    def _expect_kw(self, kw: str) -> None:
        if not self._peek_kw(kw):
            raise SPARQLSyntaxError(f"expected {kw}, got {self._peek()[1]!r}")
        self._next()

    def _expect_op(self, op: str) -> None:
        t = self._next()
        if t[1] != op:
            raise SPARQLSyntaxError(f"expected {op!r}, got {t[1]!r}")
