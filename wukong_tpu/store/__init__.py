from wukong_tpu.store.segment import CSRSegment  # noqa: F401
from wukong_tpu.store.gstore import GStore, build_partition  # noqa: F401
from wukong_tpu.store.string_server import StringServer  # noqa: F401
