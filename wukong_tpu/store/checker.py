"""Store consistency checker — the ``gsck`` console command.

Mirrors GChecker (core/store/gchecker.hpp:28-90 ff.): cross-validates index
lists against normal segments in both directions on each partition. The
reference runs this as its de-facto integration test after loading (SURVEY §4).
"""

from __future__ import annotations

import numpy as np

from wukong_tpu.store.gstore import GStore
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, PREDICATE_ID, TYPE_ID
from wukong_tpu.utils.mathutil import hash_mod


def check_partition(g: GStore, index_check: bool = True,
                    normal_check: bool = True) -> list[str]:
    """Returns a list of violation descriptions (empty = consistent)."""
    errors: list[str] = []

    if index_check:
        # every member of the type index has that type in its OUT type list
        tseg = g.segments.get((TYPE_ID, OUT))
        for (tpid, d), members in g.index.items():
            if d == IN and tpid in g.type_ids:
                # type index
                if tseg is None:
                    errors.append(f"type index {tpid} but no (TYPE_ID, OUT) segment")
                    continue
                ok = tseg.contains_pair(members, np.full(len(members), tpid))
                for v in members[~ok]:
                    errors.append(f"tidx[{tpid}] member {v} lacks type edge")
            elif d == IN:
                # predicate index IN: subject must have a (pid, OUT) edge list
                seg = g.segments.get((int(tpid), OUT))
                if seg is None:
                    errors.append(f"pidx_in[{tpid}] but no (pid, OUT) segment")
                    continue
                _, deg = seg.lookup_many(members)
                for v in members[deg == 0]:
                    errors.append(f"pidx_in[{tpid}] subject {v} has no OUT edges")
            elif d == OUT:
                seg = g.segments.get((int(tpid), IN))
                if seg is None:
                    errors.append(f"pidx_out[{tpid}] but no (pid, IN) segment")
                    continue
                _, deg = seg.lookup_many(members)
                for v in members[deg == 0]:
                    errors.append(f"pidx_out[{tpid}] object {v} has no IN edges")

    if normal_check:
        # every OUT key appears in pidx_in / every type edge in tidx
        for (pid, d), seg in g.segments.items():
            if d == OUT and pid == TYPE_ID:
                for t in np.unique(seg.edges):
                    tlist = g.index.get((int(t), IN))
                    if tlist is None:
                        errors.append(f"type {t} present in edges but no tidx")
                        continue
                    # all subjects with this type must be in tidx[t]
                    has_t = seg.contains_pair(seg.keys, np.full(len(seg.keys), t))
                    missing = np.setdiff1d(seg.keys[has_t], tlist)
                    for v in missing:
                        errors.append(f"vertex {v} of type {t} missing from tidx")
            elif d == OUT:
                plist = g.index.get((int(pid), IN))
                if plist is None:
                    errors.append(f"segment ({pid}, OUT) but no pidx_in")
                    continue
                missing = np.setdiff1d(seg.keys, plist)
                for v in missing:
                    errors.append(f"subject {v} of pred {pid} missing from pidx_in")
            elif d == IN:
                plist = g.index.get((int(pid), OUT))
                if plist is None:
                    errors.append(f"segment ({pid}, IN) but no pidx_out")
                    continue
                missing = np.setdiff1d(seg.keys, plist)
                for v in missing:
                    errors.append(f"object {v} of pred {pid} missing from pidx_out")

    return errors


def check_cross_partition(stores: list[GStore]) -> list[str]:
    """Every OUT edge (s,p,o) must have the IN copy (o,p,s) on o's owner."""
    errors: list[str] = []
    n = len(stores)
    for g in stores:
        for (pid, d), seg in g.segments.items():
            if d != OUT or pid == TYPE_ID:
                continue
            s = np.repeat(seg.keys, np.diff(seg.offsets))
            o = seg.edges
            norm = o >= NORMAL_ID_START
            s, o = s[norm], o[norm]
            owners = hash_mod(o, n)
            for dst in range(n):
                m = owners == dst
                if not m.any():
                    continue
                rseg = stores[dst].segments.get((pid, IN))
                if rseg is None:
                    errors.append(f"worker {dst} missing segment ({pid}, IN)")
                    continue
                ok = rseg.contains_pair(o[m], s[m])
                for ss, oo in zip(s[m][~ok], o[m][~ok]):
                    errors.append(
                        f"edge ({ss},{pid},{oo}) OUT@{g.sid} lacks IN copy @{dst}")
    return errors
