"""Dynamic (incremental) store: online bulk insertion — the DynamicGStore role.

The reference's dynamic store (core/store/dynamic_gstore.hpp) swaps the bump
allocator for a real allocator so `load -d <dir>` can insert triples online
(insert_triple_out/in, :537/:603), with lease-based invalidation so remote
RDMA-cached reads stay safe. On TPU the RDMA lease machinery disappears
(SURVEY §7.7): inserts append to per-segment DELTA buffers (O(batch) plus a
membership probe for dedup — never an O(segment) rebuild per batch), and the
merged CSR materializes lazily on first read after a write epoch. Each batch
bumps a store version; device-side caches compare versions and restage lazily.

New predicates/types create new segments/indexes, matching DynamicLoader's
support for unseen predicates (core/loader/dynamic_loader.hpp).
"""

from __future__ import annotations

import numpy as np

from wukong_tpu.store.gstore import GStore, _pred_runs, _triple_argsort
from wukong_tpu.store.segment import CSRSegment
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, TYPE_ID
from wukong_tpu.utils.mathutil import hash_mod


class DeltaCSRSegment:
    """CSR segment with append-only delta buffers (dynamic_gstore.hpp's role,
    redesigned): writes append (key, value) runs; reads materialize the
    merged CSR once per write epoch. Duck-types CSRSegment — every consumer
    (engines, device staging, checker, persistence) sees merged arrays.
    """

    __slots__ = ("_base", "_pending", "_n_pending", "_pending_set")

    def __init__(self, base: CSRSegment | None):
        self._base = base if base is not None else CSRSegment.empty()
        self._pending: list = []
        self._n_pending = 0
        self._pending_set: set = set()  # O(1) dedup probes into the deltas

    # ---- writes ----------------------------------------------------------
    def append(self, ks: np.ndarray, vs: np.ndarray, dedup: bool) -> int:
        """Append a batch; with dedup, pairs already present (in the base,
        the pending deltas, or earlier in the batch) are dropped. O(batch)
        plus a base membership probe — never re-scans prior deltas. Returns
        the number of edges actually appended."""
        if dedup:
            if len(ks):
                pairs = np.stack([ks, vs], axis=1)
                pairs = np.unique(pairs, axis=0)  # in-batch dups
                ks, vs = pairs[:, 0], pairs[:, 1]
            keep = ~self._base.contains_pair(ks, vs)
            if self._pending_set:
                ps = self._pending_set
                keep &= np.fromiter(
                    ((int(k), int(v)) not in ps for k, v in zip(ks, vs)),
                    dtype=bool, count=len(ks))
            ks, vs = ks[keep], vs[keep]
        if len(ks):
            ks = np.asarray(ks, np.int64)
            vs = np.asarray(vs, np.int64)
            self._pending.append((ks, vs))
            self._n_pending += len(ks)
            self._pending_set.update(zip(ks.tolist(), vs.tolist()))
        return int(len(ks))

    # ---- lazy materialization -------------------------------------------
    def _mat(self) -> CSRSegment:
        if self._pending:
            bk = np.repeat(self._base.keys, np.diff(self._base.offsets))
            all_k = np.concatenate([bk] + [p[0] for p in self._pending])
            all_v = np.concatenate([self._base.edges]
                                   + [p[1] for p in self._pending])
            order = np.lexsort((all_v, all_k))
            k, v = all_k[order], all_v[order]
            keys, counts = np.unique(k, return_counts=True)
            offsets = np.zeros(len(keys) + 1, dtype=np.int64)
            np.cumsum(counts, out=offsets[1:])
            # no pair-dedup here: dedup appends were filtered at write time,
            # non-dedup appends legitimately keep duplicates
            self._base = CSRSegment(keys=keys, offsets=offsets, edges=v)
            self._pending.clear()
            self._pending_set.clear()
            self._n_pending = 0
        return self._base

    # ---- CSRSegment interface -------------------------------------------
    @property
    def keys(self):
        return self._mat().keys

    @property
    def offsets(self):
        return self._mat().offsets

    @property
    def edges(self):
        return self._mat().edges

    @property
    def num_keys(self) -> int:
        return self._mat().num_keys

    @property
    def num_edges(self) -> int:  # exact without materializing
        return self._base.num_edges + self._n_pending

    def lookup(self, vid: int):
        return self._mat().lookup(vid)

    def lookup_many(self, vids):
        return self._mat().lookup_many(vids)

    def contains_pair(self, vids, vals):
        return self._mat().contains_pair(vids, vals)

    def memory_bytes(self) -> int:
        return self._base.memory_bytes() + 16 * self._n_pending


def insert_triples(g: GStore, triples: np.ndarray, dedup: bool = True,
                   check_ids: bool = True) -> int:
    """Insert an [N,3] batch into this partition. Returns #edges inserted
    (subject-side copies; the object-side copies are inserted symmetrically).

    Bumps g.version so device caches restage affected segments.
    """
    from wukong_tpu.runtime import faults

    # fault hook BEFORE any mutation: an injected transient leaves the store
    # untouched, so the ingest path's retry replays the batch safely
    faults.site("dynamic.insert", shard=g.sid)
    if check_ids:
        from wukong_tpu.store.gstore import check_vid_range

        check_vid_range(triples)
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    n = g.num_workers
    mine_out = hash_mod(s, n) == g.sid
    mine_in = (hash_mod(o, n) == g.sid) & (o >= NORMAL_ID_START)

    so, po, oo = s[mine_out], p[mine_out], o[mine_out]
    si, pi, oi = s[mine_in], p[mine_in], o[mine_in]

    order = _triple_argsort(po, so, oo)
    so, po, oo = so[order], po[order], oo[order]
    inserted = 0
    for pid, ks, vs in _pred_runs(po, so, oo):
        inserted += _merge_into(g, (pid, OUT), ks, vs, dedup)
        if pid == TYPE_ID:
            for t in np.unique(vs):
                members = np.unique(ks[vs == t])
                old = g.index.get((int(t), IN), np.empty(0, dtype=np.int64))
                g.index[(int(t), IN)] = np.union1d(old, members)
                g.type_ids.add(int(t))
        else:
            old = g.index.get((pid, IN), np.empty(0, dtype=np.int64))
            g.index[(pid, IN)] = np.union1d(old, np.unique(ks))

    order = _triple_argsort(pi, oi, si)
    si, pi, oi = si[order], pi[order], oi[order]
    for pid, ks, vs in _pred_runs(pi, oi, si):
        _merge_into(g, (pid, IN), ks, vs, dedup)
        old = g.index.get((pid, OUT), np.empty(0, dtype=np.int64))
        g.index[(pid, OUT)] = np.union1d(old, np.unique(ks))

    # versatile structures
    if g.vp:
        g.vp[OUT] = _merge_seg(g.vp.get(OUT), s[mine_out], p[mine_out], True)
        g.vp[IN] = _merge_seg(g.vp.get(IN), oi, pi, True)
        g.v_set = np.union1d(g.v_set, np.concatenate([s[mine_out], oi]))
        tmask = p[mine_out] == TYPE_ID
        g.t_set = np.union1d(g.t_set, o[mine_out][tmask])
        g.p_set = np.union1d(
            g.p_set, np.unique(np.concatenate([p[mine_out][~tmask], pi])))

    g.version = getattr(g, "version", 0) + 1
    return int(inserted)


def _merge_into(g: GStore, key, ks, vs, dedup: bool) -> int:
    seg = g.segments.get(key)
    if not isinstance(seg, DeltaCSRSegment):
        seg = DeltaCSRSegment(seg)
        g.segments[key] = seg
    return seg.append(np.asarray(ks, np.int64), np.asarray(vs, np.int64),
                      dedup)


def _merge_seg(seg, ks, vs, dedup: bool) -> DeltaCSRSegment:
    if not isinstance(seg, DeltaCSRSegment):
        seg = DeltaCSRSegment(seg)
    seg.append(np.asarray(ks, np.int64), np.asarray(vs, np.int64), dedup)
    return seg


# ---------------------------------------------------------------------------
# migration dual-write sinks (runtime/migration.py)
# ---------------------------------------------------------------------------
# In-flight shard-migration recipients that must observe every committed
# mutation between catch-up and cutover. Enroll/deroll run under the WAL
# mutation lock (the migration executor's catch-up/cutover critical
# sections), and every consulting write path — insert_batch_into below,
# StreamIngestor.commit_epoch — reads the dict INSIDE the same lock, so an
# enrolled recipient can never miss, or double-observe, a committed batch.
_MIGRATION_SINKS: dict = {}  # guarded by: mutation_lock()


def enroll_migration_sink(key, store) -> None:  # caller holds: mutation_lock()
    _MIGRATION_SINKS[key] = store


def deroll_migration_sink(key) -> None:  # caller holds: mutation_lock()
    _MIGRATION_SINKS.pop(key, None)


def migration_sinks() -> list:  # caller holds: mutation_lock()
    """The current dual-write targets (empty list when no migration is in
    flight — the common case pays one dict check per batch)."""
    return list(_MIGRATION_SINKS.values())


def load_dir_into(stores: list[GStore], dirname: str, dedup: bool = True) -> int:
    """`load -d <dir>`: read id-triple files and insert into every partition
    (the RDFEngine::execute_load_data path, core/engine/rdf.hpp)."""
    from wukong_tpu.loader.base import load_triples

    from wukong_tpu.store.gstore import check_vid_range

    triples = load_triples(dirname)
    check_vid_range(triples)  # once, not per store
    return insert_batch_into(stores, triples, dedup)


def insert_batch_into(stores: list[GStore], triples: np.ndarray,
                      dedup: bool = True) -> int:
    """One durable batch insert into every partition: the WAL append hook
    fires BEFORE any store mutates, so an acknowledged batch is always
    replayable and a WAL failure leaves the stores untouched. The mutation
    lock keeps the append + fan-out atomic w.r.t. checkpoint
    serialization (runtime/recovery.py)."""
    from wukong_tpu.obs.reuse import maybe_note_invalidation
    from wukong_tpu.serve import notify_mutation
    from wukong_tpu.store.wal import maybe_wal_append, mutation_lock

    with mutation_lock():
        maybe_wal_append("insert", triples, dedup)
        total = 0
        for g in stores:
            total += insert_triples(g, triples, dedup, check_ids=False)
        # dual-write: an in-flight migration's recipient mirrors the batch
        # (each sink hashes out its own shard's rows). Excluded from the
        # returned total: the count answers "how many new edges landed",
        # and the sink is a transient mirror of a store already counted
        for g in migration_sinks():
            insert_triples(g, triples, dedup, check_ids=False)
        # the serving plane's actuator edge (wukong_tpu/serve/): INSIDE
        # the mutation lock, so view maintenance re-keys surviving cache
        # entries atomically with the version bump — a view is never
        # visible at a version it doesn't match. One knob check when the
        # result cache is off.
        if stores:
            notify_mutation("insert",
                            version=getattr(stores[0], "version", 0),
                            triples=triples)
    # cache-coherence telemetry (obs/reuse.py): the batch's version edge
    # kills the stale shadow keys and lands one cache.invalidate event.
    # Outside the mutation lock — the journal emit is pure observability
    # and must never extend the write stall
    if stores:
        maybe_note_invalidation(
            "insert", version=getattr(stores[0], "version", 0),
            n_triples=int(len(triples)))
    return total
