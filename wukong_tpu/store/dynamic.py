"""Dynamic (incremental) store: online bulk insertion — the DynamicGStore role.

The reference's dynamic store (core/store/dynamic_gstore.hpp) swaps the bump
allocator for a real allocator so `load -d <dir>` can insert triples online
(insert_triple_out/in, :537/:603), with lease-based invalidation so remote
RDMA-cached reads stay safe. On TPU the RDMA lease machinery disappears
(SURVEY §7.7): instead each insert batch merge-rebuilds the affected CSR
segments (sorted-merge, optional dedup like the reference's -c flag) and bumps
a store version; device-side caches compare versions and restage lazily.

New predicates/types create new segments/indexes, matching DynamicLoader's
support for unseen predicates (core/loader/dynamic_loader.hpp).
"""

from __future__ import annotations

import numpy as np

from wukong_tpu.store.gstore import GStore, _pred_runs, _triple_argsort
from wukong_tpu.store.segment import CSRSegment
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, TYPE_ID
from wukong_tpu.utils.mathutil import hash_mod


def insert_triples(g: GStore, triples: np.ndarray, dedup: bool = True,
                   check_ids: bool = True) -> int:
    """Insert an [N,3] batch into this partition. Returns #edges inserted
    (subject-side copies; the object-side copies are inserted symmetrically).

    Bumps g.version so device caches restage affected segments.
    """
    if check_ids:
        from wukong_tpu.store.gstore import check_vid_range

        check_vid_range(triples)
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    n = g.num_workers
    mine_out = hash_mod(s, n) == g.sid
    mine_in = (hash_mod(o, n) == g.sid) & (o >= NORMAL_ID_START)

    so, po, oo = s[mine_out], p[mine_out], o[mine_out]
    si, pi, oi = s[mine_in], p[mine_in], o[mine_in]

    order = _triple_argsort(po, so, oo)
    so, po, oo = so[order], po[order], oo[order]
    inserted = 0
    for pid, ks, vs in _pred_runs(po, so, oo):
        inserted += _merge_into(g, (pid, OUT), ks, vs, dedup)
        if pid == TYPE_ID:
            for t in np.unique(vs):
                members = np.unique(ks[vs == t])
                old = g.index.get((int(t), IN), np.empty(0, dtype=np.int64))
                g.index[(int(t), IN)] = np.union1d(old, members)
                g.type_ids.add(int(t))
        else:
            old = g.index.get((pid, IN), np.empty(0, dtype=np.int64))
            g.index[(pid, IN)] = np.union1d(old, np.unique(ks))

    order = _triple_argsort(pi, oi, si)
    si, pi, oi = si[order], pi[order], oi[order]
    for pid, ks, vs in _pred_runs(pi, oi, si):
        _merge_into(g, (pid, IN), ks, vs, dedup)
        old = g.index.get((pid, OUT), np.empty(0, dtype=np.int64))
        g.index[(pid, OUT)] = np.union1d(old, np.unique(ks))

    # versatile structures
    if g.vp:
        g.vp[OUT] = _merge_seg(g.vp.get(OUT), s[mine_out], p[mine_out], True)
        g.vp[IN] = _merge_seg(g.vp.get(IN), oi, pi, True)
        g.v_set = np.union1d(g.v_set, np.concatenate([s[mine_out], oi]))
        tmask = p[mine_out] == TYPE_ID
        g.t_set = np.union1d(g.t_set, o[mine_out][tmask])
        g.p_set = np.union1d(
            g.p_set, np.unique(np.concatenate([p[mine_out][~tmask], pi])))

    g.version = getattr(g, "version", 0) + 1
    return int(inserted)


def _merge_into(g: GStore, key, ks, vs, dedup: bool) -> int:
    seg = g.segments.get(key)
    before = seg.num_edges if seg is not None else 0
    g.segments[key] = _merge_seg(seg, ks, vs, dedup)
    return g.segments[key].num_edges - before  # actual new edges (post-dedup)


def _merge_seg(seg: CSRSegment | None, ks, vs, dedup: bool) -> CSRSegment:
    if seg is None or seg.num_edges == 0:
        base_k = np.asarray(ks)
        base_v = np.asarray(vs)
        all_k, all_v = base_k, base_v
    else:
        old_k = np.repeat(seg.keys, np.diff(seg.offsets))
        all_k = np.concatenate([old_k, ks])
        all_v = np.concatenate([seg.edges, vs])
    if not dedup:
        order = np.lexsort((all_v, all_k))
        k, v = all_k[order], all_v[order]
        keys, counts = np.unique(k, return_counts=True)
        offsets = np.zeros(len(keys) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return CSRSegment(keys=keys, offsets=offsets, edges=v)
    return CSRSegment.from_pairs(all_k, all_v)  # sorts + dedups pairs


def load_dir_into(stores: list[GStore], dirname: str, dedup: bool = True) -> int:
    """`load -d <dir>`: read id-triple files and insert into every partition
    (the RDFEngine::execute_load_data path, core/engine/rdf.hpp)."""
    from wukong_tpu.loader.base import load_triples

    from wukong_tpu.store.gstore import check_vid_range

    triples = load_triples(dirname)
    check_vid_range(triples)  # once, not per store
    total = 0
    for g in stores:
        total += insert_triples(g, triples, dedup, check_ids=False)
    return total
