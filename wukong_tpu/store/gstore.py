"""Partitioned in-memory RDF graph store over CSR segments.

Capability-equivalent to the reference's GStore/StaticGStore + DGraph facade
(core/store/gstore.hpp, static_gstore.hpp, core/dgraph.hpp) with the storage
format redesigned for TPU staging (see segment.py). Semantics preserved:

- Partitioning: triple (s, p, o) lives on worker hash(s)%n as an OUT edge and on
  worker hash(o)%n as an IN edge (base_loader.hpp:172-173) — every triple is
  stored twice cluster-wide.
- Type triples (p == TYPE_ID) have index-id objects; they produce the per-vertex
  type list (v, TYPE_ID, OUT) on the subject owner and the *type index*
  tidx[t] -> members on the subject owner (gstore.hpp:875-882 collect_idx_info —
  built from OUT keys, hence subject-side). No (·, TYPE_ID, IN) normal segment
  exists (static_gstore.hpp:127-130 skips type triples on the pos side).
- Predicate indexes: pidx_in[p] = local subjects having p (from OUT keys),
  pidx_out[p] = local objects under p (from IN keys) (gstore.hpp:858-888).
- VERSATILE: per-vertex predicate lists (v, PREDICATE_ID, OUT/IN) — OUT includes
  TYPE_ID (type triples are part of the pso walk, static_gstore.hpp:295-330),
  IN excludes type triples (static_gstore.hpp:331-369); plus v/t/p sets
  (all local entities / types / predicates, static_gstore.hpp:267-279).
- Attributes: per-attr sorted (subject -> typed value) maps (gstore.hpp asv path).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from wukong_tpu.store.segment import CSRSegment
from wukong_tpu.types import IN, NORMAL_ID_START, OUT, PREDICATE_ID, TYPE_ID
from wukong_tpu.utils.mathutil import hash_mod


@dataclass
class AttrSegment:
    keys: np.ndarray  # sorted subject ids
    values: np.ndarray  # typed values (int64 or float64)
    type: int  # AttrType tag

    def lookup(self, vid: int):
        i = np.searchsorted(self.keys, vid)
        if i < len(self.keys) and self.keys[i] == vid:
            return self.values[i], True
        return None, False


@dataclass
class GStore:
    """One worker's partition of the graph."""

    sid: int
    num_workers: int
    # normal segments: (pid, dir) -> CSR; includes (TYPE_ID, OUT) = per-vertex types
    segments: dict = field(default_factory=dict)
    # index lists: (tpid, dir) -> sorted vid array
    #   (pid, IN) = local subjects having pid; (pid, OUT) = local objects under pid
    #   (tid, IN) = local members of type tid
    index: dict = field(default_factory=dict)
    # VERSATILE per-vertex predicate lists: dir -> CSR (key = vid, edges = pids)
    vp: dict = field(default_factory=dict)
    # VERSATILE singleton sets
    v_set: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=np.int64))
    t_set: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=np.int64))
    p_set: np.ndarray = field(default_factory=lambda: np.empty(0, dtype=np.int64))
    # attribute segments: aid -> AttrSegment
    attrs: dict = field(default_factory=dict)
    # which index ids are type ids (objects of rdf:type) vs predicates
    type_ids: set = field(default_factory=set)

    # ---- lookup API (mirrors core/dgraph.hpp:106-145) --------------------
    def get_triples(self, vid: int, pid: int, d: int) -> np.ndarray:
        """Neighbor list of a *local* vertex under a predicate.

        pid == PREDICATE_ID returns the VERSATILE per-vertex predicate list
        (gstore.hpp VERSATILE keys); pid == TYPE_ID with d == OUT returns the
        vertex's types. (TYPE_ID, IN) is not a normal segment — engines must use
        get_index for type membership (sparql.hpp:336-340).
        """
        if pid == PREDICATE_ID:
            seg = self.vp.get(int(d))
            return seg.lookup(vid) if seg is not None else np.empty(0, dtype=np.int64)
        seg = self.segments.get((int(pid), int(d)))
        return seg.lookup(vid) if seg is not None else np.empty(0, dtype=np.int64)

    def get_index(self, tpid: int, d: int) -> np.ndarray:
        """Index lookup: members of a type (d=IN) or subjects/objects of a predicate."""
        if tpid == TYPE_ID and int(d) == IN:
            return self.v_set  # all local entities (VERSATILE v_set)
        if tpid == TYPE_ID and int(d) == OUT:
            return self.t_set
        if tpid == PREDICATE_ID and int(d) == OUT:
            return self.p_set
        return self.index.get((int(tpid), int(d)), np.empty(0, dtype=np.int64))

    def get_attr(self, vid: int, aid: int, d: int = OUT):
        seg = self.attrs.get(int(aid))
        if seg is None:
            return None, False
        return seg.lookup(vid)

    # ---- introspection ---------------------------------------------------
    def memory_bytes(self) -> int:
        n = sum(s.memory_bytes() for s in self.segments.values())
        n += sum(a.nbytes for a in (self.v_set, self.t_set, self.p_set))
        n += sum(s.memory_bytes() for s in self.vp.values())
        n += sum(v.nbytes for v in self.index.values())
        n += sum(a.keys.nbytes + a.values.nbytes for a in self.attrs.values())
        return n

    def stats_str(self) -> str:
        ne = sum(s.num_edges for s in self.segments.values())
        return (f"worker {self.sid}/{self.num_workers}: "
                f"{len(self.segments)} segments, {ne} edges, "
                f"{len(self.index)} index lists, {self.memory_bytes() / 2**20:.1f} MiB")


def owner_of_subject(s: np.ndarray, n: int) -> np.ndarray:
    return hash_mod(s, n)


def check_vid_range(triples: np.ndarray) -> None:
    """Device staging narrows ids to int32 (types.py documents the <2^31
    assumption), and INT32_MAX itself is the device-side padding/dead-row
    sentinel — so ids must stay strictly below 2^31 - 1 or they wrap/collide
    silently into wrong query results. The minimum matters too: the native
    radix sort (wukong_native.cpp) extracts unsigned digits and relies on
    non-negative ids, so a negative id mis-sorts on the native path while
    the np.lexsort fallback orders it correctly — a toolchain-dependent
    store divergence unless rejected here (ADVICE.md round-5 #1)."""
    if len(triples) and int(triples.max()) >= 2**31 - 1:
        from wukong_tpu.utils.errors import ErrorCode, WukongError

        raise WukongError(
            ErrorCode.UNKNOWN_PATTERN,
            f"vertex id {int(triples.max())} >= 2^31 - 1: ids no longer fit "
            "the int32 device representation (see types.py)")
    if len(triples) and int(triples.min()) < 0:
        from wukong_tpu.utils.errors import ErrorCode, WukongError

        raise WukongError(
            ErrorCode.UNKNOWN_PATTERN,
            f"vertex id {int(triples.min())} < 0: ids must be non-negative "
            "(the native radix sort's unsigned-digit contract)")


def _triple_argsort(primary, secondary, tertiary) -> np.ndarray:
    """argsort by (primary, secondary, tertiary) — native radix when available
    (the loader's sorted-run preparation, base_loader.hpp sorts)."""
    from wukong_tpu.native import sort_triples_perm

    perm = sort_triples_perm(primary, secondary, tertiary)
    if perm is not None:
        return perm
    return np.lexsort((tertiary, secondary, primary))


def _pred_runs(p_sorted: np.ndarray, k_sorted: np.ndarray, v_sorted: np.ndarray):
    """Yield (pid, keys, values) slices per predicate run of presorted arrays."""
    if len(p_sorted) == 0:
        return
    upids, starts = np.unique(p_sorted, return_index=True)
    bounds = np.append(starts, len(p_sorted))
    for i, pid in enumerate(upids):
        sl = slice(bounds[i], bounds[i + 1])
        yield int(pid), k_sorted[sl], v_sorted[sl]


def build_partition(triples: np.ndarray, sid: int, num_workers: int,
                    attr_triples=None, versatile: bool = True,
                    check_ids: bool = True) -> GStore:
    """Build worker `sid`'s GStore from the full [M,3] triple array.

    The reference reaches the same state via the loader's RDMA shuffle + sorted
    insert (base_loader.hpp:165-219, static_gstore.hpp:383-454); here partition
    selection + CSR building are vectorized numpy over the shared array.
    """
    g = GStore(sid=sid, num_workers=num_workers)
    if check_ids:
        check_vid_range(triples)
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]

    # ---- normal segments + predicate indexes (one sort per side) ---------
    # One direction END-TO-END at a time (slice -> sort -> segments ->
    # free), never both directions' copies plus sort workspace at once:
    # at LUBM-10240 (1.27B triples, int32) the old both-sides-up-front
    # layout peaked past this host's 125 GB and the build OOM-killed.
    # pso order: (p, s, o) — each predicate run becomes one OUT segment
    mine_out = hash_mod(s, num_workers) == sid  # pso copy (subject owner)
    so, po, oo = s[mine_out], p[mine_out], o[mine_out]
    del mine_out
    order = _triple_argsort(po, so, oo)
    so, po, oo = so[order], po[order], oo[order]
    del order
    for pid, ks, vs in _pred_runs(po, so, oo):
        g.segments[(pid, OUT)] = CSRSegment.from_sorted_pairs(ks, vs)
        if pid != TYPE_ID:
            g.index[(pid, IN)] = g.segments[(pid, OUT)].keys.copy()
    if versatile:  # subject-side versatile pieces, before freeing the copies
        vp_out = CSRSegment.from_pairs(so, po)  # includes TYPE_ID edges
        v_sub = np.unique(so)
        p_out = np.unique(po[po != TYPE_ID])
    del so, po, oo

    # pos order: (p, o, s) — each predicate run becomes one IN segment;
    # the object side never stores type triples as normal edges (the
    # NORMAL_ID_START test folds into the owner mask: one copy, not two)
    mine_in = (hash_mod(o, num_workers) == sid) & (o >= NORMAL_ID_START)
    si, pi, oi = s[mine_in], p[mine_in], o[mine_in]
    del mine_in
    order = _triple_argsort(pi, oi, si)
    si, pi, oi = si[order], pi[order], oi[order]
    del order
    for pid, ks, vs in _pred_runs(pi, oi, si):
        g.segments[(pid, IN)] = CSRSegment.from_sorted_pairs(ks, vs)
        g.index[(pid, OUT)] = g.segments[(pid, IN)].keys.copy()

    # ---- type index: t -> local members (subject-side) -------------------
    tseg = g.segments.get((TYPE_ID, OUT))
    if tseg is not None:
        ts = np.repeat(tseg.keys, np.diff(tseg.offsets))
        to = tseg.edges
        order = np.argsort(to, kind="stable")
        ts, to = ts[order], to[order]
        for t, ks, vs in _pred_runs(to, ts, ts):
            g.index[(t, IN)] = np.unique(ks)
            g.type_ids.add(t)

    # ---- VERSATILE -------------------------------------------------------
    if versatile:
        g.vp[OUT] = vp_out
        g.vp[IN] = CSRSegment.from_pairs(oi, pi)
        g.v_set = np.union1d(v_sub, oi)
        g.t_set = (np.unique(tseg.edges) if tseg is not None
                   else np.empty(0, dtype=np.int64))
        g.p_set = np.union1d(p_out, pi)

    # ---- attributes ------------------------------------------------------
    if attr_triples:
        by_aid: dict[int, list] = {}
        for (asub, aid, at, av) in attr_triples:
            if hash_mod(asub, num_workers) == sid:
                by_aid.setdefault(int(aid), []).append((asub, at, av))
        for aid, rows in by_aid.items():
            rows.sort()
            keys = np.asarray([r[0] for r in rows], dtype=np.int64)
            at = rows[0][1]
            dtype = np.float64 if at in (2, 3) else np.int64
            vals = np.asarray([r[2] for r in rows], dtype=dtype)
            g.attrs[aid] = AttrSegment(keys=keys, values=vals, type=at)

    return g


def build_all_partitions(triples: np.ndarray, num_workers: int,
                         attr_triples=None, versatile: bool = True) -> list[GStore]:
    check_vid_range(triples)  # once, not per partition
    return [build_partition(triples, i, num_workers, attr_triples, versatile,
                            check_ids=False)
            for i in range(num_workers)]
