"""GStore persistence: save/load a built partition as one .npz bundle.

The reference always re-ingests ID-triple files at boot and only persists
optimizer statistics (stats.hpp:585-640). Rebuilding 300M+ triples of CSR on a
single host core is minutes of lexsort, so the TPU build adds store-level
checkpointing: a built partition round-trips through one compressed npz.

Bundle format (version 2): the JSON ``_meta`` array carries a format name,
a (major, minor) version, the store's dynamic-insert version, and a CRC32
per payload array. The load path validates all of it and raises a
structured ``WukongError(CHECKPOINT_CORRUPT)`` naming the offending path —
never a bare ``KeyError``/``zipfile`` traceback — and refuses bundles from
a newer *major* version (minor bumps stay readable). Version-1 bundles
(no header) predate the checksums and still load, with a warning.

Dynamic state rides along for free: ``DeltaCSRSegment``'s array properties
materialize the merged CSR, so saving a store with pending deltas persists
exactly what queries see; loading yields plain CSR segments that re-wrap
lazily on the next insert.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
import zlib

import numpy as np

from wukong_tpu.store.gstore import AttrSegment, GStore
from wukong_tpu.store.segment import CSRSegment
from wukong_tpu.utils.errors import CheckpointCorrupt
from wukong_tpu.utils.logger import log_warn

FORMAT_NAME = "wukong-gstore"
FORMAT_VERSION = (2, 1)  # (major, minor): newer-major bundles are refused
# 2.1: optional vector-store arrays (vstore_*) + "vstore" meta entry —
# a minor bump, so 2.0 readers of this lineage would still load the
# graph arrays and 2.0 bundles load here (no vstore attached)


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _collect_arrays(g: GStore) -> tuple[dict, dict]:
    """(meta, arrays): the canonical array walk of a partition — every
    array save_gstore persists, in a stable order. Shared with
    gstore_digest so the checkpoint surface and the bit-identity proof
    can never drift."""
    arrays: dict[str, np.ndarray] = {}
    meta = {"format": FORMAT_NAME, "version": list(FORMAT_VERSION),
            "store_version": int(getattr(g, "version", 0)),
            "sid": g.sid, "num_workers": g.num_workers,
            "type_ids": sorted(g.type_ids), "segments": [], "index": [],
            "vp": [], "attrs": []}
    for i, ((pid, d), seg) in enumerate(sorted(g.segments.items())):
        meta["segments"].append([int(pid), int(d)])
        arrays[f"seg{i}_k"] = seg.keys
        arrays[f"seg{i}_o"] = seg.offsets
        arrays[f"seg{i}_e"] = seg.edges
    for i, ((tpid, d), arr) in enumerate(sorted(g.index.items())):
        meta["index"].append([int(tpid), int(d)])
        arrays[f"idx{i}"] = arr
    for i, (d, seg) in enumerate(sorted(g.vp.items())):
        meta["vp"].append(int(d))
        arrays[f"vp{i}_k"] = seg.keys
        arrays[f"vp{i}_o"] = seg.offsets
        arrays[f"vp{i}_e"] = seg.edges
    for i, (aid, seg) in enumerate(sorted(g.attrs.items())):
        meta["attrs"].append([int(aid), int(seg.type)])
        arrays[f"attr{i}_k"] = seg.keys
        arrays[f"attr{i}_v"] = seg.values
    arrays["v_set"] = g.v_set
    arrays["t_set"] = g.t_set
    arrays["p_set"] = g.p_set
    vs = getattr(g, "vstore", None)
    if vs is not None:
        # the embedding plane rides the same bundle (same checksums,
        # same digest surface): a checkpoint/restore that carried the
        # triples but dropped the vectors would silently break knn
        meta["vstore"] = {"dim": int(vs.dim), "version": int(vs.version)}
        arrays.update(vs.export_arrays())
    return meta, arrays


def gstore_digest(g: GStore) -> int:
    """Running CRC over every persisted array of a partition. The
    observe-only drills compare this before/after advising: unlike the
    store version (0 until the first dynamic insert), a raw in-place
    array write cannot leave it unchanged."""
    crc = 0
    _, arrays = _collect_arrays(g)
    for name in sorted(arrays):
        crc = zlib.crc32(np.ascontiguousarray(arrays[name]).tobytes(), crc)
    return crc


def save_gstore(g: GStore, path) -> None:
    """Persist a partition to ``path`` (a filename or any file object —
    the transport's wire codec saves into a BytesIO)."""
    meta, arrays = _collect_arrays(g)
    meta["checksums"] = {name: _crc(a) for name, a in arrays.items()}
    arrays["_meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


class _Checked:
    """Array accessor that verifies the manifest checksum on first read and
    turns every structural failure into a structured CHECKPOINT_CORRUPT."""

    def __init__(self, z, meta: dict, path: str):
        self.z = z
        self.checksums = meta.get("checksums")
        self.path = path

    def __getitem__(self, name: str) -> np.ndarray:
        try:
            arr = self.z[name]
        except KeyError:
            raise CheckpointCorrupt(f"missing array {name!r}",
                                    path=self.path) from None
        except (zipfile.BadZipFile, OSError, ValueError) as e:
            raise CheckpointCorrupt(f"unreadable array {name!r}: {e}",
                                    path=self.path) from None
        if self.checksums is not None:
            want = self.checksums.get(name)
            if want is None or _crc(arr) != want:
                raise CheckpointCorrupt(
                    f"checksum mismatch on array {name!r}", path=self.path)
        return arr


def load_gstore(path: str) -> GStore:
    path = path if path.endswith(".npz") else path + ".npz"
    try:
        z = np.load(path)
        meta = json.loads(bytes(z["_meta"]).decode())
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, KeyError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise CheckpointCorrupt(f"unreadable bundle: {e}",
                                path=path) from None
    return _decode_bundle(z, meta, path)


def _decode_bundle(z, meta: dict, path: str) -> GStore:
    """Validate + rebuild a partition from an opened npz — shared by the
    on-disk load path and the transport wire codec, so a transport copy
    is checked exactly as hard as a checkpoint restore."""
    if meta.get("format") is None:
        # version-1 bundle (pre-checksum): readable, but unverifiable
        log_warn(f"legacy gstore bundle (no format header): {path}")
    elif meta["format"] != FORMAT_NAME:
        raise CheckpointCorrupt(
            f"not a gstore bundle (format={meta['format']!r})", path=path)
    else:
        major = int(meta.get("version", [0])[0])
        if major > FORMAT_VERSION[0]:
            raise CheckpointCorrupt(
                f"bundle format v{meta['version']} is newer than this "
                f"build's v{list(FORMAT_VERSION)} — refusing to guess",
                path=path)
    a = _Checked(z, meta, path)
    try:
        g = GStore(sid=meta["sid"], num_workers=meta["num_workers"])
        g.type_ids = set(meta["type_ids"])
        for i, (pid, d) in enumerate(meta["segments"]):
            g.segments[(pid, d)] = CSRSegment(
                keys=a[f"seg{i}_k"], offsets=a[f"seg{i}_o"],
                edges=a[f"seg{i}_e"])
        for i, (tpid, d) in enumerate(meta["index"]):
            g.index[(tpid, d)] = a[f"idx{i}"]
        for i, d in enumerate(meta["vp"]):
            g.vp[d] = CSRSegment(keys=a[f"vp{i}_k"], offsets=a[f"vp{i}_o"],
                                 edges=a[f"vp{i}_e"])
        for i, (aid, at) in enumerate(meta["attrs"]):
            g.attrs[aid] = AttrSegment(keys=a[f"attr{i}_k"],
                                       values=a[f"attr{i}_v"], type=at)
        g.v_set = a["v_set"]
        g.t_set = a["t_set"]
        g.p_set = a["p_set"]
        vmeta = meta.get("vstore")
        if vmeta is not None:
            from wukong_tpu.vector.vstore import VectorStore

            g.vstore = VectorStore.from_arrays(
                g.sid, g.num_workers, a["vstore_vids"], a["vstore_vecs"],
                a["vstore_alive"], version=int(vmeta.get("version", 0)))
    except (KeyError, TypeError) as e:
        raise CheckpointCorrupt(f"malformed manifest: {e}",
                                path=path) from None
    g.version = int(meta.get("store_version", 0))
    return g


def gstore_to_bytes(g: GStore) -> bytes:
    """One partition as checkpoint-format bytes: the transport's shard
    snapshot payload (runtime/transport.py ``snapshot`` op). Same arrays,
    same checksums, same digest surface as an on-disk bundle."""
    buf = io.BytesIO()
    save_gstore(g, buf)
    return buf.getvalue()


def gstore_from_bytes(blob: bytes) -> GStore:
    """Inverse of :func:`gstore_to_bytes`, with the full load-path
    validation (format header, per-array CRCs, structured errors)."""
    try:
        z = np.load(io.BytesIO(blob))
        meta = json.loads(bytes(z["_meta"]).decode())
    except (zipfile.BadZipFile, KeyError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise CheckpointCorrupt(f"unreadable bundle: {e}",
                                path="<wire>") from None
    return _decode_bundle(z, meta, "<wire>")


# ---------------------------------------------------------------------------
# replication / recovery helpers
# ---------------------------------------------------------------------------

def clone_gstore(g: GStore) -> GStore:
    """Structural copy for shard replication: container dicts are copied,
    the immutable CSR base arrays are shared (they are never mutated in
    place — inserts wrap segments in fresh DeltaCSRSegments), and any
    pending delta segments are snapshotted via their merged CSR so later
    appends to either side never leak across the copy."""
    from wukong_tpu.store.dynamic import DeltaCSRSegment

    def snap(seg):
        # _mat() merges pending deltas and returns the (immutable) CSR
        return seg._mat() if isinstance(seg, DeltaCSRSegment) else seg

    g2 = GStore(sid=g.sid, num_workers=g.num_workers)
    g2.segments = {k: snap(s) for k, s in g.segments.items()}
    g2.index = dict(g.index)
    g2.vp = {d: snap(s) for d, s in g.vp.items()}
    g2.v_set, g2.t_set, g2.p_set = g.v_set, g.t_set, g.p_set
    g2.attrs = dict(g.attrs)
    g2.type_ids = set(g.type_ids)
    g2.version = getattr(g, "version", 0)
    if getattr(g, "vstore", None) is not None:
        g2.vstore = g.vstore.clone()  # shares the immutable slot arrays
    return g2


def adopt_gstore(g: GStore, g2: GStore) -> None:
    """Swap a loaded partition's contents into an existing GStore object
    IN PLACE (engines, the proxy, and the sharded store all hold references
    to the object — replacing it would strand them on the dead store). The
    store version is force-bumped past the current one so device caches
    restage unconditionally. Cannot fail partway: the caller validates
    (loads) every bundle BEFORE adopting any of them."""
    g.segments = g2.segments
    g.index = g2.index
    g.vp = g2.vp
    g.v_set, g.t_set, g.p_set = g2.v_set, g2.t_set, g2.p_set
    g.attrs = g2.attrs
    g.type_ids = g2.type_ids
    # the embedding plane swaps with the graph (an adopted world without
    # a vstore must also DROP any stale one the target carried)
    g.vstore = getattr(g2, "vstore", None)
    g.version = max(getattr(g, "version", 0), g2.version) + 1


def restore_gstore_into(g: GStore, path: str) -> None:
    """Load a bundle and adopt it into an existing GStore object."""
    g2 = load_gstore(path)
    if g2.sid != g.sid or g2.num_workers != g.num_workers:
        raise CheckpointCorrupt(
            f"partition mismatch: bundle is {g2.sid}/{g2.num_workers}, "
            f"target is {g.sid}/{g.num_workers}", path=path)
    adopt_gstore(g, g2)


def checkpoint_part_path(dirname: str, idx: int) -> str:
    return os.path.join(dirname, f"part{idx}.npz")
