"""GStore persistence: save/load a built partition as one .npz bundle.

The reference always re-ingests ID-triple files at boot and only persists
optimizer statistics (stats.hpp:585-640). Rebuilding 300M+ triples of CSR on a
single host core is minutes of lexsort, so the TPU build adds store-level
checkpointing: a built partition round-trips through one compressed npz.
"""

from __future__ import annotations

import json
import os

import numpy as np

from wukong_tpu.store.gstore import AttrSegment, GStore
from wukong_tpu.store.segment import CSRSegment


def save_gstore(g: GStore, path: str) -> None:
    arrays: dict[str, np.ndarray] = {}
    meta = {"sid": g.sid, "num_workers": g.num_workers,
            "type_ids": sorted(g.type_ids), "segments": [], "index": [],
            "vp": [], "attrs": []}
    for i, ((pid, d), seg) in enumerate(sorted(g.segments.items())):
        meta["segments"].append([int(pid), int(d)])
        arrays[f"seg{i}_k"] = seg.keys
        arrays[f"seg{i}_o"] = seg.offsets
        arrays[f"seg{i}_e"] = seg.edges
    for i, ((tpid, d), arr) in enumerate(sorted(g.index.items())):
        meta["index"].append([int(tpid), int(d)])
        arrays[f"idx{i}"] = arr
    for i, (d, seg) in enumerate(sorted(g.vp.items())):
        meta["vp"].append(int(d))
        arrays[f"vp{i}_k"] = seg.keys
        arrays[f"vp{i}_o"] = seg.offsets
        arrays[f"vp{i}_e"] = seg.edges
    for i, (aid, seg) in enumerate(sorted(g.attrs.items())):
        meta["attrs"].append([int(aid), int(seg.type)])
        arrays[f"attr{i}_k"] = seg.keys
        arrays[f"attr{i}_v"] = seg.values
    arrays["v_set"] = g.v_set
    arrays["t_set"] = g.t_set
    arrays["p_set"] = g.p_set
    arrays["_meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    np.savez(path, **arrays)


def load_gstore(path: str) -> GStore:
    z = np.load(path if path.endswith(".npz") else path + ".npz")
    meta = json.loads(bytes(z["_meta"]).decode())
    g = GStore(sid=meta["sid"], num_workers=meta["num_workers"])
    g.type_ids = set(meta["type_ids"])
    for i, (pid, d) in enumerate(meta["segments"]):
        g.segments[(pid, d)] = CSRSegment(
            keys=z[f"seg{i}_k"], offsets=z[f"seg{i}_o"], edges=z[f"seg{i}_e"])
    for i, (tpid, d) in enumerate(meta["index"]):
        g.index[(tpid, d)] = z[f"idx{i}"]
    for i, d in enumerate(meta["vp"]):
        g.vp[d] = CSRSegment(keys=z[f"vp{i}_k"], offsets=z[f"vp{i}_o"],
                             edges=z[f"vp{i}_e"])
    for i, (aid, at) in enumerate(meta["attrs"]):
        g.attrs[aid] = AttrSegment(keys=z[f"attr{i}_k"], values=z[f"attr{i}_v"],
                                   type=at)
    g.v_set = z["v_set"]
    g.t_set = z["t_set"]
    g.p_set = z["p_set"]
    return g
