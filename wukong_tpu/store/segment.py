"""CSR segment: the TPU-native replacement for the reference's hash-table store.

The reference stores edges in a cluster-chaining hash table keyed by
(vid, pid, dir) (core/store/gstore.hpp:55-120) and probes it per row. Pointer
chasing is hostile to a vector unit, so we keep the reference's *segment*
abstraction (one segment per (pid, dir) — core/store/meta.hpp:78-142) but encode
each segment as CSR: a sorted unique key array + offsets + edge array. Lookup is
a binary search (host: np.searchsorted; device: vectorized searchsorted/gather),
which is what the reference's GPU engine approximates with block-mapped hash
probes (core/gpu/gpu_hash.cu:149-260).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class CSRSegment:
    keys: np.ndarray  # [K] sorted unique vertex ids
    offsets: np.ndarray  # [K+1] int64 prefix offsets into edges
    edges: np.ndarray  # [E] neighbor ids, sorted within each key's range

    @staticmethod
    def empty(dtype=np.int64) -> "CSRSegment":
        return CSRSegment(
            keys=np.empty(0, dtype=dtype),
            offsets=np.zeros(1, dtype=np.int64),
            edges=np.empty(0, dtype=dtype),
        )

    @staticmethod
    def from_pairs(k: np.ndarray, v: np.ndarray) -> "CSRSegment":
        """Build from parallel (key, value) arrays; sorts by (key, value), dedups pairs."""
        if len(k) == 0:
            return CSRSegment.empty(k.dtype if len(k) else np.int64)
        order = np.lexsort((v, k))
        return CSRSegment.from_sorted_pairs(k[order], v[order])

    @staticmethod
    def from_sorted_pairs(k: np.ndarray, v: np.ndarray) -> "CSRSegment":
        """Build from arrays already sorted by (key, value); dedups pairs."""
        if len(k) == 0:
            return CSRSegment.empty(np.int64)
        # drop duplicate (k, v) pairs (the reference dedups at insert for some paths)
        keep = np.ones(len(k), dtype=bool)
        keep[1:] = (k[1:] != k[:-1]) | (v[1:] != v[:-1])
        k, v = k[keep], v[keep]
        keys, counts = np.unique(k, return_counts=True)
        offsets = np.zeros(len(keys) + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        return CSRSegment(keys=keys, offsets=offsets, edges=v)

    @property
    def num_keys(self) -> int:
        return len(self.keys)

    @property
    def num_edges(self) -> int:
        return len(self.edges)

    def lookup(self, vid: int) -> np.ndarray:
        """Edge list of one key (empty if absent) — GStore::get_edges analogue."""
        i = np.searchsorted(self.keys, vid)
        if i < len(self.keys) and self.keys[i] == vid:
            return self.edges[self.offsets[i]:self.offsets[i + 1]]
        return self.edges[0:0]

    def lookup_many(self, vids: np.ndarray):
        """Vectorized lookup: returns (start, degree) per query vid (0 deg if absent)."""
        if len(self.keys) == 0:
            z = np.zeros(len(vids), dtype=np.int64)
            return z, z.copy()
        idx = np.searchsorted(self.keys, vids)
        idx_c = np.clip(idx, 0, len(self.keys) - 1)
        found = (idx < len(self.keys)) & (self.keys[idx_c] == vids)
        start = np.where(found, self.offsets[idx_c], 0)
        deg = np.where(found, self.offsets[idx_c + 1] - self.offsets[idx_c], 0)
        return start, deg

    def contains_pair(self, vids: np.ndarray, vals: np.ndarray) -> np.ndarray:
        """Vectorized membership: is `vals[i]` among the edges of `vids[i]`?

        Uses per-row binary search over the (sorted) edge range of each key —
        the k2k/k2c membership kernel (sparql.hpp:416-483) vectorized.
        """
        start, deg = self.lookup_many(vids)
        lo = start.astype(np.int64)
        end = (start + deg).astype(np.int64)
        hi = end.copy()
        if len(self.edges) == 0:
            return np.zeros(len(vids), dtype=bool)
        # branchless lower_bound over each row's ragged [start, end) range
        while True:
            active = lo < hi
            if not active.any():
                break
            mid = (lo + hi) // 2
            mv = self.edges[np.clip(mid, 0, len(self.edges) - 1)]
            less = mv < vals
            lo = np.where(active & less, mid + 1, lo)
            hi = np.where(active & ~less, mid, hi)
        inb = lo < end
        return inb & (self.edges[np.clip(lo, 0, len(self.edges) - 1)] == vals)

    def memory_bytes(self) -> int:
        return self.keys.nbytes + self.offsets.nbytes + self.edges.nbytes
