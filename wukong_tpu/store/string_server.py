"""String <-> ID mapping service (reference: core/string_server.hpp:42-227).

Loads ``str_index`` / ``str_normal`` (+ ``str_attr_index``) tables from a dataset
directory. For synthesized LUBM datasets a ``str_normal_virtual`` marker swaps in
the formulaic VirtualLubmStrings backend — our equivalent of the reference's
memory-frugal bitrie option (string_server.hpp:50-112, utils/bitrie.hpp).
"""

from __future__ import annotations

import json
import os

from wukong_tpu.utils.logger import log_info


class StringServer:
    def __init__(self, dataset_dir: str):
        self.dir = dataset_dir
        self._s2i: dict[str, int] = {}
        self._i2s: dict[int, str] = {}
        self._virtual = None
        self.pid2type: dict[int, int] = {}  # attr predicate -> AttrType tag

        idx_path = os.path.join(dataset_dir, "str_index")
        if os.path.exists(idx_path):
            self._load_table(idx_path)
        attr_path = os.path.join(dataset_dir, "str_attr_index")
        if os.path.exists(attr_path):
            with open(attr_path) as f:
                for line in f:
                    parts = line.rstrip("\n").split("\t")
                    if len(parts) == 3:
                        self._s2i[parts[0]] = int(parts[1])
                        self._i2s[int(parts[1])] = parts[0]
                        self.pid2type[int(parts[1])] = int(parts[2])

        virt_path = os.path.join(dataset_dir, "str_normal_virtual")
        norm_path = os.path.join(dataset_dir, "str_normal")
        if os.path.exists(norm_path):
            self._load_table(norm_path)
        elif os.path.exists(virt_path):
            with open(virt_path) as f:
                meta = json.load(f)
            if meta.get("generator") == "lubm":
                from wukong_tpu.loader.lubm import VirtualLubmStrings

                self._virtual = VirtualLubmStrings(meta["n_univ"], meta["seed"])
                log_info(f"string server: virtual LUBM backend "
                         f"(n_univ={meta['n_univ']}, seed={meta['seed']})")
            elif meta.get("generator") == "watdiv":
                from wukong_tpu.loader.watdiv import VirtualWatdivStrings

                self._virtual = VirtualWatdivStrings(meta["scale"], meta["seed"])
                log_info(f"string server: virtual WatDiv backend "
                         f"(scale={meta['scale']}, seed={meta['seed']})")
            else:
                raise ValueError(f"unknown virtual string backend: {meta}")

    def _load_table(self, path: str) -> None:
        with open(path) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                s, i = line.rsplit("\t", 1)
                self._s2i[s] = int(i)
                self._i2s[int(i)] = s

    # -- API (string_server.hpp str2id/id2str/exist) -----------------------
    def str2id(self, s: str) -> int:
        if s in self._s2i:
            return self._s2i[s]
        if self._virtual is not None:
            return self._virtual.str2id(s)
        raise KeyError(s)

    def id2str(self, i: int) -> str:
        i = int(i)
        if i in self._i2s:
            return self._i2s[i]
        if self._virtual is not None:
            return self._virtual.id2str(i)
        raise KeyError(i)

    def exist(self, s: str) -> bool:
        try:
            self.str2id(s)
            return True
        except KeyError:
            return False

    def exist_id(self, i: int) -> bool:
        try:
            self.id2str(i)
            return True
        except (KeyError, IndexError):
            return False
