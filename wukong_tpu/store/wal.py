"""Write-ahead log for mutations: dynamic inserts + committed stream epochs.

PR 1 made shard failure graceful and PR 2 made ingestion continuous, but
every acknowledged mutation still lived only in volatile DeltaCSRSegment
memory — a crash of the ingest path silently lost acknowledged triples.
This module is the durability rung: mutation batches are appended here
*before* they are acknowledged, so checkpoint + WAL-tail replay
(runtime/recovery.py) reconstructs a byte-identical store.

Format (one ``wal-<first_seq>.log`` per segment):

    MAGIC ("WKWAL1\\n")
    record*   where record = <u32 body_len> <u32 crc32(body)> <body>
    body = pickle((seq, kind, payload_dict))   # numpy arrays pickle intact

Torn tails are expected (a crash mid-append): replay stops at the first
truncated/short final record with a warning — that batch was never
acknowledged, so dropping it is the contract, not data loss. A CRC mismatch
*before* the tail is real corruption and raises a structured
:class:`CheckpointCorrupt` naming the segment.

Sync policy (``wal_sync`` knob): ``none`` flushes to the OS per append,
``interval`` additionally fsyncs at most once per ``wal_sync_interval_s``,
``always`` fsyncs every append (classic redo-log durability). Segments
rotate at ``wal_segment_mb``; :meth:`WriteAheadLog.truncate_upto` drops
whole segments entirely covered by a checkpoint.

The process-wide accessor :func:`active_wal` is keyed on the ``wal_dir``
knob — empty (the default) means every mutation hook degrades to a single
string check, keeping the serving hot path untouched.
"""

from __future__ import annotations

import os
import pickle
import struct
import sys
import threading
import time
import zlib
from dataclasses import dataclass

import numpy as np

from wukong_tpu.analysis.lockdep import (
    declare_leaf,
    make_lock,
    make_rlock,
    register_global_lock,
)
from wukong_tpu.config import Global
from wukong_tpu.utils.errors import CheckpointCorrupt
from wukong_tpu.utils.logger import log_warn

MAGIC = b"WKWAL1\n"
_HDR = struct.Struct("<II")  # body length, crc32(body)

SYNC_POLICIES = ("none", "interval", "always")

# the per-WAL segment lock is a declared LEAF: code holding it only does
# file I/O and never calls back out into locked subsystems — acquiring any
# tracked lock (the mutation lock above all) while holding it is a
# lock-order inversion the lockdep checker flags
declare_leaf("wal.segment")


def _emit_wal_event(kind: str, **attrs) -> None:
    """Cluster-event journal hook (obs/events.py), lazily imported so the
    WAL's import graph stays flat. MUST be called with the segment lock
    released: the journal's ring lock is itself a lockdep leaf, and
    acquiring any lock under wal.segment is an inversion."""
    from wukong_tpu.obs.events import emit_event

    emit_event(kind, **attrs)


@dataclass
class WalRecord:
    seq: int
    kind: str  # "insert" (dynamic batch) | "epoch" (stream commit)
    payload: dict


def _metrics():
    from wukong_tpu.obs.metrics import get_registry

    reg = get_registry()
    return (
        reg.counter("wukong_wal_appends_total", "WAL records appended",
                    labels=("kind",)),
        reg.counter("wukong_wal_bytes_total", "WAL bytes written"),
        reg.counter("wukong_wal_fsyncs_total", "WAL fsync calls"),
        reg.counter("wukong_wal_replayed_total", "WAL records replayed",
                    labels=("kind",)),
    )


class WriteAheadLog:
    """Append-only, checksummed, segment-rotated mutation log."""

    def __init__(self, dirname: str, sync: str | None = None,
                 sync_interval_s: float | None = None,
                 segment_bytes: int | None = None):
        if sync is not None:
            sync = sync.strip().lower()
            if sync not in SYNC_POLICIES:
                raise ValueError(f"wal_sync must be one of {SYNC_POLICIES}, "
                                 f"got {sync!r}")
        self.dir = dirname
        # None = follow the runtime-mutable Global.wal_sync knob per append
        # (an operator flipping `wal_sync always` on a live system must get
        # the stronger policy immediately, not at the next restart)
        self._sync_override = sync
        self._sync_interval_override = (None if sync_interval_s is None
                                        else float(sync_interval_s))
        self.segment_bytes = (Global.wal_segment_mb * (1 << 20)
                              if segment_bytes is None else int(segment_bytes))
        self._lock = make_lock("wal.segment")
        self._fh = None  # guarded by: _lock
        self._fh_bytes = 0  # guarded by: _lock
        self._last_fsync = 0.0  # guarded by: _lock
        # recovery replay must not re-log what it applies
        self._suppress = 0  # guarded by: _lock
        (self._m_appends, self._m_bytes, self._m_fsyncs,
         self._m_replayed) = _metrics()
        os.makedirs(dirname, exist_ok=True)
        self.next_seq = self._scan_next_seq()  # guarded by: _lock

    # ------------------------------------------------------------------
    def _segments(self) -> list[tuple[int, str]]:
        """(first_seq, path) of every on-disk segment, ascending."""
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("wal-") and name.endswith(".log"):
                try:
                    first = int(name[4:-4])
                except ValueError:
                    continue
                out.append((first, os.path.join(self.dir, name)))
        return sorted(out)

    @property
    def sync(self) -> str:
        if self._sync_override is not None:
            return self._sync_override
        live = (Global.wal_sync or "none").strip().lower()
        return live if live in SYNC_POLICIES else "none"

    @property
    def sync_interval_s(self) -> float:
        return (self._sync_interval_override
                if self._sync_interval_override is not None
                else float(Global.wal_sync_interval_s))

    def _scan_next_seq(self) -> int:
        """Find the next seq AND repair a torn tail in place: resuming
        appends after torn bytes would bury the new (acknowledged) record
        behind a mid-segment CRC error — the exact corruption the WAL
        exists to prevent — so the tail segment is truncated back to its
        last valid record before any append."""
        segs = self._segments()
        if not segs:
            return 0
        path = segs[-1][1]
        last_seq, valid_end = self._scan_segment_tail(path)
        if valid_end < os.path.getsize(path):
            dropped = os.path.getsize(path) - valid_end
            log_warn(f"WAL torn tail at {path}:{valid_end}: truncating "
                     f"{dropped} bytes of the "
                     "unacknowledged record before resuming appends")
            _emit_wal_event("wal.torn_tail", path=path, offset=valid_end,
                            dropped_bytes=int(dropped), where="open")
            with open(path, "r+b") as f:
                f.truncate(valid_end)
        return (last_seq + 1) if last_seq is not None else segs[-1][0]

    def _scan_segment_tail(self, path: str) -> tuple[int | None, int]:
        """(last valid seq or None, byte offset just past the last valid
        record) of one segment. Same corruption rules as replay: a torn
        final record is tolerated, a bad CRC before the tail raises."""
        with open(path, "rb") as f:
            data = f.read()
        if not data.startswith(MAGIC):
            raise CheckpointCorrupt("WAL segment missing magic", path=path)
        off = len(MAGIC)
        n = len(data)
        last_seq = None
        while off < n:
            if off + _HDR.size > n:
                break
            blen, crc = _HDR.unpack_from(data, off)
            body = data[off + _HDR.size: off + _HDR.size + blen]
            if len(body) < blen:
                break
            if zlib.crc32(body) != crc:
                if off + _HDR.size + blen >= n:
                    break  # torn in-place overwrite of the final record
                raise CheckpointCorrupt(
                    f"WAL crc mismatch mid-segment at offset {off}",
                    path=path)
            last_seq = pickle.loads(body)[0]
            off += _HDR.size + blen
        return last_seq, off

    # ------------------------------------------------------------------
    # append side
    # ------------------------------------------------------------------
    @property
    def suppressed(self) -> bool:
        return self._suppress > 0  # unguarded: atomic int read; replay raises the count before any hook it replays through can observe it

    def suppress(self):
        """Context manager: WAL hooks become no-ops inside (recovery replay
        re-applies mutations through their normal code paths, which would
        otherwise re-append every record it reads)."""
        wal = self

        class _S:
            def __enter__(self):
                with wal._lock:
                    wal._suppress += 1

            def __exit__(self, *exc):
                with wal._lock:
                    wal._suppress -= 1

        return _S()

    def _open_segment(self, first_seq: int) -> None:  # caller holds: _lock
        if self._fh is not None:
            self._fh.close()
        path = os.path.join(self.dir, f"wal-{first_seq:016d}.log")
        self._fh = open(path, "ab")
        if self._fh.tell() == 0:
            self._fh.write(MAGIC)
        self._fh_bytes = self._fh.tell()

    def append(self, kind: str, **payload) -> int:
        """Durably record one mutation; returns its seq. The ``wal.append``
        fault site fires BEFORE any bytes land, so an injected failure
        leaves both the log and the store untouched (the batch was never
        acknowledged)."""
        from wukong_tpu.runtime import faults

        faults.site("wal.append")
        rotated = None
        with self._lock:
            seq = self.next_seq
            body = pickle.dumps((seq, kind, payload),
                                protocol=pickle.HIGHEST_PROTOCOL)
            if self._fh is None or self._fh_bytes >= self.segment_bytes:
                # a size rotation (an open segment hit wal_segment_mb) is
                # a journal-worthy lifecycle event; the first-ever open is
                # not. Emission waits for the lock release below —
                # events.ring is a leaf and so is wal.segment.
                rotating = self._fh is not None
                self._open_segment(seq)
                if rotating:
                    rotated = self._fh.name
            self._fh.write(_HDR.pack(len(body), zlib.crc32(body)))
            self._fh.write(body)
            self._fh.flush()
            self._fh_bytes += _HDR.size + len(body)
            if self.sync == "always":
                os.fsync(self._fh.fileno())
                self._m_fsyncs.inc()
            elif self.sync == "interval":
                now = time.monotonic()
                if now - self._last_fsync >= self.sync_interval_s:
                    os.fsync(self._fh.fileno())
                    self._last_fsync = now
                    self._m_fsyncs.inc()
            self.next_seq = seq + 1
        self._m_appends.labels(kind=kind).inc()
        self._m_bytes.inc(_HDR.size + len(body))
        if rotated is not None:
            _emit_wal_event("wal.rotate", path=rotated, first_seq=seq)
        return seq

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None

    # ------------------------------------------------------------------
    # replay side
    # ------------------------------------------------------------------
    def _replay_segment(self, path: str, after_seq: int):
        return replay_segment_file(path, after_seq)

    def replay(self, after_seq: int = -1):
        """Yield every durable record with seq > after_seq, oldest first."""
        for _first, path in self._segments():
            for rec in self._replay_segment(path, after_seq):
                self._m_replayed.labels(kind=rec.kind).inc()
                yield rec

    def truncate_upto(self, seq: int) -> int:
        """Drop whole segments whose every record is <= seq (checkpointed).
        A segment straddling the boundary is kept — replay filters by seq,
        so over-retention is only disk, never duplicated application. The
        NEWEST segment is always kept even when fully covered: it anchors
        the sequence namespace — deleting every segment would restart seqs
        at 0 after a reboot while checkpoint manifests still record the old
        high-water mark, silently filtering the restarted (acknowledged)
        records out of replay. Returns segments removed."""
        segs = self._segments()
        removed = 0
        for i, (first, path) in enumerate(segs[:-1]):  # newest never dies
            nxt = segs[i + 1][0]
            # segment covers [first, nxt): droppable iff nxt - 1 <= seq
            # and it is not the active tail
            with self._lock:
                active = (self._fh is not None
                          and os.path.join(
                              self.dir,
                              f"wal-{first:016d}.log") == self._fh.name)
            if nxt - 1 <= seq and not active and nxt > first:
                os.remove(path)
                removed += 1
        return removed


# ---------------------------------------------------------------------------
# read-only replay (module functions, no WriteAheadLog construction)
#
# Worker processes (runtime/procs.py) replay the PARENT's live WAL
# directory to catch up after a checkpoint restore. They must never
# construct a WriteAheadLog on it: the constructor repairs torn tails IN
# PLACE (truncates the file), and a reader racing the parent's appender
# would see a half-written final record as "torn" and destroy acknowledged
# bytes. These functions read with the same corruption rules — torn tail
# tolerated, mid-segment CRC fatal — and never open anything for writing.
# ---------------------------------------------------------------------------

def replay_segment_file(path: str, after_seq: int):
    """Yield records with seq > after_seq from one segment, read-only."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(MAGIC):
        raise CheckpointCorrupt("WAL segment missing magic", path=path)
    off = len(MAGIC)
    n = len(data)
    while off < n:
        if off + _HDR.size > n:
            log_warn(f"WAL torn tail at {path}:{off} (short header); "
                     "dropping the unacknowledged record")
            _emit_wal_event("wal.torn_tail", path=path, offset=off,
                            where="replay")
            return
        blen, crc = _HDR.unpack_from(data, off)
        body = data[off + _HDR.size: off + _HDR.size + blen]
        if len(body) < blen:
            log_warn(f"WAL torn tail at {path}:{off} (short body); "
                     "dropping the unacknowledged record")
            _emit_wal_event("wal.torn_tail", path=path, offset=off,
                            where="replay")
            return
        if zlib.crc32(body) != crc:
            if off + _HDR.size + blen >= n:
                # final record: a torn in-place overwrite, same contract
                log_warn(f"WAL torn tail at {path}:{off} (bad crc on "
                         "final record); dropping it")
                _emit_wal_event("wal.torn_tail", path=path, offset=off,
                                where="replay")
                return
            raise CheckpointCorrupt(
                f"WAL crc mismatch mid-segment at offset {off}",
                path=path)
        seq, kind, payload = pickle.loads(body)
        if seq > after_seq:
            yield WalRecord(seq=seq, kind=kind, payload=payload)
        off += _HDR.size + blen


def replay_dir(dirname: str, after_seq: int = -1):
    """Yield every durable record with seq > after_seq from a WAL
    directory, oldest first, strictly read-only (a torn live tail is
    skipped, never repaired — that is the owning appender's job)."""
    segs = []
    for name in os.listdir(dirname):
        if name.startswith("wal-") and name.endswith(".log"):
            try:
                first = int(name[4:-4])
            except ValueError:
                continue
            segs.append((first, os.path.join(dirname, name)))
    for _first, path in sorted(segs):
        yield from replay_segment_file(path, after_seq)


# ---------------------------------------------------------------------------
# process-wide accessor + the mutation hook
# ---------------------------------------------------------------------------

_state: dict = {"wal": None, "dir": None}  # guarded by: _state_lock
_state_lock = make_lock("wal.state")

# serializes batch mutations (dynamic insert fan-out, stream epoch commits)
# against checkpoint serialization: a checkpoint that captures its WAL
# high-water mark and then serializes stores while a commit is in flight
# would half-contain the racing epoch yet record it as covered. Batch-level
# and reentrant (a commit's nested per-store inserts run on the same
# thread), so the uncontended cost is one lock op per BATCH, not per row.
_commit_lock = make_rlock("wal.mutation_lock")


def mutation_lock() -> "threading.RLock":
    """THE coarse outer commit lock. Always reach it through this accessor
    (never bind ``_commit_lock`` at import): lockdep's ``install()``
    rebuilds the module-level object when the chaos/recovery/batch suites
    flip the process into checked mode."""
    return _commit_lock


# these two are created at import time — before any test can flip the
# debug_locks knob — so they register for lockdep.install() rebinding
register_global_lock(sys.modules[__name__], "_state_lock", "wal.state")
register_global_lock(sys.modules[__name__], "_commit_lock",
                     "wal.mutation_lock", kind="rlock")


def active_wal() -> WriteAheadLog | None:
    """The process WAL per the ``wal_dir`` knob (None when unset). Keyed on
    the directory so tests pointing the knob at fresh tmp dirs get fresh
    logs; the empty-knob fast path is one string check."""
    d = Global.wal_dir
    if not d:
        return None
    with _state_lock:
        if _state["dir"] != d:
            if _state["wal"] is not None:
                _state["wal"].close()
            _state["wal"] = WriteAheadLog(d)
            _state["dir"] = d
        return _state["wal"]


def reset_wal() -> None:
    """Drop the cached process WAL (tests; config reloads pick up a new
    directory automatically via active_wal's key check)."""
    with _state_lock:
        if _state["wal"] is not None:
            _state["wal"].close()
        _state["wal"] = None
        _state["dir"] = None


def maybe_wal_append(kind: str, triples, dedup: bool, ts=None,
                     **extra) -> int | None:
    """THE durability hook every primary mutation path routes through
    (scripts/lint_obs.py gate 3 enforces this at lint time). No-op (None)
    when the WAL is off or a recovery replay is in flight."""
    wal = active_wal()
    if wal is None or wal.suppressed:
        return None
    return wal.append(kind, triples=np.asarray(triples, dtype=np.int64),
                      dedup=bool(dedup), ts=ts, **extra)
