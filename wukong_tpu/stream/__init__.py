"""Streaming subsystem: live triple ingestion + continuous SPARQL.

The Wukong+S (SOSP'17) capability ported onto this engine: timestamped
triple batches stream into the dynamic store in epoch-stamped commits
(ingest.py), registered SPARQL BGPs are evaluated *incrementally* on each
epoch's delta via semi-naive rewriting over the existing expand kernels
(continuous.py), and sliding/tumbling windows retire expired epochs and
retract their contribution (windows.py).

:class:`StreamContext` is the assembled facade the proxy exposes
(register/unregister/poll/feed verbs, runtime/proxy.py).
"""

from __future__ import annotations

import numpy as np

from wukong_tpu.stream.continuous import (
    ContinuousEngine,
    ResultDelta,
    StandingQuery,
    match_delta,
)
from wukong_tpu.stream.ingest import (
    EpochRecord,
    FileSource,
    ReplaySource,
    StreamIngestor,
)
from wukong_tpu.stream.windows import EpochWindow, WindowSpec

__all__ = [
    "ContinuousEngine", "EpochRecord", "EpochWindow", "FileSource",
    "ReplaySource", "ResultDelta", "StandingQuery", "StreamContext",
    "StreamIngestor", "WindowSpec", "match_delta",
]


class StreamContext:
    """One store's streaming runtime: ingestor + standing-query registry.

    ``stores`` lists every insert target (the host partition first; the
    distributed shards ride along like `load -d`); delta evaluation runs
    against ``stores[0]``. With ``pool`` set, delta queries ride the engine
    pool's stream lane instead of executing inline.
    """

    def __init__(self, stores: list, str_server=None, engine=None, pool=None,
                 monitor=None, dedup: bool = True):
        self.continuous = ContinuousEngine(
            stores[0], str_server, engine=engine, pool=pool, monitor=monitor)
        self.ingestor = StreamIngestor(
            stores, continuous=self.continuous, monitor=monitor, dedup=dedup)

    # -- registry verbs -------------------------------------------------
    def register(self, query, window=None, base_triples=None,
                 callback=None, tenant=None) -> int:
        return self.continuous.register(query, window=window,
                                        base_triples=base_triples,
                                        callback=callback, tenant=tenant)

    def unregister(self, qid: int) -> None:
        self.continuous.unregister(qid)

    def poll(self, qid: int, since_epoch: int = -1) -> list[ResultDelta]:
        return self.continuous.poll(qid, since_epoch)

    def result_set(self, qid: int) -> np.ndarray:
        return self.continuous.result_set(qid)

    def prune(self, qid: int, upto_epoch: int) -> int:
        """Free a standing query's consumed sink history (epoch <= cursor)."""
        return self.continuous.prune(qid, upto_epoch)

    # -- ingest verbs ---------------------------------------------------
    @property
    def epoch(self) -> int:
        return self.ingestor.epoch

    def feed(self, triples: np.ndarray, ts: float | None = None) -> EpochRecord:
        """Commit one batch as the next epoch."""
        return self.ingestor.commit_epoch(triples, ts=ts)

    def feed_source(self, source, max_epochs: int | None = None
                    ) -> list[EpochRecord]:
        return self.ingestor.ingest(source, max_epochs=max_epochs)
