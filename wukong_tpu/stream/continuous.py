"""Continuous SPARQL: standing queries evaluated incrementally per epoch.

The Wukong+S core (SOSP'17): a registered BGP query is not re-run from
scratch when new triples arrive — each ingest epoch is evaluated
*semi-naively*. For a query with patterns P1..Pn and an epoch delta D
(the batch's new triples), the new results are exactly

    union over i of  eval(P1..Pi-1, Pi|D, Pi+1..Pn)  against the merged graph

because every new result uses at least one new triple, and the term that
pins pattern i to D covers all results whose (lexicographically first) new
triple matches Pi. Each term is executed by seeding the binding table with
Pi's matches in D — the *frontier* — and running the remaining patterns
through the ordinary engine kernels (known_to_unknown & friends) against
the merged CSR, exactly the delta-join shape GPU Datalog engines use for
semi-naive iteration (arXiv:2501.13051, arXiv:2604.20073). Terms are
planned ONCE at registration (the heuristic planner's ``seed_known`` mode
orders the remaining patterns off the frontier bindings); per epoch only
the seed tables change.

Results are maintained as a set of projected rows; per-epoch additions are
emitted to an append-only per-query sink (:class:`ResultDelta`). Windowed
queries (windows.py) evaluate against a private window store and emit
retraction deltas when epochs retire.

Push-mode sinks (PR 2 follow-up d): ``register(..., callback=fn)`` invokes
``fn(delta)`` for every committed :class:`ResultDelta` next to the pull
``poll()`` surface. Callback exceptions are contained by the per-query
barrier (the epoch stays committed, the pull sink stays correct) and
surface as the ``wukong_stream_callback_errors_total`` metric plus the
query's ``callback_errors`` counter.

Supported standing-query shapes: BGPs with FILTERs, DISTINCT-style set
semantics, const/var subjects and objects, type patterns. Rejected at
registration (structured errors, never silent wrong answers): UNION,
OPTIONAL, variable predicates, attribute patterns, ORDER/LIMIT/OFFSET,
cartesian (disconnected) products.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field

import numpy as np

from wukong_tpu.config import Global
from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.trace import current as current_trace
from wukong_tpu.planner.heuristic import heuristic_plan, plan_seeded_group
from wukong_tpu.sparql.ir import NO_RESULT, Pattern, PatternGroup, SPARQLQuery
from wukong_tpu.types import IN, AttrType
from wukong_tpu.utils.errors import ErrorCode, WukongError, assert_ec
from wukong_tpu.utils.logger import log_warn
from wukong_tpu.utils.timer import get_usec

# bound on waiting for a stream-lane delta term when the deadline knob is
# off — the lane is strictly lowest-priority, so a saturated pool could
# otherwise block the feed forever
STREAM_WAIT_TIMEOUT_S = 60.0

_M_CB_ERRORS = get_registry().counter(
    "wukong_stream_callback_errors_total",
    "Push-sink callback invocations that raised (contained)")
# device-batched frontier seeding (ROADMAP follow-up a, device half):
# outcome=device when one fused XLA call produced every term's row mask,
# host when the epoch was under the amortization threshold / the knob
# pinned host, fallback when the device path failed and the per-term
# NumPy masks served instead
_M_SEED_BATCH = get_registry().counter(
    "wukong_stream_seed_batch_total",
    "Per-epoch frontier seeding by route", labels=("outcome",))


@dataclass
class ResultDelta:
    """One sink entry: rows added (sign=+1) or retracted (sign=-1) at epoch."""

    epoch: int
    sign: int
    rows: np.ndarray  # [k, len(required_vars)], row-sorted

    def __repr__(self):
        s = "+" if self.sign > 0 else "-"
        return f"ResultDelta(epoch={self.epoch}, {s}{len(self.rows)} rows)"


def _triplewise(pat: Pattern) -> tuple[int, int, int]:
    """(s, p, o) in *triple* terms: a direction-IN pattern walks in-edges of
    its subject slot, i.e. the stored triple is (object, p, subject)."""
    if pat.direction == IN:
        return pat.object, pat.predicate, pat.subject
    return pat.subject, pat.predicate, pat.object


def match_delta(pat: Pattern, triples: np.ndarray, row_mask=None):
    """Frontier of one pattern over an epoch batch: (vars, seed_table).

    vars lists the pattern's variable endpoints (triple order, deduped);
    seed_table is the [k, len(vars)] distinct bindings drawn from the batch
    rows matching the pattern's constants. Empty batch -> (vars, 0-row).
    ``row_mask`` supplies a precomputed batch-row match mask (the
    device-batched seeding path) — the host mask passes are then skipped.
    """
    ts, tp, to = _triplewise(pat)
    s, p, o = triples[:, 0], triples[:, 1], triples[:, 2]
    mask = row_mask if row_mask is not None else (p == tp)
    cols = []
    vars_: list[int] = []
    for end, col in ((ts, s), (to, o)):
        if end >= 0:
            if row_mask is None:
                mask = mask & (col == end)
        elif end in vars_:  # repeated var (?x p ?x): equality, one column
            if row_mask is None:
                mask = mask & (s == o)
        else:
            vars_.append(end)
            cols.append(col)
    if not vars_:
        # fully-const pattern: no frontier bindings to seed (rejected at
        # registration for standing queries)
        return vars_, np.empty((0, 0), dtype=np.int64)
    seed = np.stack([c[mask] for c in cols], axis=1).astype(np.int64)
    if len(seed):
        seed = np.unique(seed, axis=0)
    return vars_, seed


def device_seed_masks(patterns: list, triples: np.ndarray, owner=None):
    """Per-term frontier row masks [T, N] through ONE fused XLA call
    (join.kernels.jit_seed_masks) — the device half of ROADMAP follow-up
    (a): a large epoch's T per-term NumPy mask passes collapse into a
    single padded/bucketed dispatch. Returns None when the epoch is under
    the ``join_device_min_candidates`` amortization threshold, the
    ``join_device`` knob pins host, jax is unavailable, or anything in
    the device path fails — the caller then runs the per-term host masks
    (byte-identical by the kernel parity tests). A failure LATCHES host
    on ``owner`` (the ContinuousEngine — the wcoj path's per-query
    ``_join_device_broken`` posture, per engine here), so a deterministic
    failure like >int32 ids is paid once, not re-attempted with a warn
    per epoch. The FRONTIER stays host-partition either way; distributing
    it is item 6ii headroom."""
    knob = str(Global.join_device).strip().lower()
    n = len(triples)
    if (knob == "host" or not patterns or n == 0
            or (owner is not None
                and getattr(owner, "_seed_device_broken", False))
            or (knob != "device"
                and n * len(patterns)
                < max(int(Global.join_device_min_candidates), 1))):
        _M_SEED_BATCH.labels(outcome="host").inc()
        return None
    try:
        from wukong_tpu.join.kernels import (
            jit_seed_masks,
            pad_pow2,
            to_device_i32,
        )

        tp = np.empty(len(patterns), dtype=np.int32)
        ts = np.empty(len(patterns), dtype=np.int32)
        to = np.empty(len(patterns), dtype=np.int32)
        eq = np.zeros(len(patterns), dtype=bool)
        for i, pat in enumerate(patterns):
            ps, pp, po = _triplewise(pat)
            tp[i] = pp
            # -1 marks a wildcard endpoint; a repeated var (?x p ?x) is
            # the equality flag, matching match_delta's host masks
            ts[i] = ps if ps >= 0 else -1
            to[i] = po if po >= 0 else -1
            eq[i] = ps < 0 and ps == po
        npad = pad_pow2(n)
        s = np.full(npad, -1, dtype=np.int64)
        p = np.full(npad, -1, dtype=np.int64)
        o = np.full(npad, -1, dtype=np.int64)
        s[:n], p[:n], o[:n] = triples[:, 0], triples[:, 1], triples[:, 2]
        fn = jit_seed_masks()
        t0 = get_usec()
        masks = np.asarray(fn(
            to_device_i32(s), to_device_i32(p), to_device_i32(o),
            to_device_i32(tp), to_device_i32(ts), to_device_i32(to),
            np.asarray(eq)))[:, :n]  # blocking D2H sync
        _M_SEED_BATCH.labels(outcome="device").inc()
        from wukong_tpu.obs.device import maybe_device_dispatch

        maybe_device_dispatch(
            "stream.seed_masks", template=f"t{len(patterns)}",
            live=n, capacity=npad, wall_us=get_usec() - t0,
            nbytes=3 * 4 * npad + 3 * 4 * len(patterns)
            + len(patterns) * (1 + npad))
        return masks
    except Exception as e:
        _M_SEED_BATCH.labels(outcome="fallback").inc()
        if owner is not None:
            owner._seed_device_broken = True
        log_warn(f"device seed batching degraded to host masks: {e!r}")
        return None


def _term_var_cols(pat: Pattern) -> tuple[list[int], int, int]:
    """A term's variable endpoints in match_delta's triple order, plus
    the stacked-(s, p, o) column each seed column draws from (``ca ==
    cb`` for a one-variable term — the duplicated column dedupes
    identically to a one-column np.unique)."""
    ts, _tp, to = _triplewise(pat)
    vars_: list[int] = []
    cols: list[int] = []
    for end, c in ((ts, 0), (to, 2)):
        if end < 0 and end not in vars_:
            vars_.append(end)
            cols.append(c)
    if not cols:
        return vars_, 0, 0
    if len(cols) == 1:
        return vars_, cols[0], cols[0]
    return vars_, cols[0], cols[1]


def device_seed_extract(patterns: list, triples: np.ndarray, owner=None):
    """FULLY device-evaluated stream frontier (PR 19, consumer 2 of the
    whole-plan compiled posture): one fused XLA call evaluates every
    term's row mask AND its deduped seed rows
    (join.kernels.jit_seed_extract), dropping the per-term host
    ``np.stack``/``np.unique`` partition pin that ``device_seed_masks``
    still paid after its mask dispatch. Returns ``[(vars, seed)]`` in
    term order — byte-identical to :func:`match_delta` per the kernel
    parity tests — or None when the ``template_device`` knob pins host,
    the epoch is under the amortization threshold, or the device path
    failed (latched per engine on ``owner``, the
    ``_seed_device_broken`` posture)."""
    knob = str(Global.template_device).strip().lower()
    n = len(triples)
    if (knob == "host" or not patterns or n == 0
            or (owner is not None
                and getattr(owner, "_seed_extract_broken", False))
            or (knob != "device"
                and n * len(patterns)
                < max(int(Global.join_device_min_candidates), 1))):
        return None
    try:
        from wukong_tpu.join.kernels import (
            jit_seed_extract,
            pad_pow2,
            to_device_i32,
        )

        T = len(patterns)
        tp = np.empty(T, dtype=np.int32)
        ts = np.empty(T, dtype=np.int32)
        to = np.empty(T, dtype=np.int32)
        eq = np.zeros(T, dtype=bool)
        ca = np.zeros(T, dtype=np.int32)
        cb = np.zeros(T, dtype=np.int32)
        metas: list[list[int]] = []
        for i, pat in enumerate(patterns):
            ps, pp, po = _triplewise(pat)
            tp[i] = pp
            ts[i] = ps if ps >= 0 else -1
            to[i] = po if po >= 0 else -1
            eq[i] = ps < 0 and ps == po
            vars_, a, b = _term_var_cols(pat)
            ca[i], cb[i] = a, b
            metas.append(vars_)
        npad = pad_pow2(n)
        s = np.full(npad, -1, dtype=np.int64)
        p = np.full(npad, -1, dtype=np.int64)
        o = np.full(npad, -1, dtype=np.int64)
        s[:n], p[:n], o[:n] = triples[:, 0], triples[:, 1], triples[:, 2]
        fn = jit_seed_extract()
        t0 = get_usec()
        A, B, counts = fn(
            to_device_i32(s), to_device_i32(p), to_device_i32(o),
            to_device_i32(tp), to_device_i32(ts), to_device_i32(to),
            np.asarray(eq), to_device_i32(ca), to_device_i32(cb))
        A, B = np.asarray(A), np.asarray(B)  # blocking D2H sync
        counts = np.asarray(counts)
        _M_SEED_BATCH.labels(outcome="fused").inc()
        from wukong_tpu.obs.device import maybe_device_dispatch

        maybe_device_dispatch(
            "stream.seed_extract", template=f"t{T}",
            live=int(counts.sum()), capacity=npad * T,
            wall_us=get_usec() - t0,
            nbytes=3 * 4 * npad + 5 * 4 * T + 2 * 4 * T * npad)
        out = []
        for i, vars_ in enumerate(metas):
            k = int(counts[i])
            if not vars_:
                out.append((vars_, np.empty((0, 0), dtype=np.int64)))
            elif len(vars_) == 1:
                out.append((vars_,
                            A[i, :k].astype(np.int64).reshape(-1, 1)))
            else:
                out.append((vars_, np.stack(
                    [A[i, :k], B[i, :k]], axis=1).astype(np.int64)))
        return out
    except Exception as e:
        _M_SEED_BATCH.labels(outcome="fallback").inc()
        if owner is not None:
            owner._seed_extract_broken = True
        log_warn(f"fused device seed extraction degraded to host: {e!r}")
        return None


def _pattern_vars(patterns: list[Pattern]) -> set[int]:
    return {v for p in patterns for v in (p.subject, p.object) if v < 0}


@dataclass
class StandingQuery:
    qid: int
    proto: SPARQLQuery  # pristine parsed (unplanned) query, for refreshes
    text: str | None
    patterns: list  # parsed patterns, triple-wise orientation
    required_vars: list
    nvars: int
    term_plans: list  # term_plans[i] = planned remaining patterns for term i
    window: object = None  # EpochWindow | None
    wstore: object = None  # private window store (windowed queries only)
    base_triples: object = None  # static base included in window rebuilds
    support: object = None  # SupportIndex (windowed queries only)
    callback: object = None  # push-mode sink: fn(ResultDelta), exceptions contained
    tenant: str = "default"  # owner — delta queries inherit it (admission)
    seen: set = field(default_factory=set)
    sink: list = field(default_factory=list)  # list[ResultDelta]
    epochs_evaluated: int = 0
    degraded_epochs: int = 0  # epochs where >=1 term failed (missed results)
    callback_errors: int = 0  # push-sink invocations that raised (contained)
    last_eval_us: int = 0

    def result_set(self) -> np.ndarray:
        """Current standing result: row-sorted distinct projected rows."""
        if not self.seen:
            return np.empty((0, len(self.required_vars)), dtype=np.int64)
        return np.asarray(sorted(self.seen), dtype=np.int64)


class ContinuousEngine:
    """Standing-query registry + per-epoch semi-naive evaluator.

    ``engine`` executes delta queries inline (default: a CPUEngine over
    ``gstore``); ``pool`` routes them through the host engine pool's stream
    lane instead (scheduler.py), interleaving with one-shot queries under
    the same deadline/budget machinery.
    """

    def __init__(self, gstore, str_server=None, engine=None, pool=None,
                 monitor=None):
        self.g = gstore
        self.str_server = str_server
        if engine is None:
            from wukong_tpu.engine.cpu import CPUEngine

            engine = CPUEngine(gstore, str_server)
        self.engine = engine
        self.pool = pool
        self.monitor = monitor
        self.queries: dict[int, StandingQuery] = {}
        self._next_qid = 0
        self.last_epoch = 0  # highest epoch evaluated (stamps snapshots)
        self._abandoned: list = []  # timed-out pool handles to reap later

    def _reap_abandoned(self) -> None:
        """Discard completions whose wait timed out on an earlier epoch
        (poll() skips stream-lane qids, so only wait() can free them)."""
        for h in self._abandoned[:]:
            try:
                self.pool.wait(h, timeout=0)
            except TimeoutError:
                continue  # still running; try again next epoch
            self._abandoned.remove(h)

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def register(self, query, window=None, base_triples=None,
                 callback=None, tenant=None) -> int:
        """Register a standing query (SPARQL text or parsed SPARQLQuery).

        ``window`` (WindowSpec) scopes it to the live epochs only, evaluated
        against a private window store; ``base_triples`` [N,3] are static
        triples included in every window rebuild; ``callback`` is a
        push-mode sink invoked as ``callback(delta)`` per committed
        ResultDelta (including the registration snapshot) — exceptions are
        contained and surfaced as a metric, never as a poisoned commit;
        ``tenant`` names the owner — its per-epoch delta queries are
        stamped ``owner_tenant`` so the admission plane's weighted-fair
        scheduling runs this maintenance work at the OWNER's weight
        (priority inheritance), not the anonymous stream lane's.
        """
        if callback is not None and not callable(callback):
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "callback must be callable")
        text = None
        if isinstance(query, str):
            from wukong_tpu.sparql.parser import Parser

            text = query
            query = Parser(self.str_server).parse(query)
        self._validate(query)
        patterns = [copy.copy(p) for p in query.pattern_group.patterns]
        term_plans = [self._plan_term(patterns, i) for i in range(len(patterns))]
        # the full-query plan must also exist (window refreshes re-run it)
        heuristic_plan(copy.deepcopy(query))
        qid = self._next_qid
        self._next_qid += 1
        sq = StandingQuery(
            qid=qid, proto=copy.deepcopy(query), text=text, patterns=patterns,
            required_vars=list(query.result.required_vars),
            nvars=query.result.nvars, term_plans=term_plans,
            callback=callback,
            tenant=(tenant or getattr(query, "tenant", None) or "default"))
        if window is not None:
            from wukong_tpu.stream.windows import (
                EpochWindow,
                SupportIndex,
                WindowSpec,
            )

            if not isinstance(window, WindowSpec):
                raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                                  "window must be a WindowSpec")
            sq.window = EpochWindow(spec=window)
            sq.support = SupportIndex()
            if base_triples is not None:
                sq.base_triples = np.asarray(base_triples, dtype=np.int64)
            sq.wstore = self._build_window_store(sq)
        # initial snapshot: results already derivable at registration time
        # (from the base graph, or base_triples for windowed queries) seed
        # the standing set — epochs only ever add deltas on top of it
        self._snapshot(sq, self.last_epoch,
                       sq.wstore if sq.window is not None else self.g)
        if sq.support is not None:
            # the registration window is empty, so everything seen so far
            # derives from base_triples alone — permanent support (base
            # triples never retire)
            sq.support.note_base(sq.seen)
        self.queries[qid] = sq
        return qid

    def unregister(self, qid: int) -> None:
        assert_ec(qid in self.queries, ErrorCode.UNKNOWN_SUB,
                  f"unknown standing query {qid}")
        del self.queries[qid]

    def poll(self, qid: int, since_epoch: int = -1) -> list[ResultDelta]:
        """Append-only deltas with epoch > since_epoch (the Wukong+S
        client-pull surface). The default returns the full history including
        the registration-time snapshot — which is stamped with the epoch
        current at registration (0 before any feed), so a cursor of 0 would
        hide it for early registrants but not late ones."""
        assert_ec(qid in self.queries, ErrorCode.UNKNOWN_SUB,
                  f"unknown standing query {qid}")
        return [d for d in self.queries[qid].sink if d.epoch > since_epoch]

    def result_set(self, qid: int) -> np.ndarray:
        assert_ec(qid in self.queries, ErrorCode.UNKNOWN_SUB,
                  f"unknown standing query {qid}")
        return self.queries[qid].result_set()

    def prune(self, qid: int, upto_epoch: int) -> int:
        """Free consumed sink history: drop deltas with epoch <= upto_epoch
        (the client's poll cursor). The standing result set is unaffected —
        only the replayable history shrinks. Returns entries dropped.

        Sinks are otherwise unbounded (truncating silently would hand late
        pollers wrong answers), so long-running clients should prune behind
        their cursor."""
        assert_ec(qid in self.queries, ErrorCode.UNKNOWN_SUB,
                  f"unknown standing query {qid}")
        sq = self.queries[qid]
        kept = [d for d in sq.sink if d.epoch > upto_epoch]
        dropped = len(sq.sink) - len(kept)
        sq.sink = kept
        return dropped

    # ------------------------------------------------------------------
    # checkpoint surface (runtime/recovery.py)
    # ------------------------------------------------------------------
    def export_state(self) -> dict:
        """Picklable snapshot of the standing-query registry: pristine
        protos, per-term plans, standing result sets, sink history, and
        window live-epoch bookkeeping. Window *stores* are excluded — they
        rebuild deterministically from the live triples on import. Push
        callbacks are process-local closures and cannot survive a restart;
        they are recorded only as a flag so import can warn."""
        qs = []
        for sq in self.queries.values():
            qs.append({
                "qid": sq.qid, "proto": sq.proto, "text": sq.text,
                "patterns": sq.patterns,
                "required_vars": sq.required_vars, "nvars": sq.nvars,
                "term_plans": sq.term_plans,
                "window": ((sq.window.spec.size, sq.window.spec.slide)
                           if sq.window is not None else None),
                "window_live": (list(sq.window.live)
                                if sq.window is not None else None),
                "base_triples": sq.base_triples,
                "seen": sq.seen, "sink": sq.sink,
                "epochs_evaluated": sq.epochs_evaluated,
                "degraded_epochs": sq.degraded_epochs,
                "callback_errors": sq.callback_errors,
                "had_callback": sq.callback is not None,
            })
        return {"next_qid": self._next_qid, "last_epoch": self.last_epoch,
                "queries": qs}

    def import_state(self, state: dict) -> None:
        """Restore a registry snapshot (replacing the current registry);
        window stores are rebuilt from the checkpointed live epochs."""
        from wukong_tpu.stream.windows import EpochWindow, WindowSpec

        self.queries.clear()
        self._next_qid = int(state["next_qid"])
        self.last_epoch = int(state["last_epoch"])
        for d in state["queries"]:
            sq = StandingQuery(
                qid=d["qid"], proto=d["proto"], text=d["text"],
                patterns=d["patterns"], required_vars=d["required_vars"],
                nvars=d["nvars"], term_plans=d["term_plans"],
                base_triples=d["base_triples"], seen=d["seen"],
                sink=d["sink"], epochs_evaluated=d["epochs_evaluated"],
                degraded_epochs=d["degraded_epochs"],
                callback_errors=d["callback_errors"])
            if d["window"] is not None:
                from wukong_tpu.stream.windows import SupportIndex

                sq.window = EpochWindow(spec=WindowSpec(*d["window"]),
                                        live=list(d["window_live"]))
                sq.wstore = self._build_window_store(sq)
                # support evidence is process-local and rebuilt empty: the
                # retirement path never DEPENDS on it for correctness (the
                # overdelete evaluation drives candidates), it only loses
                # its fast paths until evidence re-accumulates
                sq.support = SupportIndex()
            if d["had_callback"]:
                log_warn(f"standing query {sq.qid}: push callback did not "
                         "survive the restart — re-register the sink")
            self.queries[sq.qid] = sq

    def _validate(self, q: SPARQLQuery) -> None:
        pg = q.pattern_group
        if pg.unions or pg.optional:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "standing queries support BGP+FILTER only "
                              "(no UNION/OPTIONAL)")
        if q.orders or q.limit >= 0 or q.offset > 0:
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "ORDER/LIMIT/OFFSET have no incremental "
                              "semantics; standing results are sets")
        if not pg.patterns:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              "standing query has no patterns")
        for p in pg.patterns:
            if p.predicate < 0:
                raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                                  "variable-predicate patterns are not "
                                  "incrementally evaluable here")
            if p.pred_type != int(AttrType.SID_t):
                raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                                  "attribute patterns are not supported in "
                                  "standing queries")
            if p.subject >= 0 and p.object >= 0:
                raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                                  "fully-constant pattern has no frontier")
        missing = [v for v in q.result.required_vars
                   if v not in _pattern_vars(pg.patterns)]
        if missing:
            raise WukongError(ErrorCode.NO_REQUIRED_VAR,
                              f"projection vars {missing} not bound by the BGP")

    def _plan_term(self, patterns: list[Pattern], i: int) -> list[Pattern]:
        """Order/orient the remaining patterns of term i off the frontier
        bindings of pattern i — done once at registration."""
        seed = {v for v in (_triplewise(patterns[i])[0],
                            _triplewise(patterns[i])[2]) if v < 0}
        pg = PatternGroup(
            patterns=[copy.copy(p) for j, p in enumerate(patterns) if j != i])
        # plan_seeded_group is THE anchorability test (planner.heuristic):
        # True plans in place off the frontier bindings (raising
        # UNKNOWN_PLAN if stuck); False means a disjoint remainder
        if pg.patterns and not plan_seeded_group(pg, seed):
            raise WukongError(
                ErrorCode.UNSUPPORTED_SHAPE,
                f"pattern {patterns[i]!r} shares no variable with the rest "
                "of the BGP (cartesian product is not incrementally "
                "evaluable)")
        return pg.patterns

    # ------------------------------------------------------------------
    # per-epoch evaluation
    # ------------------------------------------------------------------
    def on_epoch(self, epoch: int, triples: np.ndarray, ts=None) -> int:
        """Evaluate every standing query against one committed epoch.

        Called by the ingestor AFTER the batch is inserted into the main
        store. Returns total evaluation microseconds across queries.
        """
        self.last_epoch = max(self.last_epoch, int(epoch))
        total_us = 0
        tr = current_trace()  # the epoch trace, when ingest is traced
        for sq in list(self.queries.values()):
            t0 = get_usec()
            sp = (tr.start_span("stream.eval_query", qid=sq.qid)
                  if tr is not None else None)
            try:
                if sq.window is not None:
                    self._on_epoch_windowed(sq, epoch, triples)
                else:
                    self._delta_eval(sq, epoch, triples, self.engine)
            except Exception as e:
                # the main store already committed this epoch — one query's
                # failure must not escape the commit or starve the others.
                # Its results for this epoch are missing: degraded, never
                # wrong, and never a poisoned ingest path.
                sq.degraded_epochs += 1
                log_warn(f"standing query {sq.qid}: epoch {epoch} "
                         f"evaluation failed: {e!r}")
            sq.epochs_evaluated += 1
            sq.last_eval_us = get_usec() - t0
            if sp is not None:
                tr.end_span(sp, degraded_epochs=sq.degraded_epochs)
            total_us += sq.last_eval_us
        return total_us

    def _delta_eval(self, sq: StandingQuery, epoch: int, triples: np.ndarray,
                    engine) -> None:
        """One semi-naive pass: seed each term's frontier from the batch,
        run the planned remainder against the merged store, merge new rows."""
        from wukong_tpu.runtime.resilience import Deadline

        new_rows: set = set()
        degraded = False
        jobs = []  # (query, term index)
        # fused frontier first (mask + unique seed rows in ONE device
        # call); the mask-only batch and the per-term host masks remain
        # the byte-identical fallbacks, in that order
        seeds = device_seed_extract(sq.patterns, triples, owner=self)
        masks = (None if seeds is not None
                 else device_seed_masks(sq.patterns, triples, owner=self))
        for i, pat in enumerate(sq.patterns):
            if seeds is not None:
                vars_, seed = seeds[i]
            else:
                vars_, seed = match_delta(
                    pat, triples,
                    row_mask=masks[i] if masks is not None else None)
            if len(seed) == 0:
                continue
            q = self._make_delta_query(sq, i, vars_, seed)
            q.deadline = Deadline.from_config()
            jobs.append((q, i))
        if self.pool is not None and engine is self.engine:
            self._reap_abandoned()
            # stream lane: interleave with one-shot queries on the pool.
            # The wait is bounded — the lane is strictly lowest-priority,
            # so sustained interactive load could otherwise starve it and
            # block the feed indefinitely
            timeout = ((Global.query_deadline_ms / 1e3)
                       if Global.query_deadline_ms > 0
                       else STREAM_WAIT_TIMEOUT_S)
            handles = [(self.pool.submit(q, lane="stream"), i)
                       for q, i in jobs]
            outs = []
            for h, i in handles:
                try:
                    outs.append((self.pool.wait(h, timeout=timeout), i))
                except TimeoutError as e:
                    # leave the completion claimable and reap it on a later
                    # epoch; this term's results are missing for this epoch
                    self._abandoned.append(h)
                    outs.append((e, i))
        else:
            outs = []
            for q, i in jobs:
                try:
                    outs.append((engine.execute(q, from_proxy=False), i))
                except Exception as e:  # mirror the pool path's contract
                    outs.append((e, i))
        for out, i in outs:
            if isinstance(out, Exception):
                degraded = True
                log_warn(f"standing query {sq.qid}: term {i} failed at "
                         f"epoch {epoch}: {out!r}")
                continue
            if out.result.status_code != ErrorCode.SUCCESS:
                # deadline/budget expiry or engine error: results of this
                # term are missing for this epoch — degraded, never wrong
                degraded = True
                log_warn(f"standing query {sq.qid}: term {i} degraded at "
                         f"epoch {epoch}: {out.result.status_code.name}")
                continue
            try:
                new_rows |= self._project(out.result, sq.required_vars)
            except WukongError as e:
                degraded = True
                log_warn(f"standing query {sq.qid}: term {i} projection "
                         f"failed at epoch {epoch}: {e!r}")
        if degraded:
            sq.degraded_epochs += 1
        if sq.support is not None and not degraded:
            # per-result support: this epoch's evidence is EVERY row its
            # delta derived (not just the fresh ones — an already-seen row
            # re-derived here is kept alive by this epoch too)
            sq.support.note_epoch(epoch, new_rows)
        fresh = new_rows - sq.seen
        if fresh:
            sq.seen |= fresh
            self._push(sq, ResultDelta(
                epoch=epoch, sign=+1,
                rows=np.asarray(sorted(fresh), dtype=np.int64)))

    def _push(self, sq: StandingQuery, delta: ResultDelta) -> None:
        """Commit one delta: append to the pull sink, then invoke the
        push-mode callback (if any) with its exception contained — a bad
        subscriber degrades to a metric, never into the epoch commit."""
        sq.sink.append(delta)
        if sq.callback is None:
            return
        try:
            sq.callback(delta)
        except Exception as e:
            sq.callback_errors += 1
            _M_CB_ERRORS.inc()
            log_warn(f"standing query {sq.qid}: push callback failed at "
                     f"epoch {delta.epoch}: {e!r}")

    def _make_delta_query(self, sq: StandingQuery, i: int, vars_: list[int],
                          seed: np.ndarray) -> SPARQLQuery:
        q = SPARQLQuery()
        q.pattern_group = PatternGroup(
            patterns=list(sq.term_plans[i]),
            filters=sq.proto.pattern_group.filters)
        res = q.result
        res.nvars = sq.nvars
        for col, v in enumerate(vars_):
            res.add_var2col(v, col)
        res.set_table(seed)
        res.blind = True  # engines skip final-process; we project ourselves
        # priority inheritance: the delta runs AS maintenance for its
        # owner — the pool's fair sub-lane schedules it at that weight
        q.owner_tenant = sq.tenant
        return q

    @staticmethod
    def _project(res, required_vars: list[int]) -> set:
        cols = [res.var2col(v) for v in required_vars]
        if any(c == NO_RESULT for c in cols):
            if res.nrows == 0:
                return set()
            raise WukongError(ErrorCode.NO_REQUIRED_VAR,
                              "standing-query projection var unbound")
        if res.nrows == 0:
            return set()
        return set(map(tuple, res.table[:, cols].tolist()))

    # ------------------------------------------------------------------
    # windowed queries
    # ------------------------------------------------------------------
    def _build_window_store(self, sq: StandingQuery):
        from wukong_tpu.store.gstore import build_partition

        parts = [sq.window.live_triples()]
        if sq.base_triples is not None:
            parts.insert(0, sq.base_triples)
        triples = np.concatenate(parts) if len(parts) > 1 else parts[0]
        return build_partition(triples, 0, 1)

    def _on_epoch_windowed(self, sq: StandingQuery, epoch: int,
                           triples: np.ndarray) -> None:
        from wukong_tpu.engine.cpu import CPUEngine
        from wukong_tpu.runtime.resilience import retry_call
        from wukong_tpu.store.dynamic import insert_triples

        retired = sq.window.add(epoch, triples)
        if retired:
            try:
                self._retire_incremental(sq, epoch, triples, retired)
            except Exception as e:
                # a failed retirement step must not strand half-updated
                # bookkeeping — degrade to the old full refresh (rebuild +
                # re-run + diff): correct, just not incremental
                log_warn(f"standing query {sq.qid}: incremental "
                         f"retirement at epoch {epoch} degraded to full "
                         f"refresh: {e!r}")
                sq.wstore = self._build_window_store(sq)
                if sq.support is not None:
                    sq.support.reset()
                self._snapshot(sq, epoch, sq.wstore)
            return
        try:
            # the private window-store insert is a dynamic.insert fault
            # site like the main commit; dedup makes replays idempotent,
            # so retry the same way
            retry_call(lambda: insert_triples(sq.wstore, triples,
                                              dedup=True, check_ids=False),
                       site="dynamic.insert")
            self._delta_eval(sq, epoch, triples,
                             CPUEngine(sq.wstore, self.str_server))
        except Exception as e:
            # the main store already committed this epoch — a window-side
            # failure must not escape and strand half-updated bookkeeping.
            # Rebuild from the recorded live epochs and diff: a full
            # refresh, correct but not incremental.
            log_warn(f"standing query {sq.qid}: windowed epoch {epoch} "
                     f"degraded to full refresh: {e!r}")
            sq.wstore = self._build_window_store(sq)
            if sq.support is not None:
                sq.support.reset()
            self._snapshot(sq, epoch, sq.wstore)

    def _retire_incremental(self, sq: StandingQuery, epoch: int,
                            triples: np.ndarray, retired: list) -> None:
        """Per-result support-counted retraction (windows.py module doc):
        overdelete candidates from a delta evaluation seeded with the
        RETIRED triples, base-support fast path, targeted re-derivation
        over the rebuilt survivor store, then normal delta evaluation of
        the arriving epoch. Retraction work scales with the rows touching
        retired data, not with the standing result."""
        from wukong_tpu.engine.cpu import CPUEngine

        pre_store = sq.wstore  # base + previously-live epochs
        retired_triples = np.concatenate([t for _, t in retired])
        # 1. overdelete: every row with >=1 derivation using retired data
        cand = self._eval_terms_inline(
            sq, retired_triples, CPUEngine(pre_store, self.str_server))
        cand &= sq.seen
        # 2. support: evidence-exhausted rows are candidates by
        # construction (safety net, normally a subset of the overdelete);
        # base-supported rows never retract and skip verification
        if sq.support is not None:
            cand |= sq.support.retire([e for e, _ in retired]) & sq.seen
            cand -= sq.support.base
        # 3. survivor store INCLUDING the arriving epoch: a candidate row
        # re-derivable through the new triples must not flicker -/+ in
        # one epoch
        sq.wstore = self._build_window_store(sq)
        # 4. re-derive the candidates; the rest of the standing set keeps
        # all its derivations and is untouched
        dead = (cand - self._verify_rows(sq, cand)) if cand else set()
        if dead:
            sq.seen -= dead
            self._push(sq, ResultDelta(
                epoch=epoch, sign=-1,
                rows=np.asarray(sorted(dead), dtype=np.int64)))
        # 5. additions from the arriving epoch (already in the store)
        self._delta_eval(sq, epoch, triples,
                         CPUEngine(sq.wstore, self.str_server))

    def _eval_terms_inline(self, sq: StandingQuery,
                           triples: np.ndarray, engine) -> set:
        """All projected rows derivable with >=1 triple from ``triples``
        against ``engine``'s store (the semi-naive term union, inline).
        Raises on any term failure — the caller falls back to a full
        refresh rather than trusting an incomplete candidate set."""
        rows: set = set()
        seeds = device_seed_extract(sq.patterns, triples, owner=self)
        masks = (None if seeds is not None
                 else device_seed_masks(sq.patterns, triples, owner=self))
        for i, pat in enumerate(sq.patterns):
            if seeds is not None:
                vars_, seed = seeds[i]
            else:
                vars_, seed = match_delta(
                    pat, triples,
                    row_mask=masks[i] if masks is not None else None)
            if len(seed) == 0:
                continue
            q = self._make_delta_query(sq, i, vars_, seed)
            out = engine.execute(q, from_proxy=False)
            if out.result.status_code != ErrorCode.SUCCESS:
                raise WukongError(out.result.status_code,
                                  f"retirement term {i} failed")
            rows |= self._project(out.result, sq.required_vars)
        return rows

    def _verify_rows(self, sq: StandingQuery, cand: set) -> set:
        """Which candidate projected rows still have a full derivation
        over the current window store: seed the BGP with the candidate
        bindings (planned off the projection vars) and re-derive."""
        from wukong_tpu.engine.cpu import CPUEngine
        from wukong_tpu.planner.heuristic import plan_seeded_group

        if not cand:
            return set()
        pg = PatternGroup(
            patterns=[copy.copy(p) for p in sq.proto.pattern_group.patterns],
            filters=sq.proto.pattern_group.filters)
        if not plan_seeded_group(pg, set(sq.required_vars)):
            # cannot anchor on the projection vars (registration rejects
            # cartesian shapes, so this cannot happen) — caller refreshes
            raise WukongError(ErrorCode.UNSUPPORTED_SHAPE,
                              "verification not anchorable")
        q = SPARQLQuery()
        q.pattern_group = pg
        res = q.result
        res.nvars = sq.nvars
        for col, v in enumerate(sq.required_vars):
            res.add_var2col(v, col)
        res.set_table(np.asarray(sorted(cand), dtype=np.int64))
        res.blind = True
        out = CPUEngine(sq.wstore, self.str_server).execute(
            q, from_proxy=False)
        if out.result.status_code != ErrorCode.SUCCESS:
            raise WukongError(out.result.status_code,
                              "candidate re-derivation failed")
        return self._project(out.result, sq.required_vars)

    def _snapshot(self, sq: StandingQuery, epoch: int, store) -> None:
        """Full (non-incremental) evaluation against ``store``; the diff
        against the current standing set is emitted as retraction/addition
        deltas. Used for the registration snapshot and window refreshes."""
        from wukong_tpu.engine.cpu import CPUEngine

        q = copy.deepcopy(sq.proto)
        heuristic_plan(q)
        q.result.blind = True
        eng = CPUEngine(store, self.str_server)
        eng.execute(q, from_proxy=False)
        if q.result.status_code != ErrorCode.SUCCESS:
            sq.degraded_epochs += 1
            log_warn(f"standing query {sq.qid}: snapshot degraded at "
                     f"epoch {epoch}: {q.result.status_code.name}")
            return
        now = self._project(q.result, sq.required_vars)
        gone, fresh = sq.seen - now, now - sq.seen
        if gone:
            self._push(sq, ResultDelta(
                epoch=epoch, sign=-1,
                rows=np.asarray(sorted(gone), dtype=np.int64)))
        if fresh:
            self._push(sq, ResultDelta(
                epoch=epoch, sign=+1,
                rows=np.asarray(sorted(fresh), dtype=np.int64)))
        sq.seen = now
