"""Live triple ingestion: timestamped batch sources -> epoch-stamped commits.

The Wukong+S ingest side: a :class:`TripleSource` yields ``(ts, [N,3])``
batches (replayed from an in-memory array, a datagen directory, or a
timestamped file); a :class:`StreamIngestor` commits each batch into one or
more ``DynamicGStore`` partitions as one *epoch* — the unit of incremental
evaluation (continuous.py) and of window retirement (windows.py). Each
commit bumps the store version (device caches restage lazily) and notifies
the standing-query registry.

Resilience: the commit path is a ``stream.ingest`` fault site wrapped in
``retry_call`` (dedup inserts are idempotent, so a transiently-failed batch
replays safely); the store-level insert exposes its own ``dynamic.insert``
site (store/dynamic.py). Non-dedup ingest does NOT retry — a replayed batch
would double-append — so transients there surface to the caller.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from wukong_tpu.obs.metrics import get_registry
from wukong_tpu.obs.recorder import get_recorder
from wukong_tpu.obs.trace import activate, maybe_start_trace
from wukong_tpu.store.dynamic import insert_triples, migration_sinks
from wukong_tpu.utils.errors import ErrorCode, WukongError
from wukong_tpu.utils.timer import get_usec

# stream-side metrics: committed epochs/triples as counters, per-epoch
# latencies as histograms (the Monitor keeps its private CDF windows; the
# registry feeds the Prometheus/JSON exporters)
_M_EPOCHS = get_registry().counter(
    "wukong_stream_epochs_total", "Committed stream epochs")
_M_TRIPLES = get_registry().counter(
    "wukong_stream_triples_total", "Triples offered to stream commits")
_M_EVAL = get_registry().histogram(
    "wukong_stream_eval_us", "Standing-query evaluation time per epoch")
_M_LAG = get_registry().histogram(
    "wukong_stream_lag_us", "Commit-to-results lag per epoch")

# recent EpochRecords kept for inspection (bounds memory on long-running
# ingest loops; the Monitor's totals/CDFs keep counting past it)
EPOCH_LOG_WINDOW = 4096


@dataclass
class EpochRecord:
    """One committed epoch's bookkeeping (monitor + window bookkeeping)."""

    epoch: int
    ts: float  # source timestamp of the batch (replay time axis)
    n_triples: int  # batch rows offered
    n_inserted: int  # subject-side edges actually new (post-dedup)
    version: int  # store version after the commit
    ingest_us: int = 0
    eval_us: int = 0  # standing-query evaluation time for this epoch

    @property
    def lag_us(self) -> int:
        """Commit-to-results latency: how far results trail ingestion."""
        return self.ingest_us + self.eval_us


class ReplaySource:
    """Replay an in-memory [N,3] triple array as timestamped batches.

    The time axis is synthetic: batch k carries ``ts = start_ts + k*ts_step``.
    This is the datagen-replay path — deterministic, so delta-vs-oracle
    tests and benchmarks see identical schedules.
    """

    def __init__(self, triples: np.ndarray, batch_size: int,
                 start_ts: float = 0.0, ts_step: float = 1.0):
        triples = np.asarray(triples, dtype=np.int64)
        if triples.ndim != 2 or triples.shape[1] != 3:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              f"replay source wants [N,3], got {triples.shape}")
        if batch_size < 1:
            raise WukongError(ErrorCode.SYNTAX_ERROR, "batch_size must be >= 1")
        self.triples = triples
        self.batch_size = int(batch_size)
        self.start_ts = start_ts
        self.ts_step = ts_step

    def __iter__(self):
        for k, lo in enumerate(range(0, len(self.triples), self.batch_size)):
            yield (self.start_ts + k * self.ts_step,
                   self.triples[lo:lo + self.batch_size])


class FileSource:
    """Stream id-triple files (``s\\tp\\to`` rows, optional 4th ``ts``
    column) from a datagen-convention directory, in batches.

    Rows without a timestamp get the synthetic axis (batch index), matching
    ReplaySource; 4-column input is split into per-timestamp batches
    (capped at batch_size) so one epoch never mixes timestamps.

    Timestamped grouping is GLOBAL across the directory (datagen
    ``--timestamps`` writes one id_* file per source file, all spanning the
    same epochs, and rows arrive out of order within a file) — which means
    the 4-column path materializes every file before the first epoch is
    emitted, a deliberate trade: correct epoch order over unsorted input
    needs all rows, and replay directories are bounded. The 3-column path
    streams file by file as before.
    """

    def __init__(self, path: str, batch_size: int = 4096):
        self.path = path
        self.batch_size = int(batch_size)

    def _files(self) -> list[str]:
        if os.path.isfile(self.path):
            return [self.path]
        names = sorted(n for n in os.listdir(self.path)
                       if n.startswith("id_"))
        if not names:
            raise WukongError(ErrorCode.FILE_NOT_FOUND,
                              f"no id_* triple files under {self.path}")
        return [os.path.join(self.path, n) for n in names]

    def __iter__(self):
        k = 0
        pending4: list[np.ndarray] = []  # 4-col files: grouped GLOBALLY
        for f in self._files():
            data = np.loadtxt(f, dtype=np.int64, ndmin=2)
            if data.size == 0:
                continue
            if data.shape[1] == 3:
                if pending4:
                    raise WukongError(
                        ErrorCode.UNKNOWN_PATTERN,
                        f"{f}: 3-column file in a timestamped (4-column) "
                        "directory — one replay cannot mix time axes")
                for lo in range(0, len(data), self.batch_size):
                    yield float(k), data[lo:lo + self.batch_size]
                    k += 1
            elif data.shape[1] == 4:
                if k:
                    raise WukongError(
                        ErrorCode.UNKNOWN_PATTERN,
                        f"{f}: 4-column file in a synthetic-axis (3-column) "
                        "directory — one replay cannot mix time axes")
                # don't yield yet: datagen --timestamps writes one id_*
                # file per source file, each spanning the SAME epochs, so
                # per-file grouping would re-emit a timestamp once per
                # file (splitting one epoch and breaking window
                # retirement order). Collect, then sort/group globally.
                pending4.append(data)
            else:
                raise WukongError(
                    ErrorCode.UNKNOWN_PATTERN,
                    f"{f}: want 3 (s p o) or 4 (s p o ts) columns, "
                    f"got {data.shape[1]}")
        if pending4:
            data = np.concatenate(pending4)
            ts_col = data[:, 3]
            order = np.argsort(ts_col, kind="stable")
            data, ts_col = data[order], ts_col[order]
            uts, starts = np.unique(ts_col, return_index=True)
            bounds = np.append(starts, len(data))
            for t, lo, hi in zip(uts, bounds[:-1], bounds[1:]):
                for blo in range(int(lo), int(hi), self.batch_size):
                    yield (float(t),
                           data[blo:min(blo + self.batch_size, hi), :3])


class StreamIngestor:
    """Commits source batches into the store(s) as numbered epochs.

    ``stores`` are the insert targets (host partition + distributed shards,
    like `load -d`); ``continuous`` is the standing-query registry notified
    after every commit; ``monitor`` collects stream lag / per-epoch latency.
    """

    def __init__(self, stores: list, continuous=None, monitor=None,
                 dedup: bool = True):
        self.stores = list(stores)  # lock-free: whole-list rebinding (recovery heals swap it atomically); commit iterates a snapshot reference
        self.continuous = continuous
        self.monitor = monitor
        self.dedup = bool(dedup)
        # the epoch counter advances only inside the WAL mutation lock —
        # the same lock that makes a commit atomic w.r.t. checkpoints
        self.epoch = 0  # guarded by: mutation_lock()
        # recent epochs (bounded)
        self.log: deque = deque(maxlen=EPOCH_LOG_WINDOW)  # lock-free: atomic deque append; report readers tolerate a stale tail

    def commit_epoch(self, triples: np.ndarray, ts: float | None = None
                     ) -> EpochRecord:
        """Insert one batch as the next epoch, then evaluate standing
        queries on its delta. Returns the epoch's record."""
        from wukong_tpu.runtime import faults
        from wukong_tpu.store.gstore import check_vid_range

        triples = np.asarray(triples, dtype=np.int64)
        if triples.ndim != 2 or triples.shape[1] != 3:
            raise WukongError(ErrorCode.UNKNOWN_PATTERN,
                              f"epoch batch wants [N,3], got {triples.shape}")
        check_vid_range(triples)  # once per epoch, not per store
        # durability first (store/wal.py): the epoch is logged BEFORE any
        # store mutates, so a crash mid-apply replays it to completion and
        # a WAL failure fails the commit with the stores untouched — either
        # way no acknowledged epoch is ever lost. The mutation lock keeps
        # the whole commit (log + insert fan-out + registry eval) atomic
        # w.r.t. checkpoint serialization (runtime/recovery.py).
        from wukong_tpu.store.wal import maybe_wal_append, mutation_lock

        # per-epoch trace (the stream lane's unit of work): ingest + eval
        # spans, recorded into the same flight recorder as query traces
        trace = maybe_start_trace(kind="stream")
        t0 = get_usec()

        inserted = [0]  # accumulated across retry attempts: a store that
        # committed before a mid-loop transient dedups its replay to 0, so
        # only the running total counts every edge exactly once

        def _ingest() -> int:
            faults.site("stream.ingest")
            for g in self.stores:
                inserted[0] += insert_triples(g, triples, dedup=self.dedup,
                                              check_ids=False)
            # migration_sinks() read under the mutation lock this commit
            # holds: an epoch committed during a shard migration's
            # dual-write window reaches the recipient too (no epoch
            # lost). Excluded from the inserted count — the sink is a
            # transient mirror of a store already counted
            for g in migration_sinks():
                insert_triples(g, triples, dedup=self.dedup,
                               check_ids=False)
            return inserted[0]

        with mutation_lock(), activate(trace):
            maybe_wal_append("epoch", triples, self.dedup, ts=ts,
                             epoch=self.epoch + 1)
            if trace is None:
                n_ins = self._commit(_ingest)
            else:
                with trace.span("stream.ingest", n_triples=len(triples)):
                    n_ins = self._commit(_ingest)

            self.epoch += 1
            rec = EpochRecord(
                epoch=self.epoch,
                ts=float(ts) if ts is not None else float(self.epoch),
                n_triples=len(triples), n_inserted=n_ins,
                version=getattr(self.stores[0], "version", 0),
                ingest_us=get_usec() - t0)
            if self.continuous is not None:
                if trace is None:
                    rec.eval_us = self.continuous.on_epoch(
                        self.epoch, triples, rec.ts)
                else:
                    with trace.span("stream.eval", epoch=self.epoch):
                        rec.eval_us = self.continuous.on_epoch(
                            self.epoch, triples, rec.ts)
            # the serving plane's actuator edge (wukong_tpu/serve/):
            # INSIDE the mutation lock — materialized-view maintenance
            # re-keys surviving result-cache entries atomically with the
            # epoch's version bump (a view is never visible at a version
            # it doesn't match). One knob check when the cache is off.
            from wukong_tpu.serve import notify_mutation

            notify_mutation("epoch", version=rec.version,
                            triples=triples)
        # cache-coherence telemetry (obs/reuse.py): the epoch's version
        # edge kills stale shadow keys + journals cache.invalidate —
        # outside the mutation lock, pure observability
        from wukong_tpu.obs.reuse import maybe_note_invalidation

        maybe_note_invalidation("epoch", version=rec.version,
                                epoch=rec.epoch,
                                n_triples=rec.n_triples)
        if self.monitor is not None:
            self.monitor.record_stream_epoch(
                n_triples=rec.n_triples, ingest_us=rec.ingest_us,
                eval_us=rec.eval_us, lag_us=rec.lag_us)
        _M_EPOCHS.inc()
        _M_TRIPLES.inc(rec.n_triples)
        _M_EVAL.observe(rec.eval_us)
        _M_LAG.observe(rec.lag_us)
        if trace is not None:
            # rec.epoch, not self.epoch: past the mutation lock a racing
            # commit may already have advanced the shared counter (found
            # by the guarded-by gate)
            trace.qid = rec.epoch  # epoch number IS the stream qid
            get_recorder().on_complete(trace)
        self.log.append(rec)
        return rec

    def _commit(self, _ingest) -> int:
        from wukong_tpu.runtime.faults import TransientFault
        from wukong_tpu.runtime.resilience import retry_call

        if self.dedup:
            # idempotent under dedup: a replayed batch re-drops as duplicate
            return retry_call(_ingest, site="stream.ingest",
                              retry_on=(TransientFault, OSError))
        return _ingest()

    def commit_vector_epoch(self, vids, vecs=None,
                            tombstone: bool = False) -> int:
        """Vector-plane sibling of commit_epoch: apply one embedding
        upsert (or tombstone) batch to the same store fan-out this
        ingestor commits triple epochs into. WAL-before-ack, migration
        dual-write sinks, version bumps, and serving invalidation all
        live in upsert_batch_into — this seam just keeps stream-fed
        embeddings and stream-fed triples on one target list (the
        recovery heals that rebind ``stores`` cover both planes)."""
        from wukong_tpu.vector.vstore import upsert_batch_into

        return upsert_batch_into(self.stores, vids, vecs,
                                 dedup=self.dedup, tombstone=tombstone)

    def ingest(self, source, max_epochs: int | None = None) -> list[EpochRecord]:
        """Drain a TripleSource (or any (ts, batch) iterable) into epochs."""
        out = []
        for ts, batch in source:
            out.append(self.commit_epoch(batch, ts=ts))
            if max_epochs is not None and len(out) >= max_epochs:
                break
        return out
