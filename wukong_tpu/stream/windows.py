"""Epoch windows for standing queries (the Wukong+S window layer).

Wukong+S (SOSP'17) evaluates continuous queries over a bounded suffix of the
stream; expired data is retired and its contribution to standing results is
retracted. Here windows are *epoch-counted*: every ingest commit is one epoch
(ingest.py stamps them), and a :class:`WindowSpec` selects which epochs are
live.

Semantics (one rule covers both classic shapes):

- the window *closes* at every epoch divisible by ``slide``; an arriving
  epoch ``e`` first retires everything no longer reachable from the current
  window: with ``c = ((e - 1) // slide) * slide`` the last close before
  ``e``, all epochs ``<= c - (size - slide)`` retire.
- ``slide=1`` (default) is a **sliding** window: the live set is always the
  last ``size`` epochs.
- ``slide == size`` is a **tumbling** window: the previous window's contents
  retire in whole-window bulk as soon as the next window opens, so a
  mid-window epoch is never evaluated against an already-reported window.

Retraction strategy: delta evaluation is monotone (append-only), so expiry
needs its own machinery. The window keeps the raw triples of each live
epoch plus a per-result :class:`SupportIndex`; on retirement the standing
query retracts *incrementally* (continuous.py ``_retire_incremental``):

1. **Overdelete candidates** — delta evaluation seeded from the RETIRED
   triples over the pre-retirement window store finds exactly the result
   rows with at least one derivation touching retired data; every other
   row keeps all its derivations and is untouched (the DRed overdelete
   step, scoped to windows).
2. **Support fast path** — rows whose support includes the static base
   (derived at registration from ``base_triples`` alone, which never
   retire) skip verification entirely; the per-epoch evidence counts
   bound the candidate set from below (an evidence-exhausted row is
   always a candidate).
3. **Re-derive** — the surviving candidates are re-verified by seeding
   the full BGP with their projected bindings over the rebuilt survivor
   store; rows with no remaining derivation emit retraction deltas.

Retraction work is therefore proportional to the rows actually touching
retired epochs, not to the full standing result (the old behavior — a
from-scratch re-run + diff per close — survives only as the fallback when
a retirement step fails).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class SupportIndex:
    """Per-result support bookkeeping for one windowed standing query.

    ``base`` holds rows derivable from the static ``base_triples`` alone
    (recorded at registration; base triples never retire, so these rows
    never retract and skip re-verification). ``by_epoch`` records, per
    live epoch, the rows that epoch's delta evaluation derived — its
    memory is bounded by the window size. ``counts`` is the live evidence
    count per row (how many live-epoch deltas derived it, the "support"
    the retirement step consumes).
    """

    base: set = field(default_factory=set)
    by_epoch: dict = field(default_factory=dict)  # epoch -> set(rows)
    counts: dict = field(default_factory=dict)  # row -> live evidence

    def note_base(self, rows) -> None:
        self.base |= set(rows)

    def note_epoch(self, epoch: int, rows) -> None:
        rows = set(rows)
        self.by_epoch[int(epoch)] = rows
        for r in rows:
            self.counts[r] = self.counts.get(r, 0) + 1

    def retire(self, epochs) -> set:
        """Drop retired epochs' evidence; returns the rows whose live
        evidence is now exhausted (excluding base-supported rows) — a
        LOWER bound on the retraction candidates: a row with surviving
        evidence may still be dead (its surviving-epoch derivation can
        use retired triples), which is why the overdelete evaluation, not
        this set, drives candidate selection."""
        dead = set()
        for e in epochs:
            for r in self.by_epoch.pop(int(e), ()):
                c = self.counts.get(r, 0) - 1
                if c <= 0:
                    self.counts.pop(r, None)
                    if r not in self.base:
                        dead.add(r)
                else:
                    self.counts[r] = c
        return {r for r in dead if self.counts.get(r, 0) == 0}

    def support_of(self, row) -> int:
        """Live evidence count (+1 if base-supported) — telemetry."""
        return self.counts.get(row, 0) + (1 if row in self.base else 0)

    def reset(self) -> None:
        """Forget per-epoch evidence (full-refresh fallback); the base
        set stays — base triples never retire, so it can't go stale."""
        self.by_epoch.clear()
        self.counts.clear()


@dataclass(frozen=True)
class WindowSpec:
    """size: how many epochs stay live; slide: how often the window closes."""

    size: int
    slide: int = 1

    def __post_init__(self):
        if self.size < 1:
            raise ValueError(f"window size must be >= 1, got {self.size}")
        if self.slide < 1 or self.slide > self.size:
            raise ValueError(
                f"window slide must be in [1, size], got {self.slide}")

    @classmethod
    def tumbling(cls, size: int) -> "WindowSpec":
        return cls(size=size, slide=size)


@dataclass
class EpochWindow:
    """Live-epoch bookkeeping for one windowed standing query."""

    spec: WindowSpec
    # (epoch, triples) in epoch order — raw batches kept for rebuilds
    live: list = field(default_factory=list)

    def add(self, epoch: int, triples: np.ndarray) -> list:
        """Admit one epoch; returns the list of (epoch, triples) entries
        retired by this advance (non-empty only on the first epoch after a
        close — once per ``slide``, the amortized rebuild cadence)."""
        self.live.append((int(epoch), triples))
        last_close = (epoch - 1) // self.spec.slide * self.spec.slide
        cutoff = last_close - (self.spec.size - self.spec.slide)
        retired = [ent for ent in self.live if ent[0] <= cutoff]
        if retired:
            self.live = [ent for ent in self.live if ent[0] > cutoff]
        return retired

    def live_epochs(self) -> list[int]:
        return [e for e, _ in self.live]

    def live_triples(self) -> np.ndarray:
        """All live triples as one [N,3] array (rebuild input)."""
        if not self.live:
            return np.empty((0, 3), dtype=np.int64)
        return np.concatenate([t for _, t in self.live])
