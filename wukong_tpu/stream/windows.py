"""Epoch windows for standing queries (the Wukong+S window layer).

Wukong+S (SOSP'17) evaluates continuous queries over a bounded suffix of the
stream; expired data is retired and its contribution to standing results is
retracted. Here windows are *epoch-counted*: every ingest commit is one epoch
(ingest.py stamps them), and a :class:`WindowSpec` selects which epochs are
live.

Semantics (one rule covers both classic shapes):

- the window *closes* at every epoch divisible by ``slide``; an arriving
  epoch ``e`` first retires everything no longer reachable from the current
  window: with ``c = ((e - 1) // slide) * slide`` the last close before
  ``e``, all epochs ``<= c - (size - slide)`` retire.
- ``slide=1`` (default) is a **sliding** window: the live set is always the
  last ``size`` epochs.
- ``slide == size`` is a **tumbling** window: the previous window's contents
  retire in whole-window bulk as soon as the next window opens, so a
  mid-window epoch is never evaluated against an already-reported window.

Retraction strategy: delta evaluation is monotone (append-only), so expiry
cannot be incrementalized without per-result support counting. Instead the
window keeps the raw triples of each live epoch; on retirement the standing
query's window store is rebuilt from the surviving epochs and the query is
re-run from scratch over it (continuous.py `_on_epoch_windowed`). Rebuilds
happen once per ``slide`` epochs — the amortized shape Wukong+S gets from its
per-window sub-stores — and the diff against the previous result set yields
the retraction deltas.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass(frozen=True)
class WindowSpec:
    """size: how many epochs stay live; slide: how often the window closes."""

    size: int
    slide: int = 1

    def __post_init__(self):
        if self.size < 1:
            raise ValueError(f"window size must be >= 1, got {self.size}")
        if self.slide < 1 or self.slide > self.size:
            raise ValueError(
                f"window slide must be in [1, size], got {self.slide}")

    @classmethod
    def tumbling(cls, size: int) -> "WindowSpec":
        return cls(size=size, slide=size)


@dataclass
class EpochWindow:
    """Live-epoch bookkeeping for one windowed standing query."""

    spec: WindowSpec
    # (epoch, triples) in epoch order — raw batches kept for rebuilds
    live: list = field(default_factory=list)

    def add(self, epoch: int, triples: np.ndarray) -> list:
        """Admit one epoch; returns the list of (epoch, triples) entries
        retired by this advance (non-empty only on the first epoch after a
        close — once per ``slide``, the amortized rebuild cadence)."""
        self.live.append((int(epoch), triples))
        last_close = (epoch - 1) // self.spec.slide * self.spec.slide
        cutoff = last_close - (self.spec.size - self.spec.slide)
        retired = [ent for ent in self.live if ent[0] <= cutoff]
        if retired:
            self.live = [ent for ent in self.live if ent[0] > cutoff]
        return retired

    def live_epochs(self) -> list[int]:
        return [e for e, _ in self.live]

    def live_triples(self) -> np.ndarray:
        """All live triples as one [N,3] array (rebuild input)."""
        if not self.live:
            return np.empty((0, 3), dtype=np.int64)
        return np.concatenate([t for _, t in self.live])
