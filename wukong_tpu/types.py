"""ID model and triple model.

Mirrors the reference's type system (core/type.hpp:28-127, core/store/vertex.hpp:34-43,
datagen/generate_data.cpp:50-52):

- ``sid`` (string id): unsigned vertex/predicate/type id. We use int64 host-side and
  int32 on device (LUBM-10240 has ~1.4B triples but < 2^31 vertices).
- ``ssid`` (signed string id): query-side id — variables are NEGATIVE, constants
  POSITIVE (core/type.hpp:31).
- The id space is split: ids < 2^NBITS_IDX (= 2^17) are *index* ids (predicates and
  types); ids >= 2^17 are *normal* vertices (datagen/generate_data.cpp:50, 117-123).
- Reserved index ids: PREDICATE_ID=0 (``__PREDICATE__`` — the predicate index),
  TYPE_ID=1 (``rdf:type`` — the type index) (core/store/vertex.hpp:34-43).
- BLANK_ID marks OPTIONAL-unmatched cells in binding tables (core/type.hpp:33).

Directions (core/type.hpp:127): IN=0, OUT=1. A triple (s, p, o) is reachable both as
(s, p, OUT) -> o and (o, p, IN) -> s; the store indexes both.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

# ---------------------------------------------------------------------------
# Reserved ids and id-space split
# ---------------------------------------------------------------------------

PREDICATE_ID = 0  # "__PREDICATE__" — predicate-index id
TYPE_ID = 1  # rdf:type — type-index id
NBITS_IDX = 17  # ids < 2**NBITS_IDX are index (predicate/type) ids
NORMAL_ID_START = 1 << NBITS_IDX

# Device arrays are int32; BLANK_ID is the max unsigned 32-bit value in the
# reference (core/type.hpp:33). We keep tables as int32 on device, so BLANK_ID
# maps to -1 (all-ones); host-side code treats both views equivalently.
BLANK_ID = (1 << 32) - 1  # uint32 view (reference value)
BLANK_ID_I32 = -1  # int32 device view (same bit pattern)

# dtypes
SID_DTYPE = np.int64  # host-side id arrays (room for 64-bit build)
DEVICE_SID_DTYPE = np.int32  # device-side binding tables / CSR arrays


class Dir(enum.IntEnum):
    """Edge direction (core/type.hpp:127). CORUN is an optimizer hint."""

    IN = 0
    OUT = 1
    CORUN = 2


IN = Dir.IN
OUT = Dir.OUT
CORUN = Dir.CORUN


def reverse_dir(d: int) -> int:
    return Dir.OUT if d == Dir.IN else Dir.IN


# ---------------------------------------------------------------------------
# Attribute value types (utils/variant.hpp:28-50)
# ---------------------------------------------------------------------------


class AttrType(enum.IntEnum):
    SID_t = 0
    INT_t = 1
    FLOAT_t = 2
    DOUBLE_t = 3


# ---------------------------------------------------------------------------
# ssid helpers: variables are negative, constants positive
# ---------------------------------------------------------------------------


def is_var(ssid: int) -> bool:
    """Variables are encoded as negative ids (core/type.hpp:31)."""
    return ssid < 0


def is_const(ssid: int) -> bool:
    return ssid > 0


def is_idx_id(sid: int) -> bool:
    """True for predicate/type (index) ids, False for normal vertex ids."""
    return 0 <= sid < NORMAL_ID_START


def is_tpid(ssid: int) -> bool:
    """'type or predicate id': inside the index space, excluding the reserved
    PREDICATE_ID/TYPE_ID slots (core/store/vertex.hpp:41: id > 1 && id < 2^17)."""
    return 1 < ssid < NORMAL_ID_START


# ---------------------------------------------------------------------------
# Triple model
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Triple:
    """An id-encoded RDF triple (core/type.hpp:42-50)."""

    s: int
    p: int
    o: int


@dataclass(frozen=True)
class AttrTriple:
    """An attribute triple: subject, attr predicate, typed value (core/type.hpp:52-60)."""

    s: int
    a: int
    type: int  # AttrType tag
    v: object  # int | float


def triples_to_array(triples) -> np.ndarray:
    """Pack an iterable of (s, p, o) into an [N,3] int64 array."""
    arr = np.asarray(
        [(t.s, t.p, t.o) if isinstance(t, Triple) else tuple(t) for t in triples],
        dtype=SID_DTYPE,
    )
    if arr.size == 0:
        arr = arr.reshape(0, 3)
    return arr
