from wukong_tpu.utils.errors import WukongError, ErrorCode  # noqa: F401
from wukong_tpu.utils.logger import logstream, set_log_level  # noqa: F401
from wukong_tpu.utils.timer import get_usec  # noqa: F401
