"""Persistent XLA compilation cache, shared by every entry point.

The reference pays no compile cost (its CUDA kernels ship prebuilt); our
compiled chains do — first_us of a cold distributed chain was 4.5-9.7 s in
BENCH_DIST_r04 and evaporated with the process. jax's persistent cache
spans processes: measured on this host (CPU backend, 8-way shard_map chain)
the second cold process compiles in 0.07 s vs 1.49 s fresh (21x). Console,
bench, and the driver dryrun all call `setup_persistent_cache` before their
first trace so cold starts are deployment-plausible (round-4 verdict
Weak #3).

Directory resolution order: explicit argument > the ``xla_cache_dir``
config knob > the ``WUKONG_CACHE_DIR`` env form > ``<repo>/.cache/xla``.
The knob check tolerates the console boot order (setup runs before
load_config, so a not-yet-loaded config just falls through to env /
default). Setup outcomes feed the device observatory's
``wukong_device_compile_cache_total`` counter so the compile ledger's
cold-dispatch amortization claim is checkable from a scrape, not a log.
"""

from __future__ import annotations

import os

# the resolved directory is logged exactly once per process, not per
# entry-point re-call (console + bench + driver all call setup)
_logged_dir: str | None = None


def _note(outcome: str) -> None:
    """Charge the setup outcome on the device observatory's compile-cache
    counter (site ``boot`` — engine/template_compile.py charges the same
    counter under site ``template``); tolerate a broken obs import (this
    runs at process boot)."""
    try:
        from wukong_tpu.obs.device import note_compile_cache

        note_compile_cache(outcome, site="boot")
    except Exception:
        pass


def setup_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache; returns the
    directory, or None when the config knob is unavailable (old jax). Safe
    to call more than once."""
    global _logged_dir
    import jax

    try:
        if cache_dir is None:
            try:
                from wukong_tpu.config import Global

                cache_dir = str(Global.xla_cache_dir) or None
            except Exception:
                cache_dir = None
        if cache_dir is None:
            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            base = (os.environ.get("WUKONG_CACHE_DIR")
                    or os.path.join(repo, ".cache"))
            cache_dir = os.path.join(base, "xla")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        if _logged_dir != cache_dir:
            _logged_dir = cache_dir
            from wukong_tpu.utils.logger import log_info

            log_info(f"persistent XLA compile cache: {cache_dir}")
        _note("available")
        return cache_dir
    except Exception as e:
        from wukong_tpu.utils.logger import log_warn

        log_warn(f"persistent compilation cache unavailable: {e}")
        _note("unavailable")
        return None
