"""Persistent XLA compilation cache, shared by every entry point.

The reference pays no compile cost (its CUDA kernels ship prebuilt); our
compiled chains do — first_us of a cold distributed chain was 4.5-9.7 s in
BENCH_DIST_r04 and evaporated with the process. jax's persistent cache
spans processes: measured on this host (CPU backend, 8-way shard_map chain)
the second cold process compiles in 0.07 s vs 1.49 s fresh (21x). Console,
bench, and the driver dryrun all call `setup_persistent_cache` before their
first trace so cold starts are deployment-plausible (round-4 verdict
Weak #3).
"""

from __future__ import annotations

import os


def setup_persistent_cache(cache_dir: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache; returns the
    directory, or None when the config knob is unavailable (old jax). Safe
    to call more than once."""
    import jax

    try:
        if cache_dir is None:
            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            base = (os.environ.get("WUKONG_CACHE_DIR")
                    or os.path.join(repo, ".cache"))
            cache_dir = os.path.join(base, "xla")
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception as e:
        from wukong_tpu.utils.logger import log_warn

        log_warn(f"persistent compilation cache unavailable: {e}")
        return None
