"""Error codes surfaced to clients on query replies.

Mirrors utils/errors.hpp:28-79 — engine-side failures do not kill workers; they
become a ``status_code`` on the reply, and the frontend renders a message.
"""

from __future__ import annotations

import enum


class ErrorCode(enum.IntEnum):
    SUCCESS = 0
    SYNTAX_ERROR = 1  # parser-level failure
    UNKNOWN_SUB = 2  # unknown subject string
    UNKNOWN_PATTERN = 3  # pattern shape not supported by the engine
    ATTR_DISABLE = 4  # attribute query while vattr support disabled
    NO_REQUIRED_VAR = 5  # projection references an unbound variable
    UNSUPPORT_UNION = 6
    OBJ_ERROR = 7  # malformed index pattern
    VERTEX_INVALID = 8  # known var has no bound column
    UNKNOWN_FILTER = 9
    FIRST_PATTERN_ERROR = 10  # start pattern must begin an empty table
    UNKNOWN_PLAN = 11
    UNSUPPORTED_SHAPE = 12  # engine cannot run this plan shape (fallback-able)
    FILE_NOT_FOUND = 13  # dataset/HDFS source unreachable
    # ---- resilience taxonomy (no reference analogue: the reference's only
    # failure handling is "engine-side failures become a status_code"; these
    # make deadline/budget/infrastructure failures distinguishable so the
    # proxy can degrade instead of treating everything as a query bug) ----
    QUERY_TIMEOUT = 14  # per-query wall-clock deadline expired
    BUDGET_EXCEEDED = 15  # per-query intermediate-row work budget exhausted
    CAPACITY_EXCEEDED = 16  # device capacity ceiling hit (host-fallback-able)
    SHARD_UNAVAILABLE = 17  # shard down / circuit breaker open
    RETRY_EXHAUSTED = 18  # transient-failure retries used up
    CHECKPOINT_CORRUPT = 19  # checkpoint/WAL bundle unreadable or mismatched
    FRAME_TOO_LARGE = 20  # transport frame over transport_max_frame_mb
    TRANSPORT_CORRUPT = 21  # wire frame/message failed CRC or schema checks


_MESSAGES = {
    ErrorCode.SUCCESS: "success",
    ErrorCode.SYNTAX_ERROR: "syntax error",
    ErrorCode.UNKNOWN_SUB: "unknown subject (not in string server)",
    ErrorCode.UNKNOWN_PATTERN: "unsupported triple pattern",
    ErrorCode.ATTR_DISABLE: "attribute support is disabled (enable_vattr)",
    ErrorCode.NO_REQUIRED_VAR: "projection variable is not bound",
    ErrorCode.UNSUPPORT_UNION: "unsupported UNION shape",
    ErrorCode.OBJ_ERROR: "malformed index pattern",
    ErrorCode.VERTEX_INVALID: "known variable has no bound column",
    ErrorCode.UNKNOWN_FILTER: "unsupported FILTER expression",
    ErrorCode.FIRST_PATTERN_ERROR: "start pattern applied to a non-empty table",
    ErrorCode.UNKNOWN_PLAN: "invalid or missing query plan",
    ErrorCode.UNSUPPORTED_SHAPE: "plan shape unsupported by this engine",
    ErrorCode.FILE_NOT_FOUND: "dataset source unreachable",
    ErrorCode.QUERY_TIMEOUT: "query deadline expired",
    ErrorCode.BUDGET_EXCEEDED: "query work budget exhausted",
    ErrorCode.CAPACITY_EXCEEDED: "device capacity exceeded",
    ErrorCode.SHARD_UNAVAILABLE: "shard unavailable (circuit open)",
    ErrorCode.RETRY_EXHAUSTED: "transient-failure retries exhausted",
    ErrorCode.CHECKPOINT_CORRUPT: "checkpoint/WAL bundle corrupt or incompatible",
    ErrorCode.FRAME_TOO_LARGE: "transport frame exceeds transport_max_frame_mb",
    ErrorCode.TRANSPORT_CORRUPT: "transport frame or message corrupt",
}


class WukongError(Exception):
    """Query-scoped failure carrying an ErrorCode (utils/errors.hpp WukongException)."""

    def __init__(self, code: ErrorCode, detail: str = ""):
        self.code = ErrorCode(code)
        self.detail = detail
        msg = _MESSAGES.get(self.code, "unknown error")
        super().__init__(f"[{self.code.name}] {msg}" + (f": {detail}" if detail else ""))


class QueryTimeout(WukongError):
    """Per-query wall-clock deadline expired (resilience layer)."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.QUERY_TIMEOUT, detail)


class BudgetExceeded(WukongError):
    """Per-query intermediate-row work budget exhausted (resilience layer)."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.BUDGET_EXCEEDED, detail)


class CapacityExceeded(WukongError):
    """A device capacity ceiling (table_capacity_max) was hit. The proxy
    treats this as degradable: the CPU engine has no capacity classes, so
    the same query can complete host-side."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.CAPACITY_EXCEEDED, detail)


class ShardUnavailable(WukongError):
    """A shard is down or its circuit breaker is open."""

    def __init__(self, detail: str = "", shard: int | None = None):
        self.shard = shard
        super().__init__(ErrorCode.SHARD_UNAVAILABLE, detail)


class RetryExhausted(WukongError):
    """A transient failure survived every retry attempt."""

    def __init__(self, detail: str = "", last: BaseException | None = None):
        self.last = last
        super().__init__(ErrorCode.RETRY_EXHAUSTED, detail)


class CheckpointCorrupt(WukongError):
    """A persisted bundle (gstore checkpoint, WAL segment, recovery
    manifest) failed validation: truncated archive, checksum mismatch, or
    a newer-major format this build refuses to guess at. Carries the
    offending path so operators know which artifact to discard."""

    def __init__(self, detail: str = "", path: str | None = None):
        self.path = path
        super().__init__(ErrorCode.CHECKPOINT_CORRUPT,
                         f"{detail} ({path})" if path else detail)


class FrameTooLarge(WukongError):
    """A transport frame (sent or received) exceeds the configured
    ``transport_max_frame_mb`` ceiling. Raised on the ENCODE side too:
    the sender must refuse what the receiver would refuse, or the error
    surfaces as an opaque peer timeout instead of a named limit."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.FRAME_TOO_LARGE, detail)


class TransportCorrupt(WukongError):
    """A wire frame or message failed validation: bad magic, CRC mismatch
    on a complete frame, an undeclared op, or a request/reply that does
    not match its MESSAGE_REGISTRY schema. Distinct from a torn trailing
    frame, which is silently dropped (only the unacknowledged message)."""

    def __init__(self, detail: str = ""):
        super().__init__(ErrorCode.TRANSPORT_CORRUPT, detail)


def assert_ec(cond: bool, code: ErrorCode, detail: str = "") -> None:
    if not cond:
        raise WukongError(code, detail)
