"""JAX platform-selection guard for CLI entry points.

The axon sitecustomize registers the TPU PJRT plugin at interpreter start and
pins jax.config.jax_platforms to "axon,cpu", silently overriding the user's
JAX_PLATFORMS env var. When the TPU relay is unreachable, initializing the
axon backend then blocks forever — so a user who explicitly asked for
JAX_PLATFORMS=cpu would still hang. Entry points call respect_platform_env()
before any backend initializes to restore the documented env-var contract.
"""

from __future__ import annotations

import os


def respect_platform_env() -> None:
    env = os.environ.get("JAX_PLATFORMS")
    if not env:
        return
    import jax

    try:
        if jax.config.jax_platforms != env:
            jax.config.update("jax_platforms", env)
    except Exception:
        pass  # unknown platform names surface later with a clear jax error
