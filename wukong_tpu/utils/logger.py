"""Leveled logger (reference: utils/logger2.hpp — 8 levels, runtime-settable).

Console command ``logger <level>`` adjusts the level at runtime across workers.
"""

from __future__ import annotations

import sys
import time

# reference levels (logger2.hpp:112-119)
LOG_EVERYTHING = 0
LOG_DEBUG = 1
LOG_INFO = 2
LOG_EMPH = 3
LOG_WARNING = 4
LOG_ERROR = 5
LOG_FATAL = 6
LOG_NONE = 7

_LEVEL_NAMES = {
    LOG_EVERYTHING: "ALL",
    LOG_DEBUG: "DEBUG",
    LOG_INFO: "INFO",
    LOG_EMPH: "EMPH",
    LOG_WARNING: "WARN",
    LOG_ERROR: "ERROR",
    LOG_FATAL: "FATAL",
}

_COLORS = {
    LOG_DEBUG: "\033[36m",
    LOG_INFO: "",
    LOG_EMPH: "\033[1;32m",
    LOG_WARNING: "\033[1;33m",
    LOG_ERROR: "\033[1;31m",
    LOG_FATAL: "\033[1;41m",
}
_RESET = "\033[0m"

_current_level = LOG_INFO
_t0 = time.time()


def set_log_level(level: int) -> None:
    global _current_level
    _current_level = int(level)


def get_log_level() -> int:
    return _current_level


class _Stream:
    def __init__(self, level: int):
        self.level = level

    def __lshift__(self, msg):  # logstream(LOG_INFO) << "msg" style
        self.write(str(msg))
        return self

    def write(self, msg: str) -> None:
        if self.level < _current_level:
            return
        name = _LEVEL_NAMES.get(self.level, "?")
        color = _COLORS.get(self.level, "") if sys.stderr.isatty() else ""
        reset = _RESET if color else ""
        ts = time.time() - _t0
        sys.stderr.write(f"{color}[{ts:9.3f}s {name:5s}]{reset} {msg}\n")


def logstream(level: int) -> _Stream:
    return _Stream(level)


def log_debug(msg: str) -> None:
    _Stream(LOG_DEBUG).write(msg)


def log_info(msg: str) -> None:
    _Stream(LOG_INFO).write(msg)


def log_emph(msg: str) -> None:
    _Stream(LOG_EMPH).write(msg)


def log_warn(msg: str) -> None:
    _Stream(LOG_WARNING).write(msg)


def log_error(msg: str) -> None:
    _Stream(LOG_ERROR).write(msg)
