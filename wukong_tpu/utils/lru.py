"""Shared bounded-LRU cache helper.

One implementation for every memoization site that previously grew without
bound or wholesale-cleared at a size threshold (the TPU engine's
``_est_cache`` used to ``clear()`` everything at 4096 entries, so a hot
mixed workload periodically lost every estimate). Eviction is
least-recently-*used*: ``get`` refreshes recency, ``put`` evicts the
coldest entry once ``maxsize`` is exceeded.

Thread-safe: all operations hold one lock. The payloads cached here
(pattern-tuple row estimates, parsed queries, plan recipes) are small and
the operations are dict moves, so the lock is never contended for long.
"""

from __future__ import annotations

from collections import OrderedDict

from wukong_tpu.analysis.lockdep import declare_leaf, make_lock

_MISS = object()

# LRU locks guard pure dict moves — innermost by construction
declare_leaf("lru")


class LRUCache:
    """Bounded mapping with least-recently-used eviction."""

    def __init__(self, maxsize: int = 1024):
        self.maxsize = max(int(maxsize), 1)
        self._d: OrderedDict = OrderedDict()  # guarded by: _lock
        self._lock = make_lock("lru")
        self.hits = 0  # guarded by: _lock
        self.misses = 0  # guarded by: _lock
        self.evictions = 0  # guarded by: _lock

    def get(self, key, default=None):
        with self._lock:
            v = self._d.get(key, _MISS)
            if v is _MISS:
                self.misses += 1
                return default
            self._d.move_to_end(key)
            self.hits += 1
            return v

    def put(self, key, value) -> None:
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.maxsize:
                self._d.popitem(last=False)
                self.evictions += 1

    def pop(self, key, default=None):
        with self._lock:
            return self._d.pop(key, default)

    def clear(self) -> None:
        with self._lock:
            self._d.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._d)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._d

    def stats(self) -> dict:
        with self._lock:
            return {"size": len(self._d), "maxsize": self.maxsize,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}
